"""paddle.fft equivalent over jnp.fft (XLA lowers to TPU-friendly FFTs).

ref: python/paddle/fft.py — same surface: 1d/2d/nd complex, real, and
hermitian transforms + helpers. Autograd rides apply_op like every op.
"""
from __future__ import annotations

import jax.numpy as jnp

from .core.autograd import apply_op
from .core.tensor import Tensor

__all__ = [
    "fft", "ifft", "fft2", "ifft2", "fftn", "ifftn",
    "rfft", "irfft", "rfft2", "irfft2", "rfftn", "irfftn",
    "hfft", "ihfft", "fftfreq", "rfftfreq", "fftshift", "ifftshift",
]


def _norm(norm):
    if norm in (None, "backward"):
        return "backward"
    if norm in ("forward", "ortho"):
        return norm
    raise ValueError(
        f"norm must be 'backward', 'forward', or 'ortho', got {norm!r}")


def _wrap1(jfn):
    def op(x, n=None, axis=-1, norm="backward", name=None):
        return apply_op(lambda a: jfn(a, n=n, axis=axis, norm=_norm(norm)),
                        x, op_name=jfn.__name__)
    return op


def _wrap2(jfn):
    def op(x, s=None, axes=(-2, -1), norm="backward", name=None):
        return apply_op(lambda a: jfn(a, s=s, axes=axes, norm=_norm(norm)),
                        x, op_name=jfn.__name__)
    return op


def _wrapn(jfn):
    def op(x, s=None, axes=None, norm="backward", name=None):
        return apply_op(lambda a: jfn(a, s=s, axes=axes, norm=_norm(norm)),
                        x, op_name=jfn.__name__)
    return op


fft = _wrap1(jnp.fft.fft)
ifft = _wrap1(jnp.fft.ifft)
rfft = _wrap1(jnp.fft.rfft)
irfft = _wrap1(jnp.fft.irfft)
hfft = _wrap1(jnp.fft.hfft)
ihfft = _wrap1(jnp.fft.ihfft)
fft2 = _wrap2(jnp.fft.fft2)
ifft2 = _wrap2(jnp.fft.ifft2)
rfft2 = _wrap2(jnp.fft.rfft2)
irfft2 = _wrap2(jnp.fft.irfft2)
fftn = _wrapn(jnp.fft.fftn)
ifftn = _wrapn(jnp.fft.ifftn)
rfftn = _wrapn(jnp.fft.rfftn)
irfftn = _wrapn(jnp.fft.irfftn)


def fftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor(jnp.fft.fftfreq(n, d).astype(dtype or jnp.float32))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor(jnp.fft.rfftfreq(n, d).astype(dtype or jnp.float32))


def fftshift(x, axes=None, name=None):
    return apply_op(lambda a: jnp.fft.fftshift(a, axes=axes), x,
                    op_name="fftshift")


def ifftshift(x, axes=None, name=None):
    return apply_op(lambda a: jnp.fft.ifftshift(a, axes=axes), x,
                    op_name="ifftshift")
