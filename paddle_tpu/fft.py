"""paddle.fft equivalent over jnp.fft (XLA lowers to TPU-friendly FFTs).

ref: python/paddle/fft.py — same surface: 1d/2d/nd complex, real, and
hermitian transforms + helpers. Autograd rides apply_op like every op.
"""
from __future__ import annotations

import jax.numpy as jnp

from .core.autograd import apply_op
from .core.tensor import Tensor

__all__ = [
    "fft", "ifft", "fft2", "ifft2", "fftn", "ifftn",
    "rfft", "irfft", "rfft2", "irfft2", "rfftn", "irfftn",
    "hfft", "ihfft", "hfft2", "ihfft2", "hfftn", "ihfftn",
    "fftfreq", "rfftfreq", "fftshift", "ifftshift",
]


def _norm(norm):
    if norm in (None, "backward"):
        return "backward"
    if norm in ("forward", "ortho"):
        return norm
    raise ValueError(
        f"norm must be 'backward', 'forward', or 'ortho', got {norm!r}")


def _wrap1(jfn):
    def op(x, n=None, axis=-1, norm="backward", name=None):
        return apply_op(lambda a: jfn(a, n=n, axis=axis, norm=_norm(norm)),
                        x, op_name=jfn.__name__)
    return op


def _wrap2(jfn):
    def op(x, s=None, axes=(-2, -1), norm="backward", name=None):
        return apply_op(lambda a: jfn(a, s=s, axes=axes, norm=_norm(norm)),
                        x, op_name=jfn.__name__)
    return op


def _wrapn(jfn):
    def op(x, s=None, axes=None, norm="backward", name=None):
        return apply_op(lambda a: jfn(a, s=s, axes=axes, norm=_norm(norm)),
                        x, op_name=jfn.__name__)
    return op


fft = _wrap1(jnp.fft.fft)
ifft = _wrap1(jnp.fft.ifft)
rfft = _wrap1(jnp.fft.rfft)
irfft = _wrap1(jnp.fft.irfft)
hfft = _wrap1(jnp.fft.hfft)
ihfft = _wrap1(jnp.fft.ihfft)
fft2 = _wrap2(jnp.fft.fft2)
ifft2 = _wrap2(jnp.fft.ifft2)
rfft2 = _wrap2(jnp.fft.rfft2)
irfft2 = _wrap2(jnp.fft.irfft2)
fftn = _wrapn(jnp.fft.fftn)
ifftn = _wrapn(jnp.fft.ifftn)
rfftn = _wrapn(jnp.fft.rfftn)
irfftn = _wrapn(jnp.fft.irfftn)


def _hermitian_nd(h_1d, cfftn, default_axes, op_name, h_first):
    """N-d hermitian transforms composed from the separable pieces:
    complex fft over the leading axes + the 1-D hermitian transform on
    the last axis (ref: paddle/fft.py hfftn/ihfftn, which lower to
    fft_c2r/r2c the same way). Order depends on direction: ihfft (r2c)
    must see the REAL input, so it runs first; hfft (c2r) produces the
    real output, so it runs last. Per-call norms multiply into the
    correct total factor because the transform is separable."""

    def op(x, s=None, axes=None, norm="backward", name=None):
        def f(a):
            ax = list(axes) if axes is not None else (
                list(default_axes) if default_axes is not None
                else list(range(a.ndim)))
            ss = list(s) if s is not None else None
            if ss is not None and len(ss) != len(ax):
                raise ValueError(
                    f"{op_name}: len(s)={len(ss)} must match "
                    f"len(axes)={len(ax)}")
            head, last = ax[:-1], ax[-1]
            n_last = ss[-1] if ss is not None else None
            s_head = ss[:-1] if ss is not None else None
            if h_first:
                a = h_1d(a, n=n_last, axis=last, norm=_norm(norm))
                if head:
                    a = cfftn(a, s=s_head, axes=head, norm=_norm(norm))
                return a
            if head:
                a = cfftn(a, s=s_head, axes=head, norm=_norm(norm))
            return h_1d(a, n=n_last, axis=last, norm=_norm(norm))

        return apply_op(f, x, op_name=op_name)

    return op


hfft2 = _hermitian_nd(jnp.fft.hfft, jnp.fft.fftn, (-2, -1), "hfft2",
                      h_first=False)
ihfft2 = _hermitian_nd(jnp.fft.ihfft, jnp.fft.ifftn, (-2, -1), "ihfft2",
                       h_first=True)
hfftn = _hermitian_nd(jnp.fft.hfft, jnp.fft.fftn, None, "hfftn",
                      h_first=False)
ihfftn = _hermitian_nd(jnp.fft.ihfft, jnp.fft.ifftn, None, "ihfftn",
                       h_first=True)


def fftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor(jnp.fft.fftfreq(n, d).astype(dtype or jnp.float32))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor(jnp.fft.rfftfreq(n, d).astype(dtype or jnp.float32))


def fftshift(x, axes=None, name=None):
    return apply_op(lambda a: jnp.fft.fftshift(a, axes=axes), x,
                    op_name="fftshift")


def ifftshift(x, axes=None, name=None):
    return apply_op(lambda a: jnp.fft.ifftshift(a, axes=axes), x,
                    op_name="ifftshift")
