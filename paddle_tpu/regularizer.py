"""paddle.regularizer equivalent: L1Decay / L2Decay.

ref: python/paddle/regularizer.py — attached per-param via ParamAttr or
globally via the optimizer's weight_decay argument; applied to gradients
before the update (the optimizer folds coefficient * penalty' into grad).
"""
from __future__ import annotations

import jax.numpy as jnp

__all__ = ["L1Decay", "L2Decay", "WeightDecayRegularizer"]


class WeightDecayRegularizer:
    def __call__(self, param, grad):
        raise NotImplementedError


class L1Decay(WeightDecayRegularizer):
    """grad += coeff * sign(param) (ref: regularizer.py L1Decay)."""

    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)

    @property
    def coeff(self):
        return self._coeff

    def __call__(self, param, grad):
        return grad + self._coeff * jnp.sign(param)


class L2Decay(WeightDecayRegularizer):
    """grad += coeff * param (ref: regularizer.py L2Decay)."""

    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)

    @property
    def coeff(self):
        return self._coeff

    def __call__(self, param, grad):
        return grad + self._coeff * param
