"""Profiler: host-span tracer + device (XLA) profiler, two-plane design.

ref: python/paddle/profiler/profiler.py:358 (Profiler context manager with
scheduler states), paddle/fluid/platform/profiler/host_tracer.h:26
(RecordEvent spans), chrometracing_logger.cc (Chrome trace export). The
host plane is the C++ tracer in paddle_tpu._native; the device plane is
jax.profiler (XLA/xplane), which TensorBoard renders — the same division
the reference draws between HostTracer and CudaTracer/CUPTI.
"""
from __future__ import annotations

import json
import os
from typing import Optional

from ._native import lib as _lib

__all__ = ["Profiler", "RecordEvent", "ProfilerTarget",
           "export_chrome_tracing"]


class ProfilerTarget:
    CPU = "cpu"
    TPU = "tpu"
    GPUTrace = "gpu"  # reference-compat alias


class RecordEvent:
    """Host-span annotation (ref: paddle.profiler.RecordEvent; native analog
    platform/profiler/event_tracing.h RecordEvent). Usable as context
    manager or begin()/end() pair.

    Reentrant: a second ``begin()`` before ``end()`` nests (each ``end``
    closes the most recent open ``begin``, LIFO) instead of silently
    dropping the first span's start."""

    def __init__(self, name: str):
        self.name = name
        self._starts: list = []

    def begin(self):
        if _lib is not None and _lib.tracer_enabled():
            self._starts.append(_lib.tracer_now())

    def end(self):
        if _lib is not None and self._starts:
            _lib.tracer_record(self.name, self._starts.pop(),
                               _lib.tracer_now())

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


class Profiler:
    """ref: paddle.profiler.Profiler — start/stop/step, export.

    targets including TPU adds the XLA device trace (jax.profiler), viewable
    in TensorBoard; the host plane always records via the native tracer.
    """

    def __init__(self, targets=None, on_trace_ready=None, timer_only=False,
                 profile_memory=False, scheduler=None):
        self.targets = targets or [ProfilerTarget.CPU]
        self.on_trace_ready = on_trace_ready
        self.timer_only = bool(timer_only)
        self._device_dir: Optional[str] = None
        self._running = False
        self._step_count = 0
        self._step_t0: Optional[float] = None

    def start(self):
        if _lib is not None:
            _lib.tracer_start()
            self._step_t0 = _lib.tracer_now()
        # timer_only (ref: Profiler(timer_only=True) — step timing
        # without event collection) keeps the cheap host plane but skips
        # the device (XLA) trace entirely
        if not self.timer_only and (
                ProfilerTarget.TPU in self.targets
                or ProfilerTarget.GPUTrace in self.targets):
            import jax
            self._device_dir = os.environ.get(
                "PADDLE_TPU_PROFILE_DIR", "/tmp/paddle_tpu_profile")
            try:
                jax.profiler.start_trace(self._device_dir)
            except Exception:
                self._device_dir = None
        self._running = True
        return self

    def stop(self):
        if not self._running:
            return
        if _lib is not None:
            _lib.tracer_stop()
        if self._device_dir is not None:
            import jax
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
        self._running = False
        if self.on_trace_ready is not None:
            self.on_trace_ready(self)

    def step(self):
        """Mark a step boundary: the window since start()/the previous
        step() lands in the host trace as a ``ProfileStep#N`` span (ref:
        profiler.py RecordEvent(\"ProfileStep#{id}\") around each
        scheduler step) — summary() and the chrome export then break
        time down per step instead of one undifferentiated run."""
        self._step_count += 1
        if _lib is not None and _lib.tracer_enabled() \
                and self._step_t0 is not None:
            now = _lib.tracer_now()
            _lib.tracer_record(f"ProfileStep#{self._step_count}",
                               self._step_t0, now)
            self._step_t0 = now

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- export -------------------------------------------------------------
    def export(self, path: str, format: str = "json"):
        export_chrome_tracing(path)

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms"):
        """Aggregated host-span statistics table (ref:
        profiler/profiler_statistic.py op summary: calls, total, avg,
        max, min, ratio)."""
        if _lib is None:
            return "native tracer unavailable"
        data = json.loads(_lib.tracer_dump())
        agg = {}
        grand = 0.0
        for e in data.get("traceEvents", []):
            if e.get("ph") == "C":
                continue  # timeline counter events are not spans
            dur = float(e.get("dur", 0.0))
            rec = agg.setdefault(e["name"], [0, 0.0, 0.0, float("inf")])
            rec[0] += 1
            rec[1] += dur
            rec[2] = max(rec[2], dur)
            rec[3] = min(rec[3], dur)
            grand += dur
        if not agg:
            return ("no events recorded (host tracer buffer is empty — "
                    "was the profiler started, and did any RecordEvent/"
                    "step() run inside it?)")
        units = {"ms": 1e3, "us": 1.0, "s": 1e6}
        if time_unit not in units:
            raise ValueError(
                f"time_unit must be one of {sorted(units)}, "
                f"got {time_unit!r}")
        unit = units[time_unit]
        u = time_unit
        lines = [f"{'name':<36} {'calls':>7} {f'total_{u}':>11} "
                 f"{f'avg_{u}':>10} {f'max_{u}':>10} {f'min_{u}':>10} "
                 f"{'ratio':>7}"]
        for name, (calls, total, mx, mn) in sorted(
                agg.items(), key=lambda kv: -kv[1][1]):
            lines.append(
                f"{name:<36} {calls:>7} {total / unit:>11.3f} "
                f"{total / calls / unit:>10.3f} {mx / unit:>10.3f} "
                f"{mn / unit:>10.3f} "
                f"{(total / grand if grand else 0.0):>6.1%}")
        return "\n".join(lines)


def export_chrome_tracing(path: str, worker_name=None):
    """Write the host plane as chrome://tracing JSON
    (ref: chrometracing_logger.cc), merged with the step-timeline
    plane — every live ``observability.timeline.StepTimer``'s per-step
    phase counter events (``"ph": "C"``) — and the flight recorder's
    event trail (``observability.flight``, instant events ``"ph": "i"``)
    so ONE file carries spans, metric time series AND the last-N
    black-box events (chrome://tracing / Perfetto render counters as
    stacked area tracks and instants as marks)."""
    if _lib is None:
        raise RuntimeError("native tracer unavailable")
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    dump = _lib.tracer_dump()
    extra = []
    try:
        from .observability import timeline as _timeline
        extra.extend(_timeline.chrome_events())
    except Exception:
        pass
    try:
        from .observability import flight as _flight
        extra.extend(_flight.chrome_events())
    except Exception:
        pass
    if extra:
        data = json.loads(dump)
        data.setdefault("traceEvents", []).extend(extra)
        dump = json.dumps(data)
    with open(path, "w") as f:
        f.write(dump)
    return path
