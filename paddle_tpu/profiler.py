"""Profiler: host-span tracer + device (XLA) profiler, two-plane design.

ref: python/paddle/profiler/profiler.py:358 (Profiler context manager with
scheduler states), paddle/fluid/platform/profiler/host_tracer.h:26
(RecordEvent spans), chrometracing_logger.cc (Chrome trace export). The
host plane is the C++ tracer in paddle_tpu._native; the device plane is
jax.profiler (XLA/xplane), which TensorBoard renders — the same division
the reference draws between HostTracer and CudaTracer/CUPTI.
"""
from __future__ import annotations

import json
import os
from typing import Optional

from ._native import lib as _lib

__all__ = ["Profiler", "RecordEvent", "ProfilerTarget",
           "export_chrome_tracing"]


class ProfilerTarget:
    CPU = "cpu"
    TPU = "tpu"
    GPUTrace = "gpu"  # reference-compat alias


class RecordEvent:
    """Host-span annotation (ref: paddle.profiler.RecordEvent; native analog
    platform/profiler/event_tracing.h RecordEvent). Usable as context
    manager or begin()/end() pair."""

    def __init__(self, name: str):
        self.name = name
        self._t0: Optional[float] = None

    def begin(self):
        if _lib is not None and _lib.tracer_enabled():
            self._t0 = _lib.tracer_now()

    def end(self):
        if _lib is not None and self._t0 is not None:
            _lib.tracer_record(self.name, self._t0, _lib.tracer_now())
            self._t0 = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


class Profiler:
    """ref: paddle.profiler.Profiler — start/stop/step, export.

    targets including TPU adds the XLA device trace (jax.profiler), viewable
    in TensorBoard; the host plane always records via the native tracer.
    """

    def __init__(self, targets=None, on_trace_ready=None, timer_only=False,
                 profile_memory=False, scheduler=None):
        self.targets = targets or [ProfilerTarget.CPU]
        self.on_trace_ready = on_trace_ready
        self._device_dir: Optional[str] = None
        self._running = False
        self._step_count = 0

    def start(self):
        if _lib is not None:
            _lib.tracer_start()
        if ProfilerTarget.TPU in self.targets or \
                ProfilerTarget.GPUTrace in self.targets:
            import jax
            self._device_dir = os.environ.get(
                "PADDLE_TPU_PROFILE_DIR", "/tmp/paddle_tpu_profile")
            try:
                jax.profiler.start_trace(self._device_dir)
            except Exception:
                self._device_dir = None
        self._running = True
        return self

    def stop(self):
        if not self._running:
            return
        if _lib is not None:
            _lib.tracer_stop()
        if self._device_dir is not None:
            import jax
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
        self._running = False
        if self.on_trace_ready is not None:
            self.on_trace_ready(self)

    def step(self):
        self._step_count += 1

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- export -------------------------------------------------------------
    def export(self, path: str, format: str = "json"):
        export_chrome_tracing(path)

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms"):
        """Aggregated host-span statistics table (ref:
        profiler/profiler_statistic.py op summary: calls, total, avg,
        max, min, ratio)."""
        if _lib is None:
            return "native tracer unavailable"
        data = json.loads(_lib.tracer_dump())
        agg = {}
        grand = 0.0
        for e in data.get("traceEvents", []):
            dur = float(e.get("dur", 0.0))
            rec = agg.setdefault(e["name"], [0, 0.0, 0.0, float("inf")])
            rec[0] += 1
            rec[1] += dur
            rec[2] = max(rec[2], dur)
            rec[3] = min(rec[3], dur)
            grand += dur
        units = {"ms": 1e3, "us": 1.0, "s": 1e6}
        if time_unit not in units:
            raise ValueError(
                f"time_unit must be one of {sorted(units)}, "
                f"got {time_unit!r}")
        unit = units[time_unit]
        u = time_unit
        lines = [f"{'name':<36} {'calls':>7} {f'total_{u}':>11} "
                 f"{f'avg_{u}':>10} {f'max_{u}':>10} {f'min_{u}':>10} "
                 f"{'ratio':>7}"]
        for name, (calls, total, mx, mn) in sorted(
                agg.items(), key=lambda kv: -kv[1][1]):
            lines.append(
                f"{name:<36} {calls:>7} {total / unit:>11.3f} "
                f"{total / calls / unit:>10.3f} {mx / unit:>10.3f} "
                f"{mn / unit:>10.3f} "
                f"{(total / grand if grand else 0.0):>6.1%}")
        return "\n".join(lines)


def export_chrome_tracing(path: str, worker_name=None):
    """Write the host plane as chrome://tracing JSON
    (ref: chrometracing_logger.cc)."""
    if _lib is None:
        raise RuntimeError("native tracer unavailable")
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        f.write(_lib.tracer_dump())
    return path
