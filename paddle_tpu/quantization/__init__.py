"""paddle.quantization equivalent: QAT/PTQ with fake-quant layers.

ref: python/paddle/quantization/ (QuantConfig config.py, QAT qat.py, PTQ
ptq.py, observers in quanter/), legacy fake_quantize ops
(fluid/operators/fake_quantize_op). TPU note: fake-quant is pure
elementwise math so it fuses into surrounding XLA computations;
``convert_to_int8`` lowers calibrated layers to Int8Linear, which
executes REAL s8 x s8 -> s32 matmuls (a native MXU fast path) with a
per-channel scale epilogue — the analog of the reference's int8
inference kernels behind its analysis passes.
"""
from .quantize import (  # noqa: F401
    AbsmaxObserver, BaseObserver, BaseQuanter, FakeQuantAbsMax,
    Int8Linear, MovingAverageAbsmaxObserver, PTQ, QAT, QuantConfig,
    QuantedLinear, convert_to_int8, fake_quantize_abs_max, quant_absmax,
    quanter,
)

__all__ = ["QuantConfig", "BaseQuanter", "BaseObserver", "quanter",
           "QAT", "PTQ", "Int8Linear", "convert_to_int8"]
