"""paddle.quantization equivalent: QAT/PTQ with fake-quant layers.

ref: python/paddle/quantization/ (QuantConfig config.py, QAT qat.py, PTQ
ptq.py, observers in quanter/), legacy fake_quantize ops
(fluid/operators/fake_quantize_op). TPU note: fake-quant is pure
elementwise math so it fuses into surrounding XLA computations; int8
deployment lowering is a compiler concern (XLA int8 matmul) — this module
provides the calibration/training semantics.
"""
from .quantize import (  # noqa: F401
    AbsmaxObserver, BaseObserver, BaseQuanter, FakeQuantAbsMax,
    MovingAverageAbsmaxObserver, PTQ, QAT, QuantConfig, QuantedLinear,
    fake_quantize_abs_max, quant_absmax, quanter,
)

__all__ = ["QuantConfig", "BaseQuanter", "BaseObserver", "quanter",
           "QAT", "PTQ"]
