"""Fake-quant math, observers, QAT/PTQ drivers.

ref: python/paddle/quantization/{config,qat,ptq}.py + factory quanters
(quanter/abs_max.py FakeQuanterWithAbsMax...), op semantics
fake_quantize_abs_max (fluid/operators/fake_quantize_op.cc): quantize to
int range with straight-through-estimator gradients, scale from the abs
max (per tensor or EMA during training).
"""
from __future__ import annotations

import copy
from typing import Optional

import jax
import jax.numpy as jnp

from ..core.autograd import apply_op
from ..core.tensor import Tensor
from ..nn.layer import Layer
from ..nn.layers_common import Linear

__all__ = [
    "quant_absmax", "fake_quantize_abs_max", "FakeQuantAbsMax",
    "AbsmaxObserver", "MovingAverageAbsmaxObserver", "QuantConfig", "QAT",
    "PTQ", "QuantedLinear", "Int8Linear", "convert_to_int8",
]


def quant_absmax(x, bits: int = 8, scale=None):
    """Quantize-dequantize with STE backward (ref: fake_quantize_op
    FakeQuantizeAbsMax). scale=None computes the dynamic per-tensor abs
    max; a float scale uses the static calibrated step (PTQ convert)."""
    qmax = float(2 ** (bits - 1) - 1)

    @jax.custom_vjp
    def fq(a):
        s = jnp.maximum(jnp.abs(a).max(), 1e-8) if scale is None \
            else jnp.asarray(scale * qmax, a.dtype)
        q = jnp.clip(jnp.round(a / s * qmax), -qmax, qmax)
        return q * s / qmax

    def fwd(a):
        return fq(a), None

    def bwd(_, g):
        return (g,)  # straight-through

    fq.defvjp(fwd, bwd)
    return fq(x)


def fake_quantize_abs_max(x, bits: int = 8, scale=None):
    return apply_op(lambda a: quant_absmax(a, bits, scale), x,
                    op_name="fake_quantize_abs_max")


class AbsmaxObserver(Layer):
    """PTQ calibration observer (ref: quantization/observers/abs_max.py)."""

    def __init__(self, quant_bits: int = 8):
        super().__init__()
        self.quant_bits = quant_bits
        self._max = 0.0

    def forward(self, x):
        val = float(jnp.abs(x._data).max())
        self._max = max(self._max, val)
        return x

    def scale(self) -> float:
        return max(self._max, 1e-8) / (2 ** (self.quant_bits - 1) - 1)


class MovingAverageAbsmaxObserver(AbsmaxObserver):
    """ref: quanter/weighted_round.py moving-average absmax (QAT act
    ranges)."""

    def __init__(self, quant_bits: int = 8, moving_rate: float = 0.9):
        super().__init__(quant_bits)
        self.moving_rate = moving_rate

    def forward(self, x):
        val = float(jnp.abs(x._data).max())
        self._max = (self.moving_rate * self._max +
                     (1 - self.moving_rate) * val)
        return x


class FakeQuantAbsMax(Layer):
    """QAT quanter layer (ref: quanter/abs_max.py FakeQuanterWithAbsMax).
    static_scale pins the quantization step (PTQ-converted layers)."""

    def __init__(self, quant_bits: int = 8, static_scale=None):
        super().__init__()
        self.quant_bits = quant_bits
        self.static_scale = static_scale

    def forward(self, x):
        return fake_quantize_abs_max(x, self.quant_bits, self.static_scale)


class QuantedLinear(Layer):
    """Linear with fake-quantized weight + activation
    (ref: quantization/quantized_linear.py / imperative qat layers).
    act_scale, when given, freezes the activation step to the PTQ
    calibration (otherwise dynamic per-batch absmax, the QAT behavior)."""

    def __init__(self, inner: Linear, weight_bits=8, act_bits=8,
                 act_scale=None):
        super().__init__()
        self.inner = inner
        self.weight_quanter = FakeQuantAbsMax(weight_bits)
        self.act_quanter = FakeQuantAbsMax(act_bits, act_scale)

    def forward(self, x):
        from ..nn import functional as F
        xq = self.act_quanter(x)
        wq = self.weight_quanter(self.inner.weight)
        return F.linear(xq, wq, self.inner.bias)


class QuantConfig:
    """ref: quantization/config.py QuantConfig — which layer types get
    quantized, with what bit widths (per-type overrides via
    add_layer_config)."""

    def __init__(self, activation=None, weight=None):
        self.act_bits = getattr(activation, "quant_bits", 8) \
            if activation is not None else 8
        self.weight_bits = getattr(weight, "quant_bits", 8) \
            if weight is not None else 8
        # {layer_type: (weight_bits, act_bits)}
        self._types = {Linear: (self.weight_bits, self.act_bits)}

    def add_layer_config(self, layer_types, activation=None, weight=None):
        ab = getattr(activation, "quant_bits", self.act_bits) \
            if activation is not None else self.act_bits
        wb = getattr(weight, "quant_bits", self.weight_bits) \
            if weight is not None else self.weight_bits
        for t in (layer_types if isinstance(layer_types, (list, tuple))
                  else [layer_types]):
            self._types[t] = (wb, ab)

    def bits_for(self, layer):
        return self._types.get(type(layer))

    def matches(self, layer) -> bool:
        return type(layer) in self._types


def _swap_layers(model: Layer, predicate, make):
    for name, sub in list(model._sub_layers.items()):
        if sub is None:
            continue
        if predicate(sub):
            model._sub_layers[name] = make(sub)
        else:
            _swap_layers(sub, predicate, make)
    return model


class QAT:
    """Quantization-aware training driver (ref: qat.py QAT.quantize)."""

    def __init__(self, config: QuantConfig):
        self.config = config

    def quantize(self, model: Layer, inplace: bool = False) -> Layer:
        m = model if inplace else copy.deepcopy(model)

        def make(l):
            wb, ab = self.config.bits_for(l)
            return QuantedLinear(l, wb, ab)

        return _swap_layers(m, self.config.matches, make)


class PTQ:
    """Post-training quantization driver (ref: ptq.py PTQ.quantize →
    calibration forward passes → convert, which FREEZES the observed
    activation scales into the converted layers)."""

    def __init__(self, config: Optional[QuantConfig] = None):
        self.config = config or QuantConfig()
        self._observers = []

    def quantize(self, model: Layer, inplace: bool = False) -> Layer:
        """Instrument with observers; run calibration batches, then call
        convert()."""
        m = model if inplace else copy.deepcopy(model)

        def make(l):
            _, ab = self.config.bits_for(l)
            obs = AbsmaxObserver(ab)
            self._observers.append(obs)

            class _Observed(Layer):
                def __init__(self):
                    super().__init__()
                    self.inner = l
                    self.obs = obs

                def forward(self, x):
                    return self.inner(self.obs(x))

            return _Observed()

        return _swap_layers(m, self.config.matches, make)

    def convert(self, model: Layer, inplace: bool = False) -> Layer:
        """Replace observed layers with statically-quantized ones using
        each observer's calibrated scale."""
        m = model if inplace else copy.deepcopy(model)

        def pred(l):
            return type(l).__name__ == "_Observed"

        def make(l):
            wb, ab = self.config.bits_for(l.inner)
            return QuantedLinear(l.inner, wb, ab,
                                 act_scale=l.obs.scale())

        return _swap_layers(m, pred, make)


class BaseQuanter(Layer):
    """Abstract base for quanters (ref: quantization/base_quanter.py):
    subclasses implement forward (the fake-quant transform) plus the
    scales/zero-point/bit-length accessors the exporters read."""

    def forward(self, x):  # pragma: no cover - abstract
        raise NotImplementedError

    def scales(self):
        raise NotImplementedError

    def zero_points(self):
        raise NotImplementedError

    def quant_axis(self):
        return -1

    def bit_length(self):
        return 8


class BaseObserver(BaseQuanter):
    """Abstract base for observers (ref: quantization/base_observer.py):
    quanters that first watch tensors to calibrate their scales."""

    def cal_thresholds(self):
        raise NotImplementedError


def quanter(class_name: str):
    """Class decorator declaring a quanter factory under ``class_name``
    (ref: quantization/factory.py quanter): the factory captures ctor
    args and instantiates the quanter per-layer when the QuantConfig is
    applied."""

    def decorator(cls):
        class _Factory:
            def __init__(self, *args, **kwargs):
                self._args = args
                self._kwargs = kwargs

            def _instance(self, layer=None):
                return cls(*self._args, **self._kwargs)

        _Factory.__name__ = class_name
        _Factory._quanter_cls = cls
        import sys
        setattr(sys.modules[cls.__module__], class_name, _Factory)
        return cls

    return decorator


def _int8_linear_impl(a, w, ws, *b, act_step):
    orig_dtype = a.dtype
    qa = jnp.clip(jnp.round(a.astype(jnp.float32) / act_step),
                  -127, 127).astype(jnp.int8)
    acc = jax.lax.dot_general(
        qa, w, (((qa.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    y = acc.astype(jnp.float32) * (ws * act_step)
    if b:
        y = y + b[0].astype(jnp.float32)
    return y.astype(orig_dtype)


class Int8Linear(Layer):
    """Linear executing a REAL int8 matmul (ref: the int8 inference
    kernels the reference's analysis passes lower QAT/PTQ programs onto,
    fluid/inference quant passes + phi int8 kernels; on TPU int8 is a
    native MXU fast path at 2x bf16 throughput).

    Weights are stored as int8 with a per-output-channel scale;
    activations quantize on the fly with the frozen calibration step.
    The dot runs s8 x s8 -> s32 (preferred_element_type) and the
    epilogue applies (act_step * w_step) and the f32 bias.
    """

    def __init__(self, w_int8, w_step, act_step, bias=None):
        super().__init__()
        self.w_int8 = w_int8          # [in, out] jnp.int8
        self.w_step = w_step          # [out] f32 per-channel step
        self.act_step = float(act_step)
        self.bias = bias              # Tensor or None

    def forward(self, x):
        # module-level impl + weights as args: a per-call closure would
        # be refused by apply_op's fast-dispatch cache (fresh fn
        # identity every call) and each eager forward would pay ~6
        # uncompiled dispatches instead of one cached jitted program
        args = [x, self.w_int8, self.w_step]
        if self.bias is not None:
            args.append(self.bias)
        return apply_op(_int8_linear_impl, *args,
                        op_name="int8_linear", act_step=self.act_step)


def convert_to_int8(model: Layer, inplace: bool = False) -> Layer:
    """Lower calibrated QuantedLinear layers (PTQ.convert output, or QAT
    models whose act quanters carry a static scale) to Int8Linear —
    fake-quant simulation becomes actual int8 execution. Layers without
    a frozen activation scale are left untouched (dynamic ranges need
    the fake-quant path).
    """
    m = model if inplace else copy.deepcopy(model)

    def pred(l):
        return (isinstance(l, QuantedLinear)
                and l.act_quanter.static_scale is not None
                and l.weight_quanter.quant_bits == 8
                and l.act_quanter.quant_bits == 8)

    def make(l):
        w = l.inner.weight._data.astype(jnp.float32)   # [in, out]
        qmax = 127.0
        w_absmax = jnp.maximum(jnp.abs(w).max(axis=0), 1e-8)  # [out]
        w_step = w_absmax / qmax
        w_int8 = jnp.clip(jnp.round(w / w_step), -qmax, qmax) \
            .astype(jnp.int8)
        return Int8Linear(w_int8, w_step,
                          float(l.act_quanter.static_scale),
                          bias=l.inner.bias)

    return _swap_layers(m, pred, make)
