"""GradScaler with dynamic loss scaling.

ref: python/paddle/amp/grad_scaler.py:187-446 (check_finite_and_unscale +
update_loss_scaling). On TPU with bfloat16 (same exponent range as fp32)
scaling is unnecessary — enable defaults accordingly — but the fp16 path is
fully implemented for parity.

The whole scaling loop is device-resident: the loss scale and the
good/bad step counters live as 0-d device arrays, ``unscale_`` runs one
jitted executable over every grad (fp32 unscale + global finite check,
``optimizer.fused_step.unscale_and_check``), and the skip decision is a
0-d device bool that masks the optimizer update via ``where(found_inf,
old, new)`` — ``step()``/``update()`` never sync to host, fused or not.
When FLAGS_fused_optimizer is on, ``step()`` routes through
``fused_step.try_step_scaled`` so unscale, the finite check, clipping,
every param update AND the conditional skip run as ONE buffer-donated
executable. Host transfers happen only at explicit host boundaries
(``state_dict()``, a user reading ``get_loss_scaling()``).

Whole-step capture (jit/sot.py ``CapturedStep``) folds the ENTIRE
iteration — loss scale, backward, unscale + finite check, masked
update AND the dynamic-scale bookkeeping (:func:`_scale_update`) —
into one captured fwd+bwd+optimizer executable: the scale and the
good/bad counters ride as donated 0-d device carries
(:meth:`GradScaler.capture_carry` / :meth:`absorb_captured`), and
:meth:`capture_statics` gates which scaler/optimizer configurations
the captured program can reproduce bit-for-bit.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor


def _scale_update(found, scale, good, bad, incr_ratio, decr_ratio,
                  incr_every, decr_every):
    """Pure dynamic-loss-scaling bookkeeping (the reference's
    update_loss_scaling), branch-free so it runs on device."""
    bad2 = jnp.where(found, bad + 1, 0)
    good2 = jnp.where(found, 0, good + 1)
    dec = bad2 >= decr_every
    inc = good2 >= incr_every
    new_scale = jnp.where(
        found,
        jnp.where(dec, jnp.maximum(scale * decr_ratio, 1.0), scale),
        jnp.where(inc, scale * incr_ratio, scale))
    return new_scale, jnp.where(inc, 0, good2), jnp.where(dec, 0, bad2)


_update_jit = None


class GradScaler:
    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = jnp.float32(init_loss_scaling)
        self._incr_ratio = float(incr_ratio)
        self._decr_ratio = float(decr_ratio)
        self._incr_every = int(incr_every_n_steps)
        self._decr_every = int(decr_every_n_nan_or_inf)
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = jnp.int32(0)
        self._bad_steps = jnp.int32(0)
        # python False until an unscale runs, then a 0-d device bool;
        # both satisfy truthiness for host consumers (distributed AMP
        # allreduces it), neither forces a sync on the step path
        self._found_inf = False
        self._unscaled_opts = set()

    def scale(self, var):
        if not self._enable:
            return var
        # the first scale() of a new iteration (no unscale pending) is
        # the iteration boundary: clear the OR-accumulated found flag
        # even when the user skipped update() — static-scaling loops
        # legitimately do — so one bad batch can't latch the accumulator
        # and mask every future step
        if not self._unscaled_opts:
            self._found_inf = False
        # cast the scale into var's dtype so an fp16/bf16 loss keeps its
        # dtype (a strong f32 0-d array would promote where the old
        # weak Python float did not)
        return var * Tensor(self._scale.astype(var.dtype))

    def _accumulate_found(self, found):
        if self._found_inf is False:
            self._found_inf = found
        else:
            self._found_inf = jnp.logical_or(
                jnp.asarray(self._found_inf, bool), found)

    def unscale_(self, optimizer):
        if not self._enable or id(optimizer) in self._unscaled_opts:
            return
        self._unscaled_opts.add(id(optimizer))
        from ..optimizer import fused_step
        params = [p for p in optimizer._parameter_list
                  if p.grad is not None]
        if not params:
            return
        inv = jnp.float32(1.0) / self._scale
        new_grads, found = fused_step.unscale_and_check(
            [p.grad._data for p in params], inv)
        for p, g in zip(params, new_grads):
            p.grad._data = g
        self._accumulate_found(found)

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        from ..optimizer.optimizer import Optimizer
        cls = type(optimizer)
        # getattr, not cls.step: a delegating wrapper (shard_optimizer's
        # _ShardOptimizer routes through instance __getattr__) has no
        # class attr at all — treat it like an override and take the
        # legacy path that simply calls its step()
        if (getattr(cls, "step", None) is not Optimizer.step
                or getattr(cls, "_step_masked", None)
                is not Optimizer._step_masked
                or "step" in optimizer.__dict__):
            # a custom step() (LBFGS's closure loop, a user subclass
            # layering behavior on top of step) must run as written —
            # legacy host-decision path: unscale, read the flag, call
            # the override. The one AMP path that syncs to host.
            self.unscale_(optimizer)
            if not bool(jnp.asarray(self._found_inf, bool)):
                optimizer.step()
            self._unscaled_opts.discard(id(optimizer))
            return
        retry_fused = True
        # a patched/overridden unscale_ (shard_scaler's found-inf
        # allreduce, a subclass hook) must actually run — only the
        # fallback path below calls it, so skip the fused fast path
        plain_unscale = ("unscale_" not in self.__dict__
                         and type(self).unscale_ is GradScaler.unscale_)
        if plain_unscale and id(optimizer) not in self._unscaled_opts:
            # fused fast path: unscale + finite check + clip + update +
            # skip as ONE donated executable
            from ..optimizer import fused_step
            found = fused_step.try_step_scaled(
                optimizer, self._scale, prior_found=self._found_inf)
            if found is not None:
                self._accumulate_found(found)
                return
            # the fused gate just rejected this config — don't run the
            # same prepare scan (and its fallback counter) again below
            retry_fused = not fused_step.enabled()
        # fallback: batched unscale (one executable), then the masked
        # step — the decision stays on device here too
        self.unscale_(optimizer)
        optimizer._step_masked(jnp.asarray(self._found_inf, bool),
                               try_fused=retry_fused)
        self._unscaled_opts.discard(id(optimizer))

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.step(optimizer)
        self.update()

    def update(self):
        # the found flag is per-iteration regardless of dynamic scaling:
        # without this reset a single non-finite step would latch the OR
        # accumulator True and mask every future update
        found, self._found_inf = self._found_inf, False
        # update() ends the iteration for unscale marks too: an
        # unscale_-without-step iteration (grad inspection) must not
        # leave its id latched — a stale entry makes the next
        # iteration's unscale_ early-return and step() would then apply
        # still-scaled grads
        self._unscaled_opts.clear()
        if not (self._enable and self._dynamic):
            return
        global _update_jit
        if _update_jit is None:
            _update_jit = jax.jit(_scale_update)
        self._scale, self._good_steps, self._bad_steps = _update_jit(
            jnp.asarray(found, bool), self._scale,
            self._good_steps, self._bad_steps,
            jnp.float32(self._incr_ratio), jnp.float32(self._decr_ratio),
            jnp.int32(self._incr_every), jnp.int32(self._decr_every))

    # -- whole-step capture (jit/sot.py CapturedStep) ---------------------
    def capture_statics(self, optimizer):
        """Hashable static scaler config for whole-step capture, or
        ``None`` when this scaler/optimizer pairing must run the eager
        path: an overridden ``step``/``unscale_``/``update`` (the
        distributed shard_scaler wrap, a user subclass) or a custom
        optimizer ``step()`` (the LBFGS pattern) has behavior the
        captured program cannot reproduce, and a pending manual
        ``unscale_`` mark means this iteration already started
        eagerly. The tuple joins the CapturedStep signature, so two
        scalers with different schedules never share a program."""
        if type(self).step is not GradScaler.step or \
                "step" in self.__dict__:
            return None
        if type(self).unscale_ is not GradScaler.unscale_ or \
                "unscale_" in self.__dict__:
            return None
        if type(self).update is not GradScaler.update or \
                "update" in self.__dict__:
            return None
        if self._unscaled_opts:
            return None  # mid-iteration: grads already unscaled eagerly
        from ..optimizer.optimizer import Optimizer
        cls = type(optimizer)
        if (getattr(cls, "step", None) is not Optimizer.step
                or getattr(cls, "_step_masked", None)
                is not Optimizer._step_masked
                or "step" in optimizer.__dict__):
            return None  # custom step() must run as written (host path)
        return (bool(self._dynamic), self._incr_ratio, self._decr_ratio,
                self._incr_every, self._decr_every)

    def capture_carry(self):
        """The device-resident scaler state as donated 0-d carries:
        (scale f32, good_steps i32, bad_steps i32). The captured step
        consumes (donates) these and :meth:`absorb_captured` rebinds
        the outputs — the loop never uploads or syncs scaler state."""
        return (jnp.asarray(self._scale, jnp.float32),
                jnp.asarray(self._good_steps, jnp.int32),
                jnp.asarray(self._bad_steps, jnp.int32))

    def absorb_captured(self, carry, found) -> None:
        """Install a captured step's outputs: the new (scale, good,
        bad) carry and the step's 0-d device found_inf (observability
        parity — reading it is the caller's sync to pay). The captured
        program already ran this iteration's ``update()`` bookkeeping,
        so the iteration ends here: unscale marks clear and the found
        accumulator holds only this step's flag."""
        self._scale, self._good_steps, self._bad_steps = carry
        self._found_inf = found
        self._unscaled_opts.clear()

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_loss_scaling(self):
        # copy: under whole-step capture the live scale buffer is
        # DONATED to the next captured step — a returned handle
        # wrapping it would read a deleted buffer
        return Tensor(jnp.copy(jnp.asarray(self._scale)))

    def set_init_loss_scaling(self, v):
        self._scale = jnp.float32(v)

    def state_dict(self):
        return {"scale": float(self._scale), "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio,
                "good_steps": int(self._good_steps),
                "bad_steps": int(self._bad_steps)}

    def load_state_dict(self, state):
        self._scale = jnp.float32(state.get("scale", float(self._scale)))
        self._good_steps = jnp.int32(state.get("good_steps", 0))
        self._bad_steps = jnp.int32(state.get("bad_steps", 0))
