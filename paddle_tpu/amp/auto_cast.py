"""AMP autocast.

ref: python/paddle/amp/auto_cast.py + the per-op AMP hook the reference
generates into every ad_func (eager_gen.py AMP block; manual example
fluid/eager/api/manual/eager_manual/forwards/multiply_fwd_func.cc:49-70).

TPU-native: bfloat16 is the native fast dtype (MXU), needs no loss scaling.
The autocast context installs a dtype-cast hook into apply_op's dispatch:
ops on the white list run their float32 inputs as bf16/fp16.
"""
from __future__ import annotations

import threading
from typing import Optional, Set

import jax.numpy as jnp

from ..core.dtype import convert_dtype
from ..core.tensor import Tensor

# O1 white list: matmul-ish ops where low precision is safe and fast
# (ref: python/paddle/amp/amp_lists.py white_list)
WHITE_LIST = {
    "matmul", "linear", "conv1d", "conv2d", "conv3d", "mm", "bmm",
    "einsum", "flash_attention", "sdpa",
}
# ops forced to fp32 (ref: black_list — softmax/norm/exp-ish numerics)
BLACK_LIST = {
    "softmax", "log_softmax", "cross_entropy", "layer_norm", "batch_norm",
    "group_norm", "rms_norm", "exp", "log", "mean", "sum", "logsumexp",
    "cumsum",
}


def white_list():
    return WHITE_LIST


class _AmpState(threading.local):
    def __init__(self):
        self.enabled = False
        self.dtype = None
        self.level = "O1"
        self.custom_white = set()
        self.custom_black = set()


_state = _AmpState()


def amp_state():
    return _state


def amp_signature() -> tuple:
    """Hashable autocast-regime tuple: the non-tensor thread-local state
    that steers traces (apply_op casts differently under it). ONE
    definition shared by SOTFunction's path signature and
    CapturedStep's program signature, so a program traced under one
    regime can never serve a call made under another."""
    return (bool(_state.enabled), str(getattr(_state, "dtype", None)),
            getattr(_state, "level", None),
            tuple(sorted(_state.custom_white or ())),
            tuple(sorted(_state.custom_black or ())))


class auto_cast:
    """Context manager. level O1 = per-op white list; O2 = everything except
    the black list runs in low precision."""

    def __init__(self, enable=True, custom_white_list=None,
                 custom_black_list=None, level="O1", dtype="bfloat16",
                 use_promote=True):
        self.enable = enable
        self.level = level
        self.dtype = dtype
        self.custom_white = set(custom_white_list or ())
        self.custom_black = set(custom_black_list or ())

    def __enter__(self):
        self._prev = (_state.enabled, _state.dtype, _state.level,
                      _state.custom_white, _state.custom_black)
        _state.enabled = self.enable
        _state.dtype = convert_dtype(self.dtype)
        _state.level = self.level
        _state.custom_white = self.custom_white
        _state.custom_black = self.custom_black
        return self

    def __exit__(self, *exc):
        (_state.enabled, _state.dtype, _state.level,
         _state.custom_white, _state.custom_black) = self._prev
        return False


autocast = auto_cast
amp_guard = auto_cast


def maybe_cast_inputs(op_name: str, datas):
    """Called from apply_op: returns datas cast per AMP policy."""
    if not _state.enabled:
        return datas
    name = op_name or ""
    white = (WHITE_LIST | _state.custom_white) - _state.custom_black
    black = (BLACK_LIST | _state.custom_black) - _state.custom_white
    low = _state.dtype

    def cast_to(arr, d):
        if hasattr(arr, "dtype") and arr.dtype == jnp.float32:
            return arr.astype(d)
        return arr

    if _state.level == "O2":
        if name in black:
            return [cast_to(a, jnp.float32) for a in datas]
        return [cast_to(a, low) for a in datas]
    if name in white:
        return [cast_to(a, low) for a in datas]
    if name in black:
        return [a.astype(jnp.float32)
                if hasattr(a, "dtype") and a.dtype == low else a
                for a in datas]
    return datas


def decorate(models, optimizers=None, level="O1", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """ref: python/paddle/amp/auto_cast.py amp_decorate. O2 casts model
    parameters to the low dtype (master weights live in the optimizer's
    fp32 moments on TPU)."""
    if level == "O2":
        items = models if isinstance(models, (list, tuple)) else [models]
        for m in items:
            m.to(dtype=dtype)
    if optimizers is None:
        return models
    return models, optimizers
