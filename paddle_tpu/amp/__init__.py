"""paddle.amp equivalent. ref: python/paddle/amp/__init__.py"""
from .auto_cast import auto_cast, autocast, decorate, amp_guard, white_list  # noqa: F401
from .grad_scaler import GradScaler  # noqa: F401
