# placeholder, filled in by build plan
