"""paddle.amp equivalent. ref: python/paddle/amp/__init__.py"""
from .auto_cast import auto_cast, autocast, decorate, amp_guard, white_list  # noqa: F401
from .grad_scaler import GradScaler  # noqa: F401


def is_float16_supported(device=None) -> bool:
    """ref: amp/__init__.py is_float16_supported. TPUs execute fp16
    arithmetic but have no fp16 MXU advantage — supported, not native."""
    import jax
    return jax.default_backend() in ("tpu", "axon", "gpu")


def is_bfloat16_supported(device=None) -> bool:
    """ref: amp/__init__.py is_bfloat16_supported. bf16 is the TPU's
    native fast dtype."""
    return True
