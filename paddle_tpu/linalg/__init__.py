from ..ops.linalg import *  # noqa: F401,F403
