"""paddle_tpu.observability — the unified telemetry runtime.

One process-wide metrics registry (``Counter`` / ``Gauge`` /
``Histogram``, kill-switchable via ``FLAGS_metrics``, default on) that
every subsystem registers into at import time, a step-timeline
plane (``timeline.StepTimer``) whose counter events merge into
``profiler.export_chrome_tracing``, and an always-on flight recorder
(``flight``: bounded black-box event journal + crash-forensics dumps,
``FLAGS_flight_recorder``; see ``python -m paddle_tpu.observability
--flight``).

Quick tour::

    import paddle_tpu as paddle
    from paddle_tpu import observability as obs

    # ... train / serve ...
    obs.snapshot()             # nested dict: dispatch/fusion/checkpoint/
                               # serving/... counters in one place
    obs.render_prometheus()    # text exposition format for a scraper
    srv = obs.start_metrics_server(port=9464)   # GET /metrics

Subsystems surfaced (each keeps its legacy ``stats()`` as a view):
``dispatch.*`` (op counts, jit pair compiles), ``fusion.*`` (chains,
cache hits, flush reasons), ``collectives.*`` / ``watchdog.*`` (span
latency, bytes, timeouts), ``store.*`` (op retries), ``checkpoint.*``
(saves, bytes, seconds, corrupt_skipped), ``serving.*`` (admissions,
token latency, queue depth), ``memory.*``, ``faults.*``, ``step.*``.
"""
from __future__ import annotations

from . import metrics, timeline  # noqa: F401
from .metrics import (  # noqa: F401
    Counter, Gauge, Histogram, Registry, Scope, DEFAULT_BUCKETS,
    counter, gauge, histogram, scope, default_registry, enabled,
    register_collector, snapshot, render_prometheus,
)
from .timeline import StepTimer  # noqa: F401
from . import flight  # noqa: F401  (after metrics/timeline: it uses both)

__all__ = [
    "Counter", "Gauge", "Histogram", "Registry", "Scope",
    "DEFAULT_BUCKETS", "counter", "gauge", "histogram", "scope",
    "default_registry", "enabled", "register_collector", "snapshot",
    "render_prometheus", "StepTimer", "metrics", "timeline", "flight",
    "start_metrics_server",
]


def start_metrics_server(port: int = 0, host: str = "127.0.0.1",
                         registry=None):
    """Serve ``/metrics`` (Prometheus text) + ``/metrics.json`` on a
    stdlib HTTP daemon thread; returns a handle with ``.url`` and
    ``.close()``. Lazy import keeps ``http.server`` off the package
    import path."""
    from .http import start_metrics_server as _start
    return _start(port=port, host=host, registry=registry)
