"""Stdlib HTTP exposition: serve the default registry at ``/metrics``.

One daemonized ``ThreadingHTTPServer`` per ``start_metrics_server``
call — the scrape path a Prometheus instance (or ``curl``) hits. No
third-party dependency; the handler renders on demand so a scrape
always sees current values.

Routes:
    /metrics        Prometheus text exposition format (v0.0.4)
    /metrics.json   the nested ``snapshot()`` dict as JSON
    /healthz        readiness JSON from the installed ``health_cb``
                    (200 when ``ok``, 503 otherwise; 404 with no
                    callback). The fleet router's replica probe and an
                    operator's load-balancer check read the SAME
                    snapshot — one source of truth for "can this
                    process take traffic".
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from . import metrics as _metrics

__all__ = ["MetricsServer", "start_metrics_server"]


class MetricsServer:
    """Handle for a running exposition endpoint; ``close()`` stops it.

    ``health_cb`` (optional) returns the readiness dict served at
    ``/healthz`` — it must contain a boolean ``"ok"`` (→ 200/503) and
    may carry anything else (pressure level, free KV blocks, backlog).
    A callback that raises reports not-ready instead of 500ing the
    probe: a health check must never be flakier than the thing it
    checks.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 registry: Optional["_metrics.Registry"] = None,
                 health_cb: Optional[Callable[[], dict]] = None):
        reg = registry or _metrics.default_registry()
        srv = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — stdlib handler contract
                status = 200
                if self.path in ("/metrics", "/"):
                    body = reg.render_prometheus().encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif self.path == "/metrics.json":
                    body = json.dumps(reg.snapshot(), default=str,
                                      indent=None).encode()
                    ctype = "application/json"
                elif self.path == "/healthz" and srv.health_cb is not None:
                    try:
                        snap = dict(srv.health_cb())
                    except Exception as e:
                        snap = {"ok": False, "error": f"{type(e).__name__}: {e}"}
                    status = 200 if snap.get("ok") else 503
                    body = json.dumps(snap, default=str).encode()
                    ctype = "application/json"
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # silence per-scrape stderr spam
                pass

        self.health_cb = health_cb
        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name=f"metrics-http-{self.port}")
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/metrics"

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def start_metrics_server(port: int = 0, host: str = "127.0.0.1",
                         registry=None, health_cb=None) -> MetricsServer:
    """Start the scrape endpoint; ``port=0`` picks an ephemeral port
    (read it back from ``server.port`` / ``server.url``)."""
    return MetricsServer(host=host, port=port, registry=registry,
                         health_cb=health_cb)
