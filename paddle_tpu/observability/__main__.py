"""``python -m paddle_tpu.observability`` — telemetry + flight CLI.

Default: print the process metrics snapshot as JSON (mostly useful from
an embedding process; a fresh CLI process has nothing hot).

Options:
  --flight [path]  render a flight-recorder dump as a readable event
                   trail (the crash-forensics reading surface). With no
                   path, the newest ``flight-*.jsonl`` in the dump dir
                   (FLAGS_flight_dump_dir, default system temp) is
                   used; if none exists the live in-process ring is
                   shown instead.
  --trace ID       filter --flight output to one request's trace_id
  --last N         only the last N events
  --json           emit JSON instead of text
"""
from __future__ import annotations

import json
import sys


def _flight_path(argv) -> object:
    """The operand following --flight, or None."""
    i = argv.index("--flight")
    for a in argv[i + 1:]:
        if not a.startswith("--"):
            return a
        break
    return None


def _opt(argv, name):
    if name in argv:
        i = argv.index(name)
        if i + 1 < len(argv):
            return argv[i + 1]
    return None


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if "--flight" in argv:
        from . import flight
        path = _flight_path(argv)
        trace = _opt(argv, "--trace")
        last = _opt(argv, "--last")
        header, evs = {}, []
        if path is None:
            dumps = flight.find_dumps()
            if dumps:
                path = dumps[0]
        if path is not None:
            try:
                header, evs = flight.load_dump(path)
            except (OSError, ValueError) as e:
                print(f"cannot read flight dump {path!r}: {e}",
                      file=sys.stderr)
                return 1
        else:
            evs = flight.events()
            header = {"trigger": "<live ring>", "events": len(evs),
                      "dropped": flight.dropped(),
                      "capacity": flight._capacity()}
        if trace is not None:
            evs = [e for e in evs if e.get("trace_id") == trace]
        if last is not None:
            evs = evs[-int(last):]
        if "--json" in argv:
            print(json.dumps({"header": header, "events": evs},
                             indent=2, default=str))
        else:
            if path is not None:
                print(f"# {path}")
            print(flight.render_events(evs, header))
        return 0
    from .metrics import snapshot
    print(json.dumps(snapshot(), indent=2, default=str))
    return 0


if __name__ == "__main__":
    sys.exit(main())
