"""Flight recorder: the always-on black-box event journal.

Metrics (``observability.metrics``) answer "how much" and the analysis
plane answers "where will capture break"; this module answers **"what
just happened"** when a step hangs, a request dies, or the process
crashes. It keeps a fixed-capacity ring of structured events — host
monotonic-µs timestamp on the same timebase the step timeline uses,
category, name, recording thread, an optional ``trace_id`` and a small
attrs dict — that every subsystem appends into from its existing
observer seams: fusion chain flushes and program compiles, device→host
syncs, fused-optimizer donations and fallbacks, whole-step jit builds,
SOT capture lifecycle events (``sot`` category: segment_compile /
capture_compile / guard_miss / retrace / fallback-by-reason — a
production guard-miss storm reads straight out of a dump), eager
collectives (op/bytes/duration) plus the captured distributed step's
bucketed gradient sync (``collective`` category: one ``grad_bucket``
event per bucket per step — index/payload bytes/grad count, the T3
overlap-efficiency numerator — and a ``dist_step`` summary carrying
the step's host dispatch duration), checkpoint save/restore/
corruption-fallback, elastic membership transitions, watchdog timeouts
and the per-request serving lifecycle (submit → queued → admitted →
[prefilled] → decode → finished/expired/rejected, keyed by
``trace_id``), plus the paged KV block pool's allocator
(``block_alloc`` / ``block_free`` / ``block_exhausted`` — a pool
running dry reads straight out of a dump next to the starved
requests' queue time) and its prefix-sharing radix cache
(``prefix_hit`` with the tokens a request's admission skipped,
``prefix_cow`` for each boundary-block copy-on-write clone,
``prefix_evict`` when LRU pressure reclaims a cached prefix block —
how much prefill the tree absorbed, and what it cost, per request),
the hot-start plane (``warmup`` category:
cache_configured / bundle_exported / bundle_failed-by-reason /
prewarm summary / per-program captured_step+serving_program replays
— a boot that compiled fresh instead of hitting the executable cache
reads straight out of its dump), zero-downtime weight hot-swaps
(``serving`` ``swap_begin`` / ``swap_end`` pairs bracketing the step
boundary the new weights installed at, with the in-flight count and
the ok/rejected verdict), and the self-healing serving plane:
``supervisor`` events (attached / loop_death / recover — per
recovered request, with its committed-token count / quarantine with
reason=poison / restart with backoff + streak / give_up /
abort_drain) journal every decode-loop crash-or-stall recovery,
``admission`` events (engage_/release_brownout_spec,
engage_/release_brownout_prefill, engage_/release_shed,
shed / shed_static / deadline_reject / release_clear) journal every
adaptive-admission decision with the evidence it was decided on, and
``rollout`` events (begin / canary_probe with the divergence /
stage_ok / rollback / halted-by-reason / end) journal a canary weight
rollout stage by stage — a bad deploy reads straight out of the
canary's dump. The fleet fabric journals as ``fleet`` events
(router_up / submit / dispatch with replica+epoch / finished/failed/
shed terminals — exactly one per request / replica_dead with reason /
failover with the committed-token count / stale_drop — a fenced
zombie's late answer / quarantined / resurrect_attempt / resurrected /
degraded): a replica SIGKILL and its recovery read as one trace.

Recording is on by default (``FLAGS_flight_recorder``) because an
append costs the same class of work as a ``Counter`` bump — one cached
flag read, one clock read, one tuple, one GIL-atomic ``deque.append``
— enforced by bench.py's ``flight_recorder_overhead`` line (≤5% of a
cached eager dispatch, same bar as ``metrics_overhead``).

Crash forensics: :func:`dump` freezes the ring as a JSONL file (header
line + one event per line) and best-effort merges the host-tracer
chrome trace next to it (``<dump>.trace.json`` via
``profiler.export_chrome_tracing``, which also embeds these events as
instant marks) so ONE artifact carries spans, metric series and the
last-N event trail. Triggers: explicit ``dump()``, the unhandled
exception hooks and optional signal handler installed by
:func:`install_crash_hooks`, and watchdog timeouts
(``distributed/watchdog.py`` dumps automatically). Every dump bumps
``observability.dumps_total{trigger=...}``.

Reading a dump: ``python -m paddle_tpu.observability --flight [path]``.
"""
from __future__ import annotations

import json
import os
import signal as _signal
import sys
import tempfile
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from ..core.flags import _registry as _flag_registry, define_flag
from . import metrics as _metrics

_native_now = None


def _now_us() -> float:
    """timeline._now_us semantics (host-tracer µs once the native lib is
    loaded, perf_counter µs before) with the resolved native clock
    cached — the append hot path must not pay a sys.modules lookup per
    event."""
    global _native_now
    f = _native_now
    if f is not None:
        return f()
    mod = sys.modules.get("paddle_tpu._native")
    lib = getattr(mod, "lib", None)
    if lib is not None:
        _native_now = lib.tracer_now
        return _native_now()
    return time.perf_counter() * 1e6

__all__ = [
    "record", "enabled", "events", "clear", "dropped", "appended",
    "dump", "last_dump_path", "find_dumps", "load_dump",
    "render_events", "chrome_events", "install_crash_hooks",
    "uninstall_crash_hooks", "dump_dir",
]

define_flag(
    "flight_recorder", True,
    "Always-on black-box event journal (observability.flight): a "
    "fixed-capacity ring of structured events (fusion flushes, host "
    "syncs, collectives, checkpoint/elastic/serving lifecycle) dumped "
    "as crash forensics on unhandled exceptions, watchdog timeouts, "
    "signals or flight.dump(). 0 disables recording (dump() still "
    "writes whatever the ring holds)")
define_flag(
    "flight_recorder_capacity", 4096,
    "Event capacity of the flight-recorder ring; the oldest events are "
    "evicted first (a dump carries the LAST N events)")
define_flag(
    "flight_dump_dir", "",
    "Directory flight-recorder dumps are written to; empty (default) "
    "uses the system temp dir")

_flag = _flag_registry["flight_recorder"]
_cap_flag = _flag_registry["flight_recorder_capacity"]
_dir_flag = _flag_registry["flight_dump_dir"]


def _make_lock():
    from ..analysis.locks import make_lock
    return make_lock("observability.flight")


_lock = _make_lock()

_M_dumps = _metrics.counter(
    "observability.dumps_total",
    "Flight-recorder dumps written, by trigger "
    "(explicit/exception/signal/watchdog)")


def _capacity() -> int:
    try:
        return max(int(_cap_flag.value), 16)
    except (TypeError, ValueError):
        return 4096


# event tuples: (ts_us, category, name, thread_ident, trace_id, attrs)
_ring: deque = deque(maxlen=_capacity())
_appended_n = 0
_dump_seq = 0
_last_dump: Optional[str] = None


def enabled() -> bool:
    """FLAGS_flight_recorder via the cached flag-info object — the same
    one-attribute-read kill switch the metrics plane uses."""
    return bool(_flag.value)


def _rebuild_ring() -> deque:
    """Capacity flag changed: rebuild the ring keeping the newest tail.
    Cold path (only on a flag transition)."""
    global _ring
    cap = _capacity()
    with _lock:
        if _ring.maxlen != cap:
            _ring = deque(_ring, maxlen=cap)
        return _ring


def record(category: str, name: str, trace_id: Optional[str] = None,
           **attrs) -> None:
    """Append one event to the ring. Hot-path contract: one cached flag
    read, one clock read, one tuple, one GIL-atomic deque append — no
    lock, no allocation beyond the event itself (losing an event to a
    racing capacity rebuild is acceptable; a black box is best-effort
    by definition)."""
    if not _flag.value:
        return
    global _appended_n
    ring = _ring
    if ring.maxlen != _cap_flag.value and ring.maxlen != _capacity():
        ring = _rebuild_ring()
    ring.append((_now_us(), category, name, threading.get_ident(),
                 trace_id, attrs or None))
    _appended_n += 1


def appended() -> int:
    """Events recorded since process start (including evicted ones)."""
    return _appended_n


def dropped() -> int:
    """Events evicted from the ring so far."""
    return max(0, _appended_n - len(_ring))


def clear() -> None:
    """Empty the ring and reset the appended tally (test/bench hook)."""
    global _appended_n
    with _lock:
        _ring.clear()
        _appended_n = 0


def _discard_events(pred) -> int:
    """Remove ring events matching ``pred(event_tuple)`` — internal,
    used by the analysis self-check to take its SYNTHETIC crash events
    back out of the production black box without dropping the real
    events recorded around them. An append racing the rebuild may be
    lost (the ring is best-effort by contract). Returns the count
    removed."""
    global _ring
    with _lock:
        kept = [ev for ev in _ring if not pred(ev)]
        removed = len(_ring) - len(kept)
        if removed:
            _ring = deque(kept, maxlen=_ring.maxlen)
    return removed


def _thread_names() -> Dict[int, str]:
    return {t.ident: t.name for t in threading.enumerate()
            if t.ident is not None}


def _to_dict(ev: Tuple, names: Optional[Dict[int, str]] = None
             ) -> Dict[str, Any]:
    ts, cat, name, tid, trace_id, attrs = ev
    d: Dict[str, Any] = {"ts_us": round(float(ts), 1), "cat": cat,
                         "name": name, "tid": tid}
    if names:
        thread = names.get(tid)
        if thread is not None:
            d["thread"] = thread
    if trace_id is not None:
        d["trace_id"] = trace_id
    if attrs:
        d["attrs"] = attrs
    return d


def events(n: Optional[int] = None, category: Optional[str] = None,
           trace_id: Optional[str] = None) -> List[Dict[str, Any]]:
    """Snapshot of the ring (oldest → newest) as dicts, optionally
    filtered by category and/or trace_id, truncated to the last ``n``."""
    with _lock:
        items = list(_ring)
    names = _thread_names()
    out = [_to_dict(ev, names) for ev in items
           if (category is None or ev[1] == category)
           and (trace_id is None or ev[4] == trace_id)]
    if n is not None:
        out = out[-int(n):]
    return out


def chrome_events() -> List[Dict[str, Any]]:
    """The ring as chrome-trace instant events ("ph": "i") —
    ``profiler.export_chrome_tracing`` merges these beside the host
    spans and step-timeline counters so one trace file carries all
    three planes."""
    with _lock:
        items = list(_ring)
    pid = os.getpid()
    out = []
    for ts, cat, name, tid, trace_id, attrs in items:
        args = dict(attrs) if attrs else {}
        if trace_id is not None:
            args["trace_id"] = trace_id
        out.append({"name": f"{cat}.{name}", "ph": "i", "s": "t",
                    "cat": cat, "pid": pid, "tid": tid, "ts": ts,
                    "args": args})
    return out


# ---------------------------------------------------------------------------
# dumps
# ---------------------------------------------------------------------------

def dump_dir() -> str:
    """Directory dumps land in: FLAGS_flight_dump_dir, or the system
    temp dir when unset."""
    d = str(_dir_flag.value or "").strip()
    return d or tempfile.gettempdir()


def dump(path: Optional[str] = None, trigger: str = "explicit",
         note: str = "") -> str:
    """Freeze the ring as a JSONL dump (header line + one event per
    line) and best-effort write the merged chrome trace beside it.
    Works regardless of FLAGS_flight_recorder — an operator asking for
    forensics gets whatever the ring holds. Returns the dump path."""
    global _dump_seq, _last_dump
    with _lock:
        items = list(_ring)
        _dump_seq += 1
        seq = _dump_seq
    names = _thread_names()
    if path is None:
        d = dump_dir()
        os.makedirs(d, exist_ok=True)
        path = os.path.join(
            d, f"flight-{os.getpid()}-{seq:03d}-{trigger}.jsonl")
    header = {
        "kind": "flight_header", "version": 1, "pid": os.getpid(),
        "trigger": trigger, "note": note,
        "time_unix": round(time.time(), 3), "host_now_us": _now_us(),
        "events": len(items), "dropped": dropped(),
        "capacity": _ring.maxlen, "thread_names":
            {str(k): v for k, v in names.items()},
    }
    chrome_path: Optional[str] = None
    try:
        from ..profiler import export_chrome_tracing
        chrome_path = export_chrome_tracing(path + ".trace.json")
        header["chrome_trace"] = os.path.basename(chrome_path)
    except Exception:  # noqa: BLE001 — no native tracer / no such dir
        chrome_path = None
    with open(path, "w") as f:
        f.write(json.dumps(header, default=str) + "\n")
        for ev in items:
            f.write(json.dumps(_to_dict(ev, names), default=str) + "\n")
        f.flush()
        try:
            os.fsync(f.fileno())
        except OSError:
            pass
    _M_dumps.inc(trigger=trigger)
    _last_dump = path
    return path


def last_dump_path() -> Optional[str]:
    return _last_dump


def find_dumps(directory: Optional[str] = None) -> List[str]:
    """Flight dumps in ``directory`` (default: :func:`dump_dir`),
    newest first."""
    d = directory or dump_dir()
    try:
        names = [n for n in os.listdir(d)
                 if n.startswith("flight-") and n.endswith(".jsonl")]
    except OSError:
        return []
    paths = [os.path.join(d, n) for n in names]
    paths.sort(key=lambda p: (os.path.getmtime(p), p), reverse=True)
    return paths


def load_dump(path: str) -> Tuple[Dict[str, Any], List[Dict[str, Any]]]:
    """(header, events) from a JSONL dump written by :func:`dump`."""
    header: Dict[str, Any] = {}
    evs: List[Dict[str, Any]] = []
    with open(path) as f:
        for i, line in enumerate(f):
            line = line.strip()
            if not line:
                continue
            obj = json.loads(line)
            if i == 0 and obj.get("kind") == "flight_header":
                header = obj
            else:
                evs.append(obj)
    return header, evs


def render_events(evs: List[Dict[str, Any]],
                  header: Optional[Dict[str, Any]] = None) -> str:
    """Human-readable trail: relative-ms timestamps, category.name,
    thread, trace id, attrs — the crash-forensics reading view."""
    lines: List[str] = []
    if header:
        lines.append(
            f"flight dump: trigger={header.get('trigger', '?')} "
            f"pid={header.get('pid', '?')} "
            f"events={header.get('events', len(evs))} "
            f"dropped={header.get('dropped', 0)} "
            f"capacity={header.get('capacity', '?')}"
            + (f" note={header['note']}" if header.get("note") else ""))
    if not evs:
        lines.append("<no events>")
        return "\n".join(lines)
    t0 = evs[0].get("ts_us", 0.0)
    for e in evs:
        rel_ms = (e.get("ts_us", t0) - t0) / 1e3
        who = e.get("thread") or e.get("tid", "?")
        tr = f" [{e['trace_id']}]" if "trace_id" in e else ""
        attrs = e.get("attrs") or {}
        astr = " ".join(f"{k}={v}" for k, v in attrs.items())
        lines.append(f"{rel_ms:+12.3f}ms  "
                     f"{e.get('cat', '?')}.{e.get('name', '?'):<24}"
                     f" ({who}){tr}{('  ' + astr) if astr else ''}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# crash-dump triggers: unhandled exceptions + signals
# ---------------------------------------------------------------------------

_prev_sys_hook = None
_prev_thread_hook = None
_prev_signals: Dict[int, Any] = {}
_hooks_installed = False


def _safe_dump(trigger: str, note: str = "") -> Optional[str]:
    try:
        return dump(trigger=trigger, note=note)
    except Exception:  # noqa: BLE001 — forensics must never re-crash
        return None


def install_crash_hooks(signals: Tuple[int, ...] = ()) -> None:
    """Install the crash-forensics triggers: wrap ``sys.excepthook`` and
    ``threading.excepthook`` so any unhandled exception records a
    ``crash`` event and writes a flight dump before chaining to the
    previous hook, and (optionally) bind the given signal numbers
    (e.g. ``signal.SIGUSR1``) to a live dump. Idempotent;
    :func:`uninstall_crash_hooks` restores everything."""
    global _prev_sys_hook, _prev_thread_hook, _hooks_installed
    if not _hooks_installed:
        _prev_sys_hook = sys.excepthook
        _prev_thread_hook = threading.excepthook

        def sys_hook(tp, val, tb):
            record("crash", "exception", error=tp.__name__,
                   message=str(val)[:200])
            _safe_dump("exception", f"{tp.__name__}: {val}"[:200])
            _prev_sys_hook(tp, val, tb)

        def thread_hook(args):
            tname = getattr(args.thread, "name", "?")
            record("crash", "thread_exception",
                   error=args.exc_type.__name__,
                   message=str(args.exc_value)[:200], thread=tname)
            _safe_dump("exception",
                       f"{args.exc_type.__name__} in thread {tname}: "
                       f"{args.exc_value}"[:200])
            _prev_thread_hook(args)

        sys.excepthook = sys_hook
        threading.excepthook = thread_hook
        _hooks_installed = True
    for signum in signals:
        if signum in _prev_signals:
            continue

        def handler(sig, frame, _n=signum):
            record("crash", "signal", signum=int(_n))
            _safe_dump("signal", f"signal {_n}")
            prev = _prev_signals.get(_n)
            if callable(prev):
                prev(sig, frame)

        try:
            _prev_signals[signum] = _signal.signal(signum, handler)
        except (ValueError, OSError):  # not main thread / unsupported
            pass


def uninstall_crash_hooks() -> None:
    """Restore the hooks/handlers :func:`install_crash_hooks` replaced."""
    global _hooks_installed
    with _lock:
        if _hooks_installed:
            sys.excepthook = _prev_sys_hook
            threading.excepthook = _prev_thread_hook
            _hooks_installed = False
        signums = list(_prev_signals)
        for signum in signums:
            prev = _prev_signals.pop(signum)
            try:
                _signal.signal(signum, prev)
            except (ValueError, OSError):
                pass
