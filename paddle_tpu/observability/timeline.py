"""Step-timeline plane: per-step phase durations as chrome-trace counter
events.

``StepTimer`` times the canonical training-step phases (data / forward /
backward / optimizer / checkpoint — names are free-form) and, at each
``step()`` boundary, freezes them as one chrome-trace counter event
(``"ph": "C"``). ``profiler.export_chrome_tracing`` merges these events
into the host-span dump, so one trace file carries spans *and* metric
time series — chrome://tracing and Perfetto render counter events as
stacked area charts under the span tracks.

Phase durations also feed the process registry
(``step.phase_seconds{phase=...}`` histogram, ``step.steps_total``), so
``observability.snapshot()`` answers "what did the last N steps look
like" without a trace file.

Clock: the native host tracer's monotonic-µs clock when the extension is
already loaded (so span and counter timestamps share one timebase),
``time.perf_counter`` otherwise — on Linux both read CLOCK_MONOTONIC.
"""
from __future__ import annotations

import os
import sys
import time
import weakref
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

from . import metrics as _metrics

__all__ = ["StepTimer", "chrome_events", "active_timers"]

# ring cap per timer: a counter event is ~100 bytes; 20k steps ~ 2MB
_EVENT_CAP = 20000


def _now_us() -> float:
    # never triggers the native C++ build: only use the clock if the
    # extension is ALREADY loaded (then span timestamps share its base)
    mod = sys.modules.get("paddle_tpu._native")
    lib = getattr(mod, "lib", None)
    if lib is not None:
        return lib.tracer_now()
    return time.perf_counter() * 1e6


_timers: "weakref.WeakSet" = weakref.WeakSet()


class StepTimer:
    """Accumulates named phase durations within a step; ``step()`` closes
    the step, emits the chrome counter event and registry observations.

        timer = StepTimer("train")
        for batch in loader:
            with timer.phase("data"):      x, y = batch
            with timer.phase("forward"):   loss = model(x, y)
            with timer.phase("backward"):  loss.backward()
            with timer.phase("optimizer"): opt.step()
            timer.step()
    """

    def __init__(self, name: str = "train",
                 registry: Optional["_metrics.Registry"] = None):
        self.name = name
        reg = registry or _metrics.default_registry()
        self._hist = reg.histogram(
            "step.phase_seconds",
            "Per-step phase durations recorded by StepTimer")
        self._step_hist = reg.histogram(
            "step.step_seconds", "Whole-step wall time (StepTimer)")
        self._steps = reg.counter(
            "step.steps_total", "Steps closed by StepTimer.step()")
        self._events: List[Dict[str, Any]] = []
        self._current: Dict[str, float] = {}
        self.step_index = 0
        self._step_t0 = _now_us()
        _timers.add(self)

    @contextmanager
    def phase(self, name: str):
        t0 = _now_us()
        try:
            yield
        finally:
            dt = (_now_us() - t0) / 1e6
            self._current[name] = self._current.get(name, 0.0) + dt
            self._hist.observe(dt, phase=name)

    def step(self) -> Dict[str, float]:
        """Close the current step: returns its {phase: seconds} dict."""
        now = _now_us()
        wall = (now - self._step_t0) / 1e6
        phases, self._current = self._current, {}
        self._steps.inc()
        self._step_hist.observe(wall)
        args = {k: round(v * 1e3, 6) for k, v in phases.items()}  # ms
        other = wall - sum(phases.values())
        if phases and other > 0:
            args["other"] = round(other * 1e3, 6)
        self._events.append({
            "name": f"{self.name}.step_phases_ms",
            "ph": "C", "pid": os.getpid(), "tid": 0,
            "ts": now, "args": args,
        })
        if len(self._events) > _EVENT_CAP:
            del self._events[: len(self._events) - _EVENT_CAP]
        self.step_index += 1
        self._step_t0 = now
        return phases

    def chrome_events(self) -> List[Dict[str, Any]]:
        return list(self._events)

    def clear(self) -> None:
        self._events.clear()
        self._current.clear()


def active_timers() -> List[StepTimer]:
    return list(_timers)


def chrome_events() -> List[Dict[str, Any]]:
    """Counter events from every live StepTimer — what
    ``export_chrome_tracing`` merges into the host-span trace."""
    out: List[Dict[str, Any]] = []
    for t in active_timers():
        out.extend(t.chrome_events())
    out.sort(key=lambda e: e.get("ts", 0))
    return out
