"""Process-wide metrics runtime: Counter / Gauge / Histogram + Registry.

The reference treats observability as a first-class plane (HostTracer
spans, ``memory/stats.h`` current/peak counters, ``comm_task_manager``
per-collective attribution). paddle_tpu grew the same signals as five
incompatible ad-hoc ``stats()`` dicts; this module is the uniform layer
they all migrate onto:

- **Instruments** are lock-cheap and kill-switchable: every mutation
  first checks ``FLAGS_metrics`` (one cached attribute read) and
  returns immediately when metrics are off — the always-on claim is
  enforced by bench.py's ``metrics_overhead`` line (≤5% dispatch
  overhead), not asserted.
- **Labels** ride as kwargs (``counter.inc(op="add")``); label values
  keep their Python type internally (the fusion chain-length view needs
  int keys back) and stringify only at exposition time.
- **Registry** holds instruments by dotted name (``serving.admitted_total``)
  plus *collectors* — zero-hot-path-cost callbacks polled only at
  ``snapshot()`` / ``render_prometheus()`` time, used to surface
  pre-existing counters (op dispatch counts, fault-injection tallies,
  memory watermarks) without adding a single instruction to their hot
  paths.
- ``snapshot()`` returns one nested JSON-able dict; ``render_prometheus()``
  emits Prometheus text exposition format (v0.0.4).

This module depends only on ``core.flags`` and stdlib so any subsystem
(including ``core.autograd``'s dispatch funnel) can import it at module
load without cycles.
"""
from __future__ import annotations

import bisect
import math
import threading
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..core.flags import _registry as _flag_registry  # noqa: F401

__all__ = [
    "Counter", "Gauge", "Histogram", "Registry", "Scope",
    "default_registry", "enabled", "flag_info", "counter", "gauge",
    "histogram", "scope", "register_collector", "snapshot",
    "render_prometheus", "DEFAULT_BUCKETS",
]

# Fixed log-spaced buckets: half-decade steps over 1µs .. 100s — wide
# enough for µs-scale dispatch and 10s-scale checkpoint persists with
# one shared shape (fixed buckets keep every Histogram cell a flat
# int list, no per-observation allocation).
DEFAULT_BUCKETS: Tuple[float, ...] = tuple(
    round(10.0 ** (e / 2.0), 12) for e in range(-12, 5))


_metrics_flag = None  # resolved _FlagInfo (registry identity is stable)


def enabled() -> bool:
    """FLAGS_metrics value via a cached flag-info object — the same
    one-attribute-read pattern autograd uses for check_nan_inf."""
    global _metrics_flag
    if _metrics_flag is None:
        _metrics_flag = _flag_registry["metrics"]
    return bool(_metrics_flag.value)


def flag_info():
    """The live FLAGS_metrics registry entry (identity is stable): hot
    paths cache it once and branch on ``.value`` inline — the cheapest
    legal kill-switch check (one global + one attribute read)."""
    global _metrics_flag
    if _metrics_flag is None:
        _metrics_flag = _flag_registry["metrics"]
    return _metrics_flag


def _label_key(labels: Dict[str, Any]) -> Tuple[Tuple[str, Any], ...]:
    if len(labels) == 1:  # the common case: one (k, v) pair, no sort
        return tuple(labels.items())
    return tuple(sorted(labels.items()))


class _Instrument:
    """Shared cell bookkeeping: () is the unlabeled cell, labeled cells
    key on sorted (name, value) tuples."""

    kind = "untyped"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        from ..analysis.locks import make_lock
        self._lock = make_lock(f"metrics.instrument:{name}")
        self._cells: Dict[Tuple, Any] = {}

    # -- introspection ---------------------------------------------------
    def series(self) -> Dict[Tuple, Any]:
        """{label-key tuple: cell snapshot} — () = unlabeled."""
        with self._lock:
            return dict(self._cells)

    def reset(self) -> None:
        with self._lock:
            self._cells.clear()

    def __repr__(self):
        return f"{type(self).__name__}({self.name!r})"


class Counter(_Instrument):
    """Monotonic counter. ``inc(n)`` unlabeled, ``inc(op="add")``
    labeled; mixing both works (separate cells).

    The unlabeled cell is the plain attribute ``_v`` so measured hot
    paths (the op-dispatch funnel) can count with ONE guarded attribute
    add — ``if flag.value: counter._v += 1`` — instead of a method call
    + lock (~1µs, >5% of a cached CPU dispatch). ``_v += n`` under the
    GIL can lose an increment across racing threads; telemetry
    tolerates that, the dispatch budget does not tolerate the lock."""

    kind = "counter"

    def __init__(self, name: str, help: str = ""):
        super().__init__(name, help)
        self._v = 0  # unlabeled fast cell (see class docstring)

    def inc(self, n: float = 1, **labels) -> None:
        if not enabled():
            return
        if not labels:
            self._v += n  # lock-free on purpose (class docstring)
            return
        key = _label_key(labels)
        with self._lock:
            self._cells[key] = self._cells.get(key, 0) + n

    def value(self, **labels):
        if not labels:
            return self._v
        key = _label_key(labels)
        with self._lock:
            return self._cells.get(key, 0)

    def series(self) -> Dict[Tuple, Any]:
        with self._lock:
            out = dict(self._cells)
        if self._v or not out:
            out[()] = self._v
        return out

    def reset(self) -> None:
        with self._lock:
            self._cells.clear()
            self._v = 0


class Gauge(_Instrument):
    """Point-in-time value; ``set_function`` installs a pull callback
    evaluated only at snapshot/exposition time (queue depths, cache
    sizes — zero hot-path cost)."""

    kind = "gauge"

    def __init__(self, name: str, help: str = ""):
        super().__init__(name, help)
        self._fn: Optional[Callable[[], float]] = None

    def set(self, v: float, **labels) -> None:
        if not enabled():
            return
        key = _label_key(labels) if labels else ()
        with self._lock:
            self._cells[key] = v

    def inc(self, n: float = 1, **labels) -> None:
        if not enabled():
            return
        key = _label_key(labels) if labels else ()
        with self._lock:
            self._cells[key] = self._cells.get(key, 0) + n

    def dec(self, n: float = 1, **labels) -> None:
        self.inc(-n, **labels)

    def set_function(self, fn: Callable[[], float]) -> None:
        self._fn = fn

    def value(self, **labels):
        if self._fn is not None and not labels:
            try:
                return self._fn()
            except Exception:  # noqa: BLE001 — a dead pull fn reads 0
                return 0
        key = _label_key(labels) if labels else ()
        with self._lock:
            return self._cells.get(key, 0)

    def series(self) -> Dict[Tuple, Any]:
        out = super().series()
        if self._fn is not None and () not in out:
            out[()] = self.value()
        return out


class _HistCell:
    __slots__ = ("counts", "sum", "count", "min", "max")

    def __init__(self, nbuckets: int):
        self.counts = [0] * (nbuckets + 1)  # +1 = the +Inf bucket
        self.sum = 0.0
        self.count = 0
        self.min = math.inf
        self.max = -math.inf


class Histogram(_Instrument):
    """Fixed-bucket histogram (log-spaced by default). ``observe(v)``
    is one bisect + three adds under the lock."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "", buckets=None):
        super().__init__(name, help)
        self.buckets: Tuple[float, ...] = tuple(
            sorted(buckets)) if buckets else DEFAULT_BUCKETS

    def observe(self, v: float, **labels) -> None:
        if not enabled():
            return
        v = float(v)
        key = _label_key(labels) if labels else ()
        i = bisect.bisect_left(self.buckets, v)
        with self._lock:
            cell = self._cells.get(key)
            if cell is None:
                cell = self._cells[key] = _HistCell(len(self.buckets))
            cell.counts[i] += 1
            cell.sum += v
            cell.count += 1
            if v < cell.min:
                cell.min = v
            if v > cell.max:
                cell.max = v

    # -- views -----------------------------------------------------------
    def _cell_dict(self, cell: _HistCell) -> Dict[str, Any]:
        nonzero = {}
        for le, c in zip(self.buckets, cell.counts):
            if c:
                nonzero[_fmt_num(le)] = c
        if cell.counts[-1]:
            nonzero["+Inf"] = cell.counts[-1]
        return {
            "count": cell.count,
            "sum": round(cell.sum, 9),
            "avg": round(cell.sum / cell.count, 9) if cell.count else 0.0,
            "min": cell.min if cell.count else 0.0,
            "max": cell.max if cell.count else 0.0,
            "buckets": nonzero,  # per-bucket (not cumulative) counts
        }

    def value(self, **labels) -> Dict[str, Any]:
        key = _label_key(labels) if labels else ()
        with self._lock:
            cell = self._cells.get(key)
            if cell is None:
                return {"count": 0, "sum": 0.0, "avg": 0.0,
                        "min": 0.0, "max": 0.0, "buckets": {}}
            return self._cell_dict(cell)


def _fmt_num(v) -> str:
    """Compact numeric literal valid in both exposition values and
    JSON-ish snapshots (1e-06, 0.25, 3)."""
    if isinstance(v, float) and v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v) if not isinstance(v, float) else format(v, "g")


def _escape_help(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(s: str) -> str:
    return (s.replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _sanitize(name: str) -> str:
    out = []
    for i, ch in enumerate(name):
        if ch.isalnum() and (i > 0 or not ch.isdigit()) or ch in "_:":
            out.append(ch)
        else:
            out.append("_")
    return "".join(out)


def _labels_str(key: Tuple[Tuple[str, Any], ...]) -> str:
    if not key:
        return ""
    inner = ",".join(
        f'{_sanitize(k)}="{_escape_label(str(v))}"' for k, v in key)
    return "{" + inner + "}"


class Scope:
    """Named-scope instrument factory: ``scope("serving").counter("x")``
    creates/fetches ``serving.x`` in the parent registry."""

    def __init__(self, registry: "Registry", prefix: str):
        self._registry = registry
        self._prefix = prefix.rstrip(".")

    def _full(self, name: str) -> str:
        return f"{self._prefix}.{name}"

    def counter(self, name: str, help: str = "") -> Counter:
        return self._registry.counter(self._full(name), help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._registry.gauge(self._full(name), help)

    def histogram(self, name: str, help: str = "",
                  buckets=None) -> Histogram:
        return self._registry.histogram(self._full(name), help, buckets)

    def scope(self, prefix: str) -> "Scope":
        return Scope(self._registry, self._full(prefix))


class Registry:
    """Central instrument table + snapshot-time collectors.

    Instrument creation is get-or-create by dotted name (idempotent —
    re-imports and multiple component instances share one instrument);
    asking for an existing name with a different type raises.
    """

    def __init__(self):
        from ..analysis.locks import make_lock
        self._lock = make_lock("metrics.registry", rlock=True)
        self._instruments: "OrderedDict[str, _Instrument]" = OrderedDict()
        self._collectors: "OrderedDict[str, Callable]" = OrderedDict()

    # -- creation --------------------------------------------------------
    def _get_or_create(self, cls, name: str, help: str, **kw):
        with self._lock:
            inst = self._instruments.get(name)
            if inst is not None:
                if not isinstance(inst, cls):
                    raise TypeError(
                        f"metric {name!r} already registered as "
                        f"{type(inst).__name__}, requested {cls.__name__}")
                return inst
            inst = cls(name, help, **kw)
            self._instruments[name] = inst
            return inst

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets=None) -> Histogram:
        return self._get_or_create(Histogram, name, help, buckets=buckets)

    def scope(self, prefix: str) -> Scope:
        return Scope(self, prefix)

    def register_collector(self, name: str, fn: Callable) -> None:
        """``fn() -> {dotted_name: number | {label_value: number}}``,
        polled only at snapshot/exposition time. Re-registering a name
        replaces the callback (module reload safety)."""
        with self._lock:
            self._collectors[name] = fn

    def get(self, name: str) -> Optional[_Instrument]:
        with self._lock:
            return self._instruments.get(name)

    def instruments(self) -> List[_Instrument]:
        with self._lock:
            return list(self._instruments.values())

    def reset(self) -> None:
        """Zero every instrument cell (collectors are external views and
        keep their own state). Test/bench convenience."""
        for inst in self.instruments():
            inst.reset()

    # -- collection ------------------------------------------------------
    def _collected(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        with self._lock:
            items = list(self._collectors.items())
        for cname, fn in items:
            try:
                part = fn() or {}
            except Exception:  # noqa: BLE001 — one bad view can't kill all
                continue
            out.update(part)
        return out

    # -- snapshot --------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """One nested dict over every instrument + collector: dotted
        names split into sub-dicts (``serving.admitted_total`` lands at
        ``snap["serving"]["admitted_total"]``)."""
        flat: Dict[str, Any] = {}
        for inst in self.instruments():
            series = inst.series()
            if isinstance(inst, Histogram):
                if not series:
                    flat[inst.name] = inst.value()
                elif tuple(series) == ((),):
                    flat[inst.name] = inst.value()
                else:
                    flat[inst.name] = {
                        (key[0][1] if len(key) == 1 else
                         ",".join(f"{k}={v}" for k, v in key)):
                        inst._cell_dict(cell)
                        for key, cell in series.items()}
            else:
                if not series:
                    flat[inst.name] = (inst.value()
                                       if isinstance(inst, Gauge) else 0)
                elif tuple(series) == ((),):
                    flat[inst.name] = series[()]
                else:
                    out = {}
                    for key, v in series.items():
                        if key == ():
                            out["_total"] = v
                        elif len(key) == 1:
                            out[key[0][1]] = v
                        else:
                            out[",".join(f"{k}={lv}" for k, lv in key)] = v
                    flat[inst.name] = out
        flat.update(self._collected())
        nested: Dict[str, Any] = {}
        for name, v in flat.items():
            parts = name.split(".")
            d = nested
            for p in parts[:-1]:
                nxt = d.get(p)
                if not isinstance(nxt, dict):
                    nxt = d[p] = {}
                d = nxt
            d[parts[-1]] = v
        return nested

    # -- exposition ------------------------------------------------------
    def render_prometheus(self) -> str:
        """Prometheus text exposition format v0.0.4."""
        lines: List[str] = []
        for inst in self.instruments():
            mname = _sanitize(inst.name.replace(".", "_"))
            if inst.help:
                lines.append(f"# HELP {mname} {_escape_help(inst.help)}")
            lines.append(f"# TYPE {mname} {inst.kind}")
            series = inst.series()
            if isinstance(inst, Histogram):
                if not series:
                    series = {(): _HistCell(len(inst.buckets))}
                for key, cell in series.items():
                    cum = 0
                    for le, c in zip(inst.buckets, cell.counts):
                        cum += c
                        lk = key + (("le", _fmt_num(le)),)
                        lines.append(
                            f"{mname}_bucket{_labels_str(lk)} {cum}")
                    cum += cell.counts[-1]
                    lk = key + (("le", "+Inf"),)
                    lines.append(f"{mname}_bucket{_labels_str(lk)} {cum}")
                    lines.append(
                        f"{mname}_sum{_labels_str(key)} "
                        f"{_fmt_num(float(cell.sum))}")
                    lines.append(
                        f"{mname}_count{_labels_str(key)} {cell.count}")
            else:
                if not series:
                    series = {(): inst.value()
                              if isinstance(inst, Gauge) else 0}
                for key, v in series.items():
                    lines.append(
                        f"{mname}{_labels_str(key)} "
                        f"{_fmt_num(float(v))}")
        # collectors render as untyped counters
        for name, v in sorted(self._collected().items()):
            mname = _sanitize(name.replace(".", "_"))
            lines.append(f"# TYPE {mname} counter")
            if isinstance(v, dict):
                # single implicit label named after the trailing name
                # segment's subject ("key")
                for lv, n in sorted(v.items(), key=lambda kv: str(kv[0])):
                    lines.append(
                        f'{mname}{{key="{_escape_label(str(lv))}"}} '
                        f"{_fmt_num(float(n))}")
            else:
                lines.append(f"{mname} {_fmt_num(float(v))}")
        return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# default (process-wide) registry + module-level conveniences
# ---------------------------------------------------------------------------

_default = Registry()


def default_registry() -> Registry:
    return _default


def counter(name: str, help: str = "") -> Counter:
    return _default.counter(name, help)


def gauge(name: str, help: str = "") -> Gauge:
    return _default.gauge(name, help)


def histogram(name: str, help: str = "", buckets=None) -> Histogram:
    return _default.histogram(name, help, buckets)


def scope(prefix: str) -> Scope:
    return _default.scope(prefix)


def register_collector(name: str, fn: Callable) -> None:
    _default.register_collector(name, fn)


def snapshot() -> Dict[str, Any]:
    return _default.snapshot()


def render_prometheus() -> str:
    return _default.render_prometheus()
