"""SOT-style dy2static: guarded compiled subgraphs with graph breaks.

The reference compiles arbitrary user Python with a CPython-bytecode
tracer (ref: python/paddle/jit/sot/opcode_translator/executor/
opcode_executor.py — guard-based cache, graph-break fallback) plus an AST
transpiler (python/paddle/jit/dy2static/). A bytecode interpreter is the
wrong tool on TPU, where every tensor op already flows through ONE
dispatch point (core.autograd.apply_op). This tracer therefore works at
the op-dispatch level:

- **Record**: run the function EAGERLY (so it is always correct, any
  Python allowed) while logging each apply_op into the current *segment*.
  When Python forces a host value out of a tensor (``bool()``/``item()``/
  ``.numpy()`` — i.e. data-dependent control flow), the segment is closed
  and the extracted value becomes a **guard** (the analog of the
  reference's graph break + guard).
- **Replay**: later calls with the same input signature execute the
  recorded segments as jit-compiled programs. Guards validate
  SPECULATIVELY: every segment of the recorded path dispatches without
  waiting, the guard tensors are packed into one uint8 array in-jit,
  and a single host fetch checks the whole path — N graph breaks cost
  one device round-trip, not N serialized ones. Matching paths run
  fully compiled; a mismatch discards the speculated tail (segments are
  pure programs; side-effectful recordings never replay) and re-records
  that branch (the trace tree grows one path per taken branch, e.g. one
  per while-loop trip count).
- **Fallback**: recordings that consumed RNG (dropout) or mutated
  buffers in place (BN train-mode running stats) are marked non-
  replayable — those calls simply stay eager, which is the reference's
  graph-break fallback contract with correctness guaranteed.

Dynamic shapes: the compile cache is keyed on input signatures and
LRU-bounded (FLAGS_sot_cache_size). Axes declared dynamic via
``BucketPolicy`` are padded up to the next bucket so varlen batches
reuse a bounded set of entries instead of compiling per length.
"""
from __future__ import annotations

import warnings
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import random as random_mod
from ..core import tensor as tensor_mod
from ..core import autograd as autograd_mod
from ..core.flags import define_flag, flag_value
from ..core.tensor import Tensor

__all__ = ["sot_compile", "SOTFunction", "BucketPolicy"]

define_flag("sot_cache_size", 64,
            "Max (signature, guard-path) entries in a SOTFunction's "
            "compile cache (LRU eviction)")


class BucketPolicy:
    """Pad dynamic axes up to bucket sizes so varlen inputs share compiled
    entries. ``axes`` maps arg index -> {axis: buckets}; ``buckets`` is a
    sorted list of sizes, or "pow2" for powers of two.

    Padding uses ``pad_value`` — choose it so the padded region is
    numerically inert for your model (e.g. the loss ignore_index for
    token ids, 0 for already-masked activations). This is an explicit
    policy, not silent magic: bucketing changes tensor shapes the
    function sees.
    """

    def __init__(self, axes: Dict[int, Dict[int, Any]], pad_value=0):
        self.axes = axes
        self.pad_value = pad_value

    def bucket_of(self, size: int, buckets) -> int:
        if buckets == "pow2":
            b = 1
            while b < size:
                b *= 2
            return b
        for b in buckets:
            if b >= size:
                return int(b)
        return int(buckets[-1])  # larger than every bucket: use max

    def apply(self, args: tuple):
        out = list(args)
        for idx, ax_map in self.axes.items():
            if idx >= len(out) or not isinstance(out[idx], Tensor):
                continue
            arr = out[idx]._data
            pads = [(0, 0)] * arr.ndim
            changed = False
            for axis, buckets in ax_map.items():
                size = arr.shape[axis]
                tgt = self.bucket_of(size, buckets)
                if tgt > size:
                    pads[axis] = (0, tgt - size)
                    changed = True
            if changed:
                arr = jnp.pad(arr, pads, constant_values=self.pad_value)
                out[idx] = Tensor(arr, stop_gradient=out[idx].stop_gradient)
        return tuple(out)


# ---------------------------------------------------------------------------
# recording structures
# ---------------------------------------------------------------------------

class _Op:
    __slots__ = ("fn", "arg_refs", "kwargs", "out_ids", "multi", "name")

    def __init__(self, fn, arg_refs, kwargs, out_ids, multi, name=""):
        self.fn = fn            # pure jax fn captured at record time
        self.arg_refs = arg_refs  # list of ("id", sot_id) | ("ext", Tensor) | ("lit", value)
        self.kwargs = kwargs
        self.out_ids = out_ids
        self.multi = multi
        self.name = name        # dispatch op name (capture-plan metadata)


class _Segment:
    __slots__ = ("ops", "jitted", "input_ids", "ext_tensors", "output_ids")

    def __init__(self):
        self.ops: List[_Op] = []
        self.jitted = None
        self.input_ids: List[int] = []
        self.ext_tensors: List[Tensor] = []
        self.output_ids: List[int] = []


class _Guard:
    __slots__ = ("tensor_id", "kind", "value")

    def __init__(self, tensor_id, kind, value):
        self.tensor_id = tensor_id
        self.kind = kind        # "item" | "numpy"
        self.value = value      # python scalar or small-ndarray bytes


class _Recording:
    """One straight-line trace: segments alternating with guards, plus the
    provenance of the final return value."""

    __slots__ = ("segments", "guards", "ext_guards", "result_spec",
                 "replayable", "why_not")

    def __init__(self):
        self.segments: List[_Segment] = []
        self.guards: List[_Guard] = []
        # (Tensor ref, bytes): captured tensors whose host value steered
        # Python during recording — re-checked up front at every replay
        self.ext_guards: List[Tuple[Tensor, bytes]] = []
        self.result_spec = None
        self.replayable = True
        self.why_not = ""


_MAX_GUARD_BYTES = 256

# content-digest memo for raw-array cache keys: keyed by object id with a
# weakref keeping the entry honest (a dead id can be reused by a new array)
_digest_memo: Dict[int, Tuple[Any, tuple]] = {}


def _content_digest(a):
    import hashlib
    import weakref
    # memoize ONLY for jax.Array: device buffers are immutable, so the
    # digest stays valid for the object's lifetime. Mutable host arrays
    # (np.ndarray) are re-hashed every call — host sha1 is cheap and a
    # stale digest would silently replay old constants.
    memoizable = isinstance(a, jax.Array)
    key = id(a)
    if memoizable:
        hit = _digest_memo.get(key)
        if hit is not None and hit[0]() is a:
            return hit[1]
    arr = np.asarray(a)
    dig = (arr.shape, str(arr.dtype),
           hashlib.sha1(arr.tobytes()).hexdigest())
    if memoizable:
        try:
            _digest_memo[key] = (weakref.ref(
                a, lambda _: _digest_memo.pop(key, None)), dig)
        except TypeError:
            pass
    return dig


class _Recorder:
    """Installs the apply_op / materialize / mutation / rng hooks for the
    duration of one eagerly-executed call."""

    def __init__(self):
        self.rec = _Recording()
        self.cur = _Segment()
        self.next_id = 0
        self.tensor_ids: Dict[int, int] = {}   # id(Tensor) -> sot id
        self.keepalive: List[Tensor] = []      # pin tensors so ids stay valid
        self.produced_in_cur: set = set()
        self.guard_values: List[Any] = []

    # -- id helpers --------------------------------------------------------
    def tag(self, t: Tensor) -> int:
        sid = self.next_id
        self.next_id += 1
        self.tensor_ids[id(t)] = sid
        self.keepalive.append(t)
        return sid

    def ref_of(self, t: Tensor):
        sid = self.tensor_ids.get(id(t))
        if sid is None:
            return ("ext", t)      # parameter / captured tensor
        return ("id", sid)

    # -- hooks -------------------------------------------------------------
    def on_op(self, fn, args, kwargs, outs, name):
        arg_refs = []
        for a in args:
            if isinstance(a, Tensor):
                arg_refs.append(self.ref_of(a))
            else:
                arg_refs.append(("lit", a))
        out_ids = []
        for o in outs:
            sid = self.tag(o)
            out_ids.append(sid)
            self.produced_in_cur.add(sid)
        self.cur.ops.append(
            _Op(fn, arg_refs, dict(kwargs), out_ids, len(outs) > 1,
                name))

    def on_materialize(self, t: Tensor, kind: str):
        sid = self.tensor_ids.get(id(t))
        arr = np.asarray(t._data)
        if arr.nbytes > _MAX_GUARD_BYTES:
            self.rec.replayable = False
            self.rec.why_not = (
                f"materialized a {arr.nbytes}-byte tensor into Python "
                f"(> {_MAX_GUARD_BYTES}B guard limit)")
            return
        value = arr.tobytes()
        if sid is None:
            # a tensor from outside the trace (captured param/const)
            # steered Python: guard on its value directly
            self.rec.ext_guards.append((t, value))
            return
        self._break(sid, kind, value)

    def on_mutation(self, t: Tensor):
        self.rec.replayable = False
        self.rec.why_not = "in-place tensor mutation during trace"

    def on_rng(self):
        self.rec.replayable = False
        self.rec.why_not = "RNG consumed during trace (e.g. dropout)"

    def on_backward(self):
        self.rec.replayable = False
        self.rec.why_not = "autograd backward ran during trace"

    def _break(self, sid: int, kind: str, value):
        # only tensors produced in the CURRENT segment need exporting from
        # it; guards on inputs or earlier-segment outputs read the replay
        # env directly
        extra = [sid] if sid in self.produced_in_cur else []
        self._close_segment(extra_outputs=extra)
        self.rec.guards.append(_Guard(sid, kind, value))

    def _close_segment(self, extra_outputs=()):
        seg = self.cur
        for sid in extra_outputs:
            if sid not in seg.output_ids:
                seg.output_ids.append(sid)
        self.rec.segments.append(seg)
        self.cur = _Segment()
        self.produced_in_cur = set()

    # -- finalize ----------------------------------------------------------
    def finish(self, result):
        # mark every id consumed by later segments / the result as a
        # segment output, and compute each segment's inputs
        def result_refs(r):
            if isinstance(r, Tensor):
                return self.ref_of(r)
            if isinstance(r, (list, tuple)):
                return (type(r).__name__,
                        [result_refs(v) for v in r])
            if isinstance(r, dict):
                return ("dict", {k: result_refs(v) for k, v in r.items()})
            return ("lit", r)

        self._close_segment()
        self.rec.result_spec = result_refs(result)

        produced_by = {}
        for si, seg in enumerate(self.rec.segments):
            for op in seg.ops:
                for oid in op.out_ids:
                    produced_by[oid] = si

        needed_after: Dict[int, set] = {}

        def note_need(sid, at_seg):
            src = produced_by.get(sid)
            if src is not None and src != at_seg:
                needed_after.setdefault(src, set()).add(sid)

        for si, seg in enumerate(self.rec.segments):
            for op in seg.ops:
                for kind, v in op.arg_refs:
                    if kind == "id":
                        note_need(v, si)

        def walk_result(spec):
            kind = spec[0]
            if kind == "id":
                note_need(spec[1], -1)
            elif kind in ("list", "tuple"):
                for v in spec[1]:
                    walk_result(v)
            elif kind == "dict":
                for v in spec[1].values():
                    walk_result(v)

        walk_result(self.rec.result_spec)
        # a guard read after later segments still needs its producer to
        # export it
        for g in self.rec.guards:
            note_need(g.tensor_id, -1)

        for si, seg in enumerate(self.rec.segments):
            outs = set(seg.output_ids) | needed_after.get(si, set())
            seg.output_ids = sorted(outs)
            ins = []
            exts = []
            seen_ext = set()
            local = {oid for op in seg.ops for oid in op.out_ids}
            for op in seg.ops:
                for kind, v in op.arg_refs:
                    if kind == "id" and v not in local and v not in ins:
                        ins.append(v)
                    elif kind == "ext" and id(v) not in seen_ext:
                        seen_ext.add(id(v))
                        exts.append(v)
            seg.input_ids = ins
            seg.ext_tensors = exts
        return self.rec


class _RecorderSession:
    def __init__(self, recorder: _Recorder):
        self.recorder = recorder

    def __enter__(self):
        r = self.recorder
        if autograd_mod._op_recorder is not None:
            raise RuntimeError(
                "SOT recording cannot nest with static-graph recording")
        autograd_mod._op_recorder = \
            lambda fn, args, kwargs, outs, name: r.on_op(
                fn, args, kwargs, outs, name)
        tensor_mod._materialize_hook = r.on_materialize
        tensor_mod._mutation_hook = r.on_mutation
        random_mod._key_observer = r.on_rng
        autograd_mod._backward_observer = r.on_backward
        return r

    def __exit__(self, *exc):
        autograd_mod._op_recorder = None
        tensor_mod._materialize_hook = None
        tensor_mod._mutation_hook = None
        random_mod._key_observer = None
        autograd_mod._backward_observer = None
        return False


# ---------------------------------------------------------------------------
# replay
# ---------------------------------------------------------------------------

def _compile_segment(seg: _Segment):
    """Build one jitted callable: (ext_arrays, input_arrays) -> outputs."""
    ops = seg.ops
    input_ids = list(seg.input_ids)
    output_ids = list(seg.output_ids)

    def seg_fn(ext_vals, in_vals):
        env: Dict[int, Any] = dict(zip(input_ids, in_vals))
        ext_map = {id(t): v for t, v in zip(seg.ext_tensors, ext_vals)}
        for op in ops:
            call = []
            for kind, v in op.arg_refs:
                if kind == "id":
                    call.append(env[v])
                elif kind == "ext":
                    call.append(ext_map[id(v)])
                else:
                    call.append(v)
            res = op.fn(*call, **op.kwargs)
            res = tuple(res) if op.multi else (res,)
            for oid, r in zip(op.out_ids, res):
                env[oid] = r
        return [env[o] for o in output_ids]

    return jax.jit(seg_fn)


@jax.jit
def _pack_bytes(vals):
    """Concatenate arbitrary fixed-size-dtype arrays into ONE uint8
    array (little-endian element bytes == numpy tobytes order)."""
    parts = []
    for v in vals:
        v = jnp.asarray(v)
        if v.dtype == jnp.bool_:
            v = v.astype(jnp.uint8)
        flat = v.reshape(-1)
        if flat.dtype.itemsize > 1:
            flat = jax.lax.bitcast_convert_type(
                flat, jnp.uint8).reshape(-1)
        parts.append(flat)
    if not parts:
        return jnp.zeros((0,), jnp.uint8)
    return jnp.concatenate(parts)


class _CompiledPath:
    """One guard path of one signature: compiled segments + guards."""

    def __init__(self, rec: _Recording, input_ids: List[int]):
        self.rec = rec
        self.input_ids = input_ids
        for seg in rec.segments:
            seg.jitted = _compile_segment(seg)
        # tail guard values (guard 0 is checked early, on its own),
        # concatenated once for the packed single-fetch validation
        self._tail_guard_bytes = b"".join(
            g.value for g in rec.guards[1:])

    def replay(self, input_tensors: List[Tensor]):
        """Returns (ok, result). ok=False on a guard miss.

        Each segment executes through apply_op, so replayed outputs carry
        tape nodes: loss.backward() after a replayed call differentiates
        THROUGH the compiled segments into the inputs and the captured
        parameters (apply_op takes jax.vjp of the jitted segment — the
        jit boundary is kept as a call primitive, so it stays compiled).

        Guard handling is SPECULATIVE (the lax.cond-flavored answer to
        the reference's per-break host sync, SURVEY §3.1): the FIRST
        guard is checked after the first segment (so a wrong candidate
        path — MRU probing tries siblings — costs ~one segment, as the
        per-guard scheme did), then every remaining segment dispatches
        without waiting and the rest of the guard tensors are packed
        into one uint8 array in-jit and validated with ONE further
        fetch — N graph breaks cost ~2 device round-trips instead of N
        serialized ones (device-resident ext guards share one more
        packed fetch). Segments are pure compiled programs
        (RNG/mutating recordings never replay), so a wrong-path tail is
        discarded without side effects; any exception while speculating
        (e.g. a NaN check tripping on wrong-path garbage) also falls
        back to re-recording, and NaN flags the discarded tail enqueued
        are rolled back.
        """
        from ..core import autograd as autograd_mod
        from ..core.autograd import apply_op
        rec = self.rec
        # ext guards: host values compare directly; device-resident ones
        # share one packed fetch
        dev_guards = []
        for t, val in rec.ext_guards:
            if isinstance(t._data, jax.Array):
                dev_guards.append((t._data, val))
            elif np.asarray(t._data).tobytes() != val:
                return False, None
        if dev_guards:
            got = np.asarray(_pack_bytes(
                [d for d, _ in dev_guards])).tobytes()
            if got != b"".join(v for _, v in dev_guards):
                return False, None
        env: Dict[int, Tensor] = dict(zip(self.input_ids, input_tensors))
        guard_vals = []
        # NaN-flag isolation: flush whatever earlier eager ops enqueued
        # FIRST (outside the try — a genuine pre-existing NaN raises
        # here with its real attribution), then give the speculation its
        # own queue. On success the speculation's flags merge back (they
        # belong to real outputs); on a miss they are discarded with the
        # garbage they describe. A mid-speculation stride flush only
        # ever sees speculation-owned flags, so a trip there is caught
        # below and simply falls back to re-record.
        autograd_mod.flush_nan_checks()
        saved_pending = autograd_mod._nan_pending
        autograd_mod._nan_pending = []

        def miss():
            autograd_mod._nan_pending = saved_pending
            return False, None

        try:
            for si, seg in enumerate(rec.segments):
                n_ext = len(seg.ext_tensors)
                in_tensors = [env[i] for i in seg.input_ids]
                if seg.ops:
                    jitted = seg.jitted

                    def run_seg(*flat, _j=jitted, _n=n_ext):
                        return tuple(_j(list(flat[:_n]),
                                        list(flat[_n:])))

                    outs = apply_op(run_seg, *seg.ext_tensors,
                                    *in_tensors, op_name="sot_segment")
                    if not isinstance(outs, tuple):
                        outs = (outs,)
                    for oid, o in zip(seg.output_ids, outs):
                        env[oid] = o
                if si < len(rec.guards):
                    g = rec.guards[si]
                    if si == 0:
                        # early check: wrong sibling candidates bail
                        # after one segment instead of a full path
                        got = np.asarray(
                            env[g.tensor_id]._data).tobytes()
                        if got != g.value:
                            return miss()
                    else:
                        guard_vals.append(env[g.tensor_id]._data)
            if guard_vals:
                got = np.asarray(_pack_bytes(guard_vals)).tobytes()
                if got != self._tail_guard_bytes:
                    return miss()  # miss somewhere on the tail
        except FloatingPointError:
            # wrong-path garbage legitimately trips the NaN check;
            # re-record eagerly — if the CORRECT path is non-finite, the
            # re-record reproduces the error with its real context
            return miss()
        except Exception as e:  # noqa: BLE001 — degrade, but loudly
            warnings.warn(
                f"SOT replay fell back to re-recording on an unexpected "
                f"{type(e).__name__}: {e} — speculation disabled for "
                f"this call", RuntimeWarning)
            return miss()
        autograd_mod._nan_pending = \
            saved_pending + autograd_mod._nan_pending
        return True, self._build_result(env)

    def _build_result(self, env):
        def build(spec):
            kind = spec[0]
            if kind == "id":
                return env[spec[1]]
            if kind == "ext":
                return spec[1]
            if kind in ("list", "tuple"):
                vals = [build(v) for v in spec[1]]
                return tuple(vals) if kind == "tuple" else vals
            if kind == "dict":
                return {k: build(v) for k, v in spec[1].items()}
            return spec[1]
        return build(self.rec.result_spec)


class SOTFunction:
    """paddle.jit.to_static with graph breaks (see module docstring)."""

    def __init__(self, fn: Callable, bucket_policy: Optional[BucketPolicy]
                 = None, name: Optional[str] = None, input_spec=None):
        self._fn = fn
        self._bucket = bucket_policy
        self.input_spec = input_spec  # kept for save/export tooling parity
        self._name = name or getattr(fn, "__name__", "fn")
        # (signature, guard-values-tuple) -> _CompiledPath; the eager
        # fallback marker lives under (signature, "eager") so it never
        # shadows compiled paths of OTHER branches of the same signature
        self._cache: "OrderedDict[Tuple, Any]" = OrderedDict()
        self._warned = set()
        # why recordings stayed eager, by reason — the capture planner
        # reads this as dynamic PTC002-class evidence
        self._fallback_reasons: Dict[str, int] = {}
        # Layers whose .training flag steers the trace (dropout/BN modes):
        # the bound self plus any Layer captured in the fn's closure.
        # Their modes join the cache signature — the analog of the
        # reference SOT guarding attribute reads.
        from ..nn.layer import Layer
        self._layers = []

        def note(v):
            if isinstance(v, Layer) and v not in self._layers:
                self._layers.append(v)
            elif isinstance(v, (list, tuple)):
                for x in v:
                    if isinstance(x, Layer):
                        note(x)
            elif isinstance(v, dict):
                for x in v.values():
                    if isinstance(x, Layer):
                        note(x)

        note(getattr(fn, "__self__", None))
        for cell in getattr(fn, "__closure__", None) or ():
            try:
                note(cell.cell_contents)
            except ValueError:
                continue
        # module-global Layers the code actually references (co_names)
        code = getattr(fn, "__code__", None)
        gl = getattr(fn, "__globals__", None)
        if code is not None and gl is not None:
            for name in code.co_names:
                note(gl.get(name))

    # -- signature ---------------------------------------------------------
    @staticmethod
    def _arg_key(a):
        if isinstance(a, Tensor):
            return ("T", tuple(a._data.shape), str(a._data.dtype),
                    not a.stop_gradient)
        if isinstance(a, (np.ndarray, jax.Array)):
            # raw arrays are baked into the trace as constants, so the
            # key must cover their CONTENT (repr truncates large arrays);
            # the digest is memoized per array object so a reused buffer
            # isn't re-hashed (and re-fetched) every call
            return ("A", *_content_digest(a))
        return ("L", repr(a))

    def _signature(self, args, kwargs):
        parts = [self._arg_key(a) for a in args]
        for k in sorted(kwargs):
            parts.append((k, self._arg_key(kwargs[k])))
        # non-tensor state that steers traces: layer train/eval modes and
        # the AMP autocast regime (apply_op casts differently under it)
        from ..amp.auto_cast import _state as _amp_state
        modes = tuple(
            sub.training for lyr in self._layers
            for sub in lyr.sublayers(include_self=True))
        parts.append(("mode", modes, bool(_amp_state.enabled),
                      str(getattr(_amp_state, "dtype", None)),
                      getattr(_amp_state, "level", None),
                      tuple(sorted(getattr(_amp_state, "custom_white",
                                           ()) or ())),
                      tuple(sorted(getattr(_amp_state, "custom_black",
                                           ()) or ()))))
        return tuple(parts)

    def _cache_put(self, key, value):
        self._cache[key] = value
        self._cache.move_to_end(key)
        limit = max(int(flag_value("sot_cache_size") or 64), 1)
        while len(self._cache) > limit:
            self._cache.popitem(last=False)

    def cache_size(self):
        return len(self._cache)

    def capture_metadata(self):
        """Segment/guard metadata for the capture planner
        (``analysis.capture_plan``): per recorded path, the compiled
        segments (op names, arity) and the guards between them — the
        ground-truth segmentation whole-step capture starts from — plus
        the reasons any recording stayed eager (dynamic PTC002-class
        evidence: RNG, in-place mutation, oversized guards)."""
        paths = []
        for key, val in self._cache.items():
            if val == "eager":
                paths.append({"kind": "eager"})
                continue
            rec = val.rec
            paths.append({
                "kind": "compiled",
                "segments": [
                    {"n_ops": len(seg.ops),
                     "ops": [op.name for op in seg.ops],
                     "inputs": len(seg.input_ids),
                     "ext_tensors": len(seg.ext_tensors),
                     "outputs": len(seg.output_ids)}
                    for seg in rec.segments],
                "guards": [{"kind": g.kind, "nbytes": len(g.value)}
                           for g in rec.guards],
                "ext_guards": len(rec.ext_guards),
            })
        return {"name": self._name,
                "cache_entries": len(self._cache),
                "paths": paths,
                "fallback_reasons": dict(self._fallback_reasons)}

    @staticmethod
    def _tensor_args(args, kwargs):
        return [a for a in args if isinstance(a, Tensor)] + \
            [kwargs[k] for k in sorted(kwargs)
             if isinstance(kwargs[k], Tensor)]

    # -- record ------------------------------------------------------------
    def _record(self, sig, args, kwargs):
        rec_obj = _Recorder()
        tensor_args = self._tensor_args(args, kwargs)
        input_ids = [rec_obj.tag(t) for t in tensor_args]
        with _RecorderSession(rec_obj):
            result = self._fn(*args, **kwargs)
        rec = rec_obj.finish(result)
        guard_path = tuple(g.value for g in rec.guards)
        if rec.replayable:
            path = _CompiledPath(rec, input_ids)
            self._cache_put((sig, guard_path), path)
        else:
            # marker key is distinct from every guard-path key, so a
            # non-replayable BRANCH never evicts compiled sibling paths
            self._cache_put((sig, "eager"), "eager")
            # bounded cardinality: why_not can embed per-call values
            # (guard byte sizes) — past the cap, collapse to <other>
            reason = rec.why_not
            if reason not in self._fallback_reasons and \
                    len(self._fallback_reasons) >= 16:
                reason = "<other>"
            self._fallback_reasons[reason] = \
                self._fallback_reasons.get(reason, 0) + 1
            if self._name not in self._warned:
                self._warned.add(self._name)
                warnings.warn(
                    f"to_static({self._name}): trace is not replayable "
                    f"({rec.why_not}); running eagerly (graph-break "
                    f"fallback)", stacklevel=3)
        return result

    # -- call --------------------------------------------------------------
    def __call__(self, *args, **kwargs):
        # nested under an active recording (outer SOTFunction or static
        # program tape): run the plain function so the OUTER recorder sees
        # every op — an inner replay would hide ops behind opaque ext refs
        if autograd_mod._op_recorder is not None:
            return self._fn(*args, **kwargs)
        if self._bucket is not None:
            args = self._bucket.apply(args)
        sig = self._signature(args, kwargs)
        tensor_args = self._tensor_args(args, kwargs)
        # candidate paths for this signature, most-recently-used first.
        # Each replay re-checks its own guards, so trying candidates in
        # order is always correct; a taken-branch set of size k costs at
        # most k replay attempts before falling back to re-recording.
        candidates = [(k, v) for k, v in reversed(self._cache.items())
                      if k[0] == sig and v != "eager"]
        for key, path in candidates:
            ok, result = path.replay(tensor_args)
            if ok:
                self._cache.move_to_end(key)
                return result
        if self._cache.get((sig, "eager")) == "eager":
            # a known non-replayable branch for this signature: plain
            # eager, skip the recording bookkeeping
            self._cache.move_to_end((sig, "eager"))
            return self._fn(*args, **kwargs)
        return self._record(sig, args, kwargs)


def sot_compile(fn=None, bucket_policy: Optional[BucketPolicy] = None):
    """Decorator form: @sot_compile or sot_compile(fn, bucket_policy=...)."""
    def deco(f):
        return SOTFunction(f, bucket_policy)
    if fn is not None:
        return deco(fn)
    return deco
