"""SOT-style dy2static: guarded compiled subgraphs with graph breaks.

The reference compiles arbitrary user Python with a CPython-bytecode
tracer (ref: python/paddle/jit/sot/opcode_translator/executor/
opcode_executor.py — guard-based cache, graph-break fallback) plus an AST
transpiler (python/paddle/jit/dy2static/). A bytecode interpreter is the
wrong tool on TPU, where every tensor op already flows through ONE
dispatch point (core.autograd.apply_op). This tracer therefore works at
the op-dispatch level:

- **Record**: run the function EAGERLY (so it is always correct, any
  Python allowed) while logging each apply_op into the current *segment*.
  When Python forces a host value out of a tensor (``bool()``/``item()``/
  ``.numpy()`` — i.e. data-dependent control flow), the segment is closed
  and the extracted value becomes a **guard** (the analog of the
  reference's graph break + guard).
- **Replay**: later calls with the same input signature execute the
  recorded segments as jit-compiled programs. Guards validate
  SPECULATIVELY: every segment of the recorded path dispatches without
  waiting, the guard tensors are packed into one uint8 array in-jit,
  and a single host fetch checks the whole path — N graph breaks cost
  one device round-trip, not N serialized ones. Matching paths run
  fully compiled; a mismatch discards the speculated tail (segments are
  pure programs; side-effectful recordings never replay) and re-records
  that branch (the trace tree grows one path per taken branch, e.g. one
  per while-loop trip count).
- **Fallback**: recordings that consumed RNG (dropout) or mutated
  buffers in place (BN train-mode running stats) are marked non-
  replayable — those calls simply stay eager, which is the reference's
  graph-break fallback contract with correctness guaranteed.

Dynamic shapes: the compile cache is keyed on input signatures and
LRU-bounded (FLAGS_sot_cache_size). Axes declared dynamic via
``BucketPolicy`` are padded up to the next bucket so varlen batches
reuse a bounded set of entries instead of compiling per length.
"""
from __future__ import annotations

import warnings
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import random as random_mod
from ..core import tensor as tensor_mod
from ..core import autograd as autograd_mod
from ..core.flags import define_flag, flag_value
from ..core.flags import _registry as _flag_registry
from ..core.tensor import Tensor
from ..observability import flight as _flight
from ..observability import metrics as _om

__all__ = ["sot_compile", "SOTFunction", "BucketPolicy", "capture",
           "CapturedStep", "capture_jit"]

define_flag("sot_cache_size", 64,
            "Max (signature, guard-path) entries in a SOTFunction's "
            "compile cache (LRU eviction)")
define_flag("sot_capture", True,
            "Whole-step program capture (jit/sot.py): SOTFunction "
            "replays recorded paths as compiled segments and "
            "hapi.Model.train_batch/eval_batch + jit.TrainStep run as "
            "ONE cached, buffer-donated executable. 0 is the kill "
            "switch: every consumer falls back to today's per-chain "
            "eager fusion, bit-for-bit")
define_flag("sot_capture_cache", 8,
            "Max captured whole-step executables per CapturedStep "
            "(LRU eviction; one entry per input signature x "
            "train/eval-mode x trainable-set x optimizer config)")
define_flag("sot_guard_budget", 512,
            "Max TOTAL guard bytes a recorded SOT path may validate "
            "per replay (per-guard values are capped at 256B "
            "separately); an over-budget recording stays eager with a "
            "counted fallback reason")

_capture_flag = _flag_registry["sot_capture"]
_capture_cache_flag = _flag_registry["sot_capture_cache"]
_guard_budget_flag = _flag_registry["sot_guard_budget"]

# -- telemetry: the production counters a guard-miss storm is diagnosed
# from (plus sot.* flight-recorder events for the black-box trail)
_M = _om.scope("sot")
_M_flag = _om.flag_info()
_M_captured = _M.counter(
    "captured_steps_total",
    "Step executions served by a captured program — a successful "
    "SOTFunction whole-path replay or one CapturedStep/capture_jit "
    "donated executable call")
_M_guard_miss = _M.counter(
    "guard_misses_total",
    "Replay guard validations that missed: the speculated tail was "
    "discarded (side-effect-free) and the next candidate path or a "
    "re-record served the call")
_M_retraces = _M.counter(
    "retraces_total",
    "Calls where every cached candidate path missed its guards and "
    "the branch was re-recorded (the trace tree grew)")
_M_fallbacks = _M.counter(
    "fallbacks_total",
    "Recordings that stayed eager (per-chain fusion), by reason "
    "(rng / mutation / backward / oversized_guard / guard_budget / "
    "gate reasons from CapturedStep)")
_M_seg_compiles = _M.counter(
    "segment_compiles_total",
    "SOT path segments jit-compiled (compile-on-second-replay; the "
    "first replay of a path runs its segments un-jitted)")
_M_step_compiles = _M.counter(
    "captured_compiles_total",
    "Whole-step captured programs built (CapturedStep signatures + "
    "capture_jit first executions)")
_M_hits = _M.counter(
    "cache_hits_total",
    "CapturedStep executions served by an already-built executable")


def _fallback_category(why: str) -> str:
    """Bounded-cardinality label for fallbacks_total: why_not strings
    can embed per-call values (byte sizes), counters must not."""
    if "RNG" in why:
        return "rng"
    if "mutation" in why:
        return "mutation"
    if "backward" in why:
        return "backward"
    if "guard budget" in why:
        return "guard_budget"
    if "guard limit" in why or "materialized" in why:
        return "oversized_guard"
    return "other"


def _count_fallback(reason: str, name: str = "") -> None:
    _M_fallbacks.inc(reason=reason)
    _flight.record("sot", "fallback", reason=reason, fn=name)


class BucketPolicy:
    """Pad dynamic axes up to bucket sizes so varlen inputs share compiled
    entries. ``axes`` maps arg index -> {axis: buckets}; ``buckets`` is a
    sorted list of sizes, or "pow2" for powers of two.

    Padding uses ``pad_value`` — choose it so the padded region is
    numerically inert for your model (e.g. the loss ignore_index for
    token ids, 0 for already-masked activations). This is an explicit
    policy, not silent magic: bucketing changes tensor shapes the
    function sees.
    """

    def __init__(self, axes: Dict[int, Dict[int, Any]], pad_value=0):
        self.axes = axes
        self.pad_value = pad_value

    def bucket_of(self, size: int, buckets) -> int:
        if buckets == "pow2":
            b = 1
            while b < size:
                b *= 2
            return b
        for b in buckets:
            if b >= size:
                return int(b)
        return int(buckets[-1])  # larger than every bucket: use max

    def apply(self, args: tuple):
        out = list(args)
        for idx, ax_map in self.axes.items():
            if idx >= len(out) or not isinstance(out[idx], Tensor):
                continue
            arr = out[idx]._data
            pads = [(0, 0)] * arr.ndim
            changed = False
            for axis, buckets in ax_map.items():
                size = arr.shape[axis]
                tgt = self.bucket_of(size, buckets)
                if tgt > size:
                    pads[axis] = (0, tgt - size)
                    changed = True
            if changed:
                arr = jnp.pad(arr, pads, constant_values=self.pad_value)
                out[idx] = Tensor(arr, stop_gradient=out[idx].stop_gradient)
        return tuple(out)


# ---------------------------------------------------------------------------
# recording structures
# ---------------------------------------------------------------------------

class _Op:
    __slots__ = ("fn", "arg_refs", "kwargs", "out_ids", "multi", "name")

    def __init__(self, fn, arg_refs, kwargs, out_ids, multi, name=""):
        self.fn = fn            # pure jax fn captured at record time
        self.arg_refs = arg_refs  # list of ("id", sot_id) | ("ext", Tensor) | ("lit", value)
        self.kwargs = kwargs
        self.out_ids = out_ids
        self.multi = multi
        self.name = name        # dispatch op name (capture-plan metadata)


class _Segment:
    __slots__ = ("ops", "jitted", "pure", "input_ids", "ext_tensors",
                 "output_ids")

    def __init__(self):
        self.ops: List[_Op] = []
        self.jitted = None   # built lazily: compile-on-second-replay
        self.pure = None     # the un-jitted segment function
        self.input_ids: List[int] = []
        self.ext_tensors: List[Tensor] = []
        self.output_ids: List[int] = []


class _Guard:
    __slots__ = ("tensor_id", "kind", "value")

    def __init__(self, tensor_id, kind, value):
        self.tensor_id = tensor_id
        self.kind = kind        # "item" | "numpy"
        self.value = value      # python scalar or small-ndarray bytes


class _Recording:
    """One straight-line trace: segments alternating with guards, plus the
    provenance of the final return value."""

    __slots__ = ("segments", "guards", "ext_guards", "result_spec",
                 "replayable", "why_not")

    def __init__(self):
        self.segments: List[_Segment] = []
        self.guards: List[_Guard] = []
        # (Tensor ref, bytes): captured tensors whose host value steered
        # Python during recording — re-checked up front at every replay
        self.ext_guards: List[Tuple[Tensor, bytes]] = []
        self.result_spec = None
        self.replayable = True
        self.why_not = ""


_MAX_GUARD_BYTES = 256

# content-digest memo for raw-array cache keys: keyed by object id with a
# weakref keeping the entry honest (a dead id can be reused by a new array)
_digest_memo: Dict[int, Tuple[Any, tuple]] = {}


def _content_digest(a):
    import hashlib
    import weakref
    # memoize ONLY for jax.Array: device buffers are immutable, so the
    # digest stays valid for the object's lifetime. Mutable host arrays
    # (np.ndarray) are re-hashed every call — host sha1 is cheap and a
    # stale digest would silently replay old constants.
    memoizable = isinstance(a, jax.Array)
    key = id(a)
    if memoizable:
        hit = _digest_memo.get(key)
        if hit is not None and hit[0]() is a:
            return hit[1]
    arr = np.asarray(a)
    dig = (arr.shape, str(arr.dtype),
           hashlib.sha1(arr.tobytes()).hexdigest())
    if memoizable:
        try:
            _digest_memo[key] = (weakref.ref(
                a, lambda _: _digest_memo.pop(key, None)), dig)
        except TypeError:
            pass
    return dig


class _Recorder:
    """Installs the apply_op / materialize / mutation / rng hooks for the
    duration of one eagerly-executed call."""

    def __init__(self):
        self.rec = _Recording()
        self.cur = _Segment()
        self.next_id = 0
        self.tensor_ids: Dict[int, int] = {}   # id(Tensor) -> sot id
        self.keepalive: List[Tensor] = []      # pin tensors so ids stay valid
        self.produced_in_cur: set = set()
        self.guard_values: List[Any] = []

    # -- id helpers --------------------------------------------------------
    def tag(self, t: Tensor) -> int:
        sid = self.next_id
        self.next_id += 1
        self.tensor_ids[id(t)] = sid
        self.keepalive.append(t)
        return sid

    def ref_of(self, t: Tensor):
        sid = self.tensor_ids.get(id(t))
        if sid is None:
            return ("ext", t)      # parameter / captured tensor
        return ("id", sid)

    # -- hooks -------------------------------------------------------------
    def on_op(self, fn, args, kwargs, outs, name):
        arg_refs = []
        for a in args:
            if isinstance(a, Tensor):
                arg_refs.append(self.ref_of(a))
            else:
                arg_refs.append(("lit", a))
        out_ids = []
        for o in outs:
            sid = self.tag(o)
            out_ids.append(sid)
            self.produced_in_cur.add(sid)
        self.cur.ops.append(
            _Op(fn, arg_refs, dict(kwargs), out_ids, len(outs) > 1,
                name))

    def on_materialize(self, t: Tensor, kind: str):
        sid = self.tensor_ids.get(id(t))
        arr = np.asarray(t._data)
        if arr.nbytes > _MAX_GUARD_BYTES:
            self.rec.replayable = False
            self.rec.why_not = (
                f"materialized a {arr.nbytes}-byte tensor into Python "
                f"(> {_MAX_GUARD_BYTES}B guard limit)")
            return
        value = arr.tobytes()
        if sid is None:
            # a tensor from outside the trace (captured param/const)
            # steered Python: guard on its value directly
            self.rec.ext_guards.append((t, value))
            return
        self._break(sid, kind, value)

    def on_mutation(self, t: Tensor):
        self.rec.replayable = False
        self.rec.why_not = "in-place tensor mutation during trace"

    def on_rng(self):
        self.rec.replayable = False
        self.rec.why_not = "RNG consumed during trace (e.g. dropout)"

    def on_backward(self):
        self.rec.replayable = False
        self.rec.why_not = "autograd backward ran during trace"

    def _break(self, sid: int, kind: str, value):
        # only tensors produced in the CURRENT segment need exporting from
        # it; guards on inputs or earlier-segment outputs read the replay
        # env directly
        extra = [sid] if sid in self.produced_in_cur else []
        self._close_segment(extra_outputs=extra)
        self.rec.guards.append(_Guard(sid, kind, value))

    def _close_segment(self, extra_outputs=()):
        seg = self.cur
        for sid in extra_outputs:
            if sid not in seg.output_ids:
                seg.output_ids.append(sid)
        self.rec.segments.append(seg)
        self.cur = _Segment()
        self.produced_in_cur = set()

    # -- finalize ----------------------------------------------------------
    def finish(self, result):
        # mark every id consumed by later segments / the result as a
        # segment output, and compute each segment's inputs
        def result_refs(r):
            if isinstance(r, Tensor):
                return self.ref_of(r)
            if isinstance(r, (list, tuple)):
                return (type(r).__name__,
                        [result_refs(v) for v in r])
            if isinstance(r, dict):
                return ("dict", {k: result_refs(v) for k, v in r.items()})
            return ("lit", r)

        self._close_segment()
        self.rec.result_spec = result_refs(result)

        produced_by = {}
        for si, seg in enumerate(self.rec.segments):
            for op in seg.ops:
                for oid in op.out_ids:
                    produced_by[oid] = si

        needed_after: Dict[int, set] = {}

        def note_need(sid, at_seg):
            src = produced_by.get(sid)
            if src is not None and src != at_seg:
                needed_after.setdefault(src, set()).add(sid)

        for si, seg in enumerate(self.rec.segments):
            for op in seg.ops:
                for kind, v in op.arg_refs:
                    if kind == "id":
                        note_need(v, si)

        def walk_result(spec):
            kind = spec[0]
            if kind == "id":
                note_need(spec[1], -1)
            elif kind in ("list", "tuple"):
                for v in spec[1]:
                    walk_result(v)
            elif kind == "dict":
                for v in spec[1].values():
                    walk_result(v)

        walk_result(self.rec.result_spec)
        # a guard read after later segments still needs its producer to
        # export it
        for g in self.rec.guards:
            note_need(g.tensor_id, -1)

        for si, seg in enumerate(self.rec.segments):
            outs = set(seg.output_ids) | needed_after.get(si, set())
            seg.output_ids = sorted(outs)
            ins = []
            exts = []
            seen_ext = set()
            local = {oid for op in seg.ops for oid in op.out_ids}
            for op in seg.ops:
                for kind, v in op.arg_refs:
                    if kind == "id" and v not in local and v not in ins:
                        ins.append(v)
                    elif kind == "ext" and id(v) not in seen_ext:
                        seen_ext.add(id(v))
                        exts.append(v)
            seg.input_ids = ins
            seg.ext_tensors = exts
        return self.rec


class _RecorderSession:
    def __init__(self, recorder: _Recorder):
        self.recorder = recorder

    def __enter__(self):
        r = self.recorder
        if autograd_mod._op_recorder is not None:
            raise RuntimeError(
                "SOT recording cannot nest with static-graph recording")
        autograd_mod._op_recorder = \
            lambda fn, args, kwargs, outs, name: r.on_op(
                fn, args, kwargs, outs, name)
        tensor_mod._materialize_hook = r.on_materialize
        tensor_mod._mutation_hook = r.on_mutation
        random_mod._key_observer = r.on_rng
        autograd_mod._backward_observer = r.on_backward
        return r

    def __exit__(self, *exc):
        autograd_mod._op_recorder = None
        tensor_mod._materialize_hook = None
        tensor_mod._mutation_hook = None
        random_mod._key_observer = None
        autograd_mod._backward_observer = None
        return False


# ---------------------------------------------------------------------------
# replay
# ---------------------------------------------------------------------------

def _segment_fn(seg: _Segment):
    """Build one PURE callable: (ext_arrays, input_arrays) -> outputs.
    Jitting is the caller's policy (compile-on-second-replay, like the
    fusion plane's second-sighting rule)."""
    ops = seg.ops
    input_ids = list(seg.input_ids)
    output_ids = list(seg.output_ids)

    def seg_fn(ext_vals, in_vals):
        env: Dict[int, Any] = dict(zip(input_ids, in_vals))
        ext_map = {id(t): v for t, v in zip(seg.ext_tensors, ext_vals)}
        for op in ops:
            call = []
            for kind, v in op.arg_refs:
                if kind == "id":
                    call.append(env[v])
                elif kind == "ext":
                    call.append(ext_map[id(v)])
                else:
                    call.append(v)
            res = op.fn(*call, **op.kwargs)
            res = tuple(res) if op.multi else (res,)
            for oid, r in zip(op.out_ids, res):
                env[oid] = r
        return [env[o] for o in output_ids]

    return seg_fn


@jax.jit
def _pack_bytes(vals):
    """Concatenate arbitrary fixed-size-dtype arrays into ONE uint8
    array (little-endian element bytes == numpy tobytes order)."""
    parts = []
    for v in vals:
        v = jnp.asarray(v)
        if v.dtype == jnp.bool_:
            v = v.astype(jnp.uint8)
        flat = v.reshape(-1)
        if flat.dtype.itemsize > 1:
            flat = jax.lax.bitcast_convert_type(
                flat, jnp.uint8).reshape(-1)
        parts.append(flat)
    if not parts:
        return jnp.zeros((0,), jnp.uint8)
    return jnp.concatenate(parts)


class _CompiledPath:
    """One guard path of one signature: recorded segments + guards.
    Segments compile LAZILY — the first replay runs them un-jitted
    (one-off paths never pay XLA), the second replay jits each segment
    once (``sot.segment_compiles_total`` + a flight event), and later
    replays are fully compiled."""

    def __init__(self, rec: _Recording, input_ids: List[int],
                 name: str = ""):
        self.rec = rec
        self.input_ids = input_ids
        self.name = name
        self.replays = 0  # successful whole-path replays
        for seg in rec.segments:
            seg.pure = _segment_fn(seg)
        # tail guard values (guard 0 is checked early, on its own),
        # concatenated once for the packed single-fetch validation
        self._tail_guard_bytes = b"".join(
            g.value for g in rec.guards[1:])

    def _runner(self, seg: _Segment):
        if self.replays < 1:
            return seg.pure
        if seg.jitted is None:
            from .warmup import ensure_executable_cache
            ensure_executable_cache()
            seg.jitted = jax.jit(seg.pure)
            _M_seg_compiles.inc()
            _flight.record("sot", "segment_compile", fn=self.name,
                           ops=len(seg.ops))
        return seg.jitted

    def replay(self, input_tensors: List[Tensor]):
        """Returns (ok, result). ok=False on a guard miss.

        Each segment executes through apply_op, so replayed outputs carry
        tape nodes: loss.backward() after a replayed call differentiates
        THROUGH the compiled segments into the inputs and the captured
        parameters (apply_op takes jax.vjp of the jitted segment — the
        jit boundary is kept as a call primitive, so it stays compiled).

        Guard handling is SPECULATIVE (the lax.cond-flavored answer to
        the reference's per-break host sync, SURVEY §3.1): the FIRST
        guard is checked after the first segment (so a wrong candidate
        path — MRU probing tries siblings — costs ~one segment, as the
        per-guard scheme did), then every remaining segment dispatches
        without waiting and the rest of the guard tensors are packed
        into one uint8 array in-jit and validated with ONE further
        fetch — N graph breaks cost ~2 device round-trips instead of N
        serialized ones (device-resident ext guards share one more
        packed fetch). Segments are pure compiled programs
        (RNG/mutating recordings never replay), so a wrong-path tail is
        discarded without side effects; any exception while speculating
        (e.g. a NaN check tripping on wrong-path garbage) also falls
        back to re-recording, and NaN flags the discarded tail enqueued
        are rolled back.
        """
        from ..core import autograd as autograd_mod
        from ..core.autograd import apply_op
        rec = self.rec
        # ext guards: host values compare directly; device-resident ones
        # share one packed fetch
        dev_guards = []
        for t, val in rec.ext_guards:
            if isinstance(t._data, jax.Array):
                dev_guards.append((t._data, val))
            elif np.asarray(t._data).tobytes() != val:
                self._note_miss("ext")
                return False, None
        if dev_guards:
            got = np.asarray(_pack_bytes(
                [d for d, _ in dev_guards])).tobytes()
            if got != b"".join(v for _, v in dev_guards):
                self._note_miss("ext")
                return False, None
        env: Dict[int, Tensor] = dict(zip(self.input_ids, input_tensors))
        guard_vals = []
        # NaN-flag isolation: flush whatever earlier eager ops enqueued
        # FIRST (outside the try — a genuine pre-existing NaN raises
        # here with its real attribution), then give the speculation its
        # own queue. On success the speculation's flags merge back (they
        # belong to real outputs); on a miss they are discarded with the
        # garbage they describe. A mid-speculation stride flush only
        # ever sees speculation-owned flags, so a trip there is caught
        # below and simply falls back to re-record.
        autograd_mod.flush_nan_checks()
        saved_pending = autograd_mod._nan_pending
        autograd_mod._nan_pending = []

        def miss():
            autograd_mod._nan_pending = saved_pending
            return False, None

        try:
            for si, seg in enumerate(rec.segments):
                n_ext = len(seg.ext_tensors)
                in_tensors = [env[i] for i in seg.input_ids]
                if seg.ops:
                    runner = self._runner(seg)

                    def run_seg(*flat, _j=runner, _n=n_ext):
                        return tuple(_j(list(flat[:_n]),
                                        list(flat[_n:])))

                    outs = apply_op(run_seg, *seg.ext_tensors,
                                    *in_tensors, op_name="sot_segment")
                    if not isinstance(outs, tuple):
                        outs = (outs,)
                    for oid, o in zip(seg.output_ids, outs):
                        env[oid] = o
                if si < len(rec.guards):
                    g = rec.guards[si]
                    if si == 0:
                        # early check: wrong sibling candidates bail
                        # after one segment instead of a full path
                        got = np.asarray(
                            env[g.tensor_id]._data).tobytes()
                        if got != g.value:
                            self._note_miss("early")
                            return miss()
                    else:
                        guard_vals.append(env[g.tensor_id]._data)
            if guard_vals:
                got = np.asarray(_pack_bytes(guard_vals)).tobytes()
                if got != self._tail_guard_bytes:
                    self._note_miss("tail")
                    return miss()  # miss somewhere on the tail
        except FloatingPointError:
            # wrong-path garbage legitimately trips the NaN check;
            # re-record eagerly — if the CORRECT path is non-finite, the
            # re-record reproduces the error with its real context
            return miss()
        except Exception as e:  # noqa: BLE001 — degrade, but loudly
            warnings.warn(
                f"SOT replay fell back to re-recording on an unexpected "
                f"{type(e).__name__}: {e} — speculation disabled for "
                f"this call", RuntimeWarning)
            return miss()
        autograd_mod._nan_pending = \
            saved_pending + autograd_mod._nan_pending
        self.replays += 1
        if _M_flag.value:
            _M_captured._v += 1  # inline fast cell: per-replay hot path
        return True, self._build_result(env)

    def _note_miss(self, where: str) -> None:
        _M_guard_miss.inc()
        _flight.record("sot", "guard_miss", fn=self.name, where=where)

    def _build_result(self, env):
        def build(spec):
            kind = spec[0]
            if kind == "id":
                return env[spec[1]]
            if kind == "ext":
                return spec[1]
            if kind in ("list", "tuple"):
                vals = [build(v) for v in spec[1]]
                return tuple(vals) if kind == "tuple" else vals
            if kind == "dict":
                return {k: build(v) for k, v in spec[1].items()}
            return spec[1]
        return build(self.rec.result_spec)


class SOTFunction:
    """paddle.jit.to_static with graph breaks (see module docstring)."""

    def __init__(self, fn: Callable, bucket_policy: Optional[BucketPolicy]
                 = None, name: Optional[str] = None, input_spec=None):
        self._fn = fn
        self._bucket = bucket_policy
        self.input_spec = input_spec  # kept for save/export tooling parity
        self._name = name or getattr(fn, "__name__", "fn")
        # (signature, guard-values-tuple) -> _CompiledPath; the eager
        # fallback marker lives under (signature, "eager") so it never
        # shadows compiled paths of OTHER branches of the same signature
        self._cache: "OrderedDict[Tuple, Any]" = OrderedDict()
        self._warned = set()
        # why recordings stayed eager, by reason — the capture planner
        # reads this as dynamic PTC002-class evidence
        self._fallback_reasons: Dict[str, int] = {}
        # Layers whose .training flag steers the trace (dropout/BN modes):
        # the bound self plus any Layer captured in the fn's closure.
        # Their modes join the cache signature — the analog of the
        # reference SOT guarding attribute reads.
        from ..nn.layer import Layer
        self._layers = []

        def note(v):
            if isinstance(v, Layer) and v not in self._layers:
                self._layers.append(v)
            elif isinstance(v, (list, tuple)):
                for x in v:
                    if isinstance(x, Layer):
                        note(x)
            elif isinstance(v, dict):
                for x in v.values():
                    if isinstance(x, Layer):
                        note(x)

        note(getattr(fn, "__self__", None))
        for cell in getattr(fn, "__closure__", None) or ():
            try:
                note(cell.cell_contents)
            except ValueError:
                continue
        # module-global Layers the code actually references (co_names)
        code = getattr(fn, "__code__", None)
        gl = getattr(fn, "__globals__", None)
        if code is not None and gl is not None:
            for name in code.co_names:
                note(gl.get(name))

    # -- signature ---------------------------------------------------------
    @staticmethod
    def _arg_key(a):
        if isinstance(a, Tensor):
            return ("T", tuple(a._data.shape), str(a._data.dtype),
                    not a.stop_gradient)
        if isinstance(a, (np.ndarray, jax.Array)):
            # raw arrays are baked into the trace as constants, so the
            # key must cover their CONTENT (repr truncates large arrays);
            # the digest is memoized per array object so a reused buffer
            # isn't re-hashed (and re-fetched) every call
            return ("A", *_content_digest(a))
        return ("L", repr(a))

    def _signature(self, args, kwargs):
        parts = [self._arg_key(a) for a in args]
        for k in sorted(kwargs):
            parts.append((k, self._arg_key(kwargs[k])))
        # non-tensor state that steers traces: layer train/eval modes and
        # the AMP autocast regime (apply_op casts differently under it)
        from ..amp.auto_cast import amp_signature
        modes = tuple(
            sub.training for lyr in self._layers
            for sub in lyr.sublayers(include_self=True))
        parts.append(("mode", modes) + amp_signature())
        return tuple(parts)

    def _cache_put(self, key, value):
        self._cache[key] = value
        self._cache.move_to_end(key)
        limit = max(int(flag_value("sot_cache_size") or 64), 1)
        while len(self._cache) > limit:
            self._cache.popitem(last=False)

    def cache_size(self):
        return len(self._cache)

    def capture_metadata(self):
        """Segment/guard metadata for the capture planner
        (``analysis.capture_plan``): per recorded path, the compiled
        segments (op names, arity) and the guards between them — the
        ground-truth segmentation whole-step capture starts from — plus
        the reasons any recording stayed eager (dynamic PTC002-class
        evidence: RNG, in-place mutation, oversized guards)."""
        paths = []
        for key, val in self._cache.items():
            if val == "eager":
                paths.append({"kind": "eager"})
                continue
            rec = val.rec
            paths.append({
                "kind": "compiled",
                "segments": [
                    {"n_ops": len(seg.ops),
                     "ops": [op.name for op in seg.ops],
                     "inputs": len(seg.input_ids),
                     "ext_tensors": len(seg.ext_tensors),
                     "outputs": len(seg.output_ids)}
                    for seg in rec.segments],
                "guards": [{"kind": g.kind, "nbytes": len(g.value)}
                           for g in rec.guards],
                "ext_guards": len(rec.ext_guards),
            })
        return {"name": self._name,
                "cache_entries": len(self._cache),
                "paths": paths,
                "fallback_reasons": dict(self._fallback_reasons)}

    @staticmethod
    def _tensor_args(args, kwargs):
        return [a for a in args if isinstance(a, Tensor)] + \
            [kwargs[k] for k in sorted(kwargs)
             if isinstance(kwargs[k], Tensor)]

    # -- record ------------------------------------------------------------
    def _record(self, sig, args, kwargs):
        rec_obj = _Recorder()
        tensor_args = self._tensor_args(args, kwargs)
        input_ids = [rec_obj.tag(t) for t in tensor_args]
        with _RecorderSession(rec_obj):
            result = self._fn(*args, **kwargs)
        rec = rec_obj.finish(result)
        if rec.replayable:
            # per-path guard budget: every replay re-validates the whole
            # guard set, so a path with kilobytes of guards pays more in
            # validation than compiled replay saves
            budget = max(int(_guard_budget_flag.value or 0), 0)
            total = sum(len(g.value) for g in rec.guards) + \
                sum(len(v) for _, v in rec.ext_guards)
            if budget and total > budget:
                rec.replayable = False
                rec.why_not = (
                    f"guard budget exceeded ({total}B of guard values > "
                    f"FLAGS_sot_guard_budget={budget}B)")
        guard_path = tuple(g.value for g in rec.guards)
        if rec.replayable:
            path = _CompiledPath(rec, input_ids, self._name)
            self._cache_put((sig, guard_path), path)
        else:
            # marker key is distinct from every guard-path key, so a
            # non-replayable BRANCH never evicts compiled sibling paths
            self._cache_put((sig, "eager"), "eager")
            # bounded cardinality: why_not can embed per-call values
            # (guard byte sizes) — past the cap, collapse to <other>
            reason = rec.why_not
            _count_fallback(_fallback_category(reason), self._name)
            if reason not in self._fallback_reasons and \
                    len(self._fallback_reasons) >= 16:
                reason = "<other>"
            self._fallback_reasons[reason] = \
                self._fallback_reasons.get(reason, 0) + 1
            if self._name not in self._warned:
                self._warned.add(self._name)
                warnings.warn(
                    f"to_static({self._name}): trace is not replayable "
                    f"({rec.why_not}); running eagerly (graph-break "
                    f"fallback)", stacklevel=3)
        return result

    # -- call --------------------------------------------------------------
    def __call__(self, *args, **kwargs):
        # nested under an active recording (outer SOTFunction or static
        # program tape): run the plain function so the OUTER recorder sees
        # every op — an inner replay would hide ops behind opaque ext refs
        if autograd_mod._op_recorder is not None:
            return self._fn(*args, **kwargs)
        if not _capture_flag.value:
            # kill switch: today's per-chain eager fusion, bit-for-bit
            return self._fn(*args, **kwargs)
        if self._bucket is not None:
            args = self._bucket.apply(args)
        sig = self._signature(args, kwargs)
        tensor_args = self._tensor_args(args, kwargs)
        # candidate paths for this signature, most-recently-used first.
        # Each replay re-checks its own guards, so trying candidates in
        # order is always correct; a taken-branch set of size k costs at
        # most k replay attempts before falling back to re-recording.
        candidates = [(k, v) for k, v in reversed(self._cache.items())
                      if k[0] == sig and v != "eager"]
        for key, path in candidates:
            ok, result = path.replay(tensor_args)
            if ok:
                self._cache.move_to_end(key)
                return result
        if candidates:
            # every cached path for this signature missed: the branch
            # re-records below (discard-and-retrace)
            _M_retraces.inc()
            _flight.record("sot", "retrace", fn=self._name,
                           candidates=len(candidates))
        if self._cache.get((sig, "eager")) == "eager":
            # a known non-replayable branch for this signature: plain
            # eager, skip the recording bookkeeping
            self._cache.move_to_end((sig, "eager"))
            return self._fn(*args, **kwargs)
        return self._record(sig, args, kwargs)


def sot_compile(fn=None, bucket_policy: Optional[BucketPolicy] = None):
    """Decorator form: @sot_compile or sot_compile(fn, bucket_policy=...)."""
    def deco(f):
        return SOTFunction(f, bucket_policy)
    if fn is not None:
        return deco(fn)
    return deco


def capture(fn=None, bucket_policy: Optional[BucketPolicy] = None,
            name: Optional[str] = None):
    """``@sot.capture`` — production whole-step capture for an arbitrary
    step callable: record once, replay as lazily-compiled segments with
    speculatively validated guards, fall back per-chain to eager fusion
    on unreplayable events (RNG/mutation/host I/O) with a counted
    reason. ``FLAGS_sot_capture=0`` restores plain eager execution.
    (For the known fwd+bwd+optimizer train-step shape, use
    :class:`CapturedStep` / ``jit.TrainStep`` — those run the whole step
    as ONE donated executable instead of per-segment replay.)"""
    def deco(f):
        return SOTFunction(f, bucket_policy, name=name)
    if fn is not None:
        return deco(fn)
    return deco


def capture_jit(fn, donate_argnums=(), name: Optional[str] = None,
                warm: Optional[Dict[str, Any]] = None):
    """Wrap an already-whole-step function (e.g. the serving decode
    body) as a captured executable: ``jax.jit`` + SOT capture
    accounting — the first (trace+compile) execution journals a
    ``sot.capture_compile`` flight event and every call counts into
    ``sot.captured_steps_total`` while ``FLAGS_sot_capture`` is on.
    Behavior is identical to ``jax.jit`` (the kill switch only mutes
    the accounting — the step was already a single executable).
    ``warm`` (a small JSON-able dict, e.g. the serving engines'
    program geometry) records the first compile into the warm-bundle
    manifest (``jit.warmup.note_program``) so a boot pre-warm can
    rebuild it AOT."""
    from .warmup import ensure_executable_cache, note_program
    ensure_executable_cache()
    jf = jax.jit(fn, donate_argnums=donate_argnums)
    nm = name or getattr(fn, "__name__", "fn")
    compiled = [False]

    def call(*args, **kwargs):
        out = jf(*args, **kwargs)
        # accounting only (execution above is a bare jax.jit either
        # way); the kill switch mutes ALL of it, and the compile event
        # lands only after the first call actually succeeded
        if _capture_flag.value:
            if not compiled[0]:
                compiled[0] = True
                _M_step_compiles.inc()
                _flight.record("sot", "capture_compile", fn=nm)
                if warm is not None:
                    note_program("serving", nm, {"meta": dict(warm)})
            if _M_flag.value:
                _M_captured._v += 1  # inline fast cell: hot path
        return out

    call._jitted = jf
    call.__name__ = nm
    return call


# ---------------------------------------------------------------------------
# whole-step capture: fwd + bwd + optimizer as ONE donated executable
# ---------------------------------------------------------------------------

class CapturedStep:
    """Execute a train (or eval) step as ONE cached, buffer-donated
    jitted executable — the Fusion III engine behind
    ``hapi.Model.train_batch``/``eval_batch`` and ``jit.TrainStep``.

    The capture plan (``analysis.capture_plan``, PR 7) proved a llama
    ``Model.fit`` step segments CONSISTENT: every flush boundary is
    absorbed by capture, the loss fetch is HOISTABLE, and the donated
    optimizer step is the tail segment. This class executes that plan:

    * **One program** per *signature* — batch shapes/dtypes, layer
      train/eval modes, the trainable set, optimizer type + static
      hyperparameters + per-param weight-decay statics, clip spec. A
      signature change is the guard miss: the stale program stays
      cached (LRU, ``FLAGS_sot_capture_cache``) and the new signature
      retraces.
    * **Compile policy** (``strict`` mode): first sighting of a
      signature runs today's eager path (and warms optimizer state),
      the second builds + compiles the whole-step program, later calls
      hit the cache — the fusion plane's compile-on-second-sighting.
    * **Donation** — params, buffers, optimizer state and the
      device-resident RNG carry are donated; leaves aliased by a live
      ``detach()`` snapshot are copied first (the PR 5 alias-registry
      contract), and pending eager-fusion chains are flushed through
      ``fusion.capture_handoff()`` before anything is invalidated.
    * **Hoisted loss** — the returned loss is a LAZY device scalar
      (a ``Tensor``); nothing inside the captured region syncs to
      host. Fetch it at the logging boundary (``float(loss)``).
    * **AMP + GradScaler** capture too (the PR 10 ``amp`` residue,
      closed): the autocast regime joins the signature and the forward
      traces under the ambient thread-local; with ``step(...,
      scaler=)`` the whole iteration — loss scale, backward, unscale +
      finite check, device-masked skip, dynamic-scale bookkeeping —
      is the one donated executable, scaler counters riding as 0-d
      device carries.
    * **Fallbacks** are total and counted (``sot.fallbacks_total``
      {reason} + a flight event): debug flags
      (check_nan_inf / benchmark / retain-all), layer or tensor hooks,
      non-fusable optimizers, unknown clip objects, non-static
      hyperparams, aliased donation leaves, pre-accumulated grads,
      overridden scaler/optimizer steps (``scaler``) —
      each returns ``None`` and the caller runs today's eager path.
    """

    def __init__(self, network, loss_fn=None, optimizer=None,
                 mean_reduce: bool = False, cast_loss_f32: bool = False,
                 donate: bool = True, strict: bool = True,
                 bucket_policy: Optional[BucketPolicy] = None,
                 name: str = "step", build_kind: str = "sot_capture"):
        from .api import _Swap
        self.network = network
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self._swap = _Swap(network)
        self._mean_reduce = mean_reduce
        self._cast_f32 = cast_loss_f32
        self._donate = donate
        self._strict = strict
        self._bucket = bucket_policy
        self._name = name
        self._build_kind = build_kind
        self._sublayers = list(network.sublayers(include_self=True))
        self._cache: "OrderedDict[tuple, Any]" = OrderedDict()
        # device-resident RNG carry: (root key, step counter), donated
        # through the program so dropout re-randomizes per step without
        # a per-step host->device key upload
        self._rng = None
        self._rng_epoch = None
        self.stats: Dict[str, Any] = {
            "captured_steps": 0, "compiles": 0, "cache_hits": 0,
            "eager_steps": 0, "fallbacks": {}}

    # -- gating ------------------------------------------------------------
    def _gate(self, train: bool, scaler=None) -> Optional[str]:
        """Capture preconditions. None = capturable; otherwise the
        fallback reason (the caller runs today's eager path). AMP
        autocast is NOT a gate anymore: the regime is part of the
        program signature and the forward traces under the ambient
        thread-local, so AMP (and GradScaler, via the ``scaler``
        carry) steps capture like plain ones."""
        if scaler is not None and \
                scaler.capture_statics(self.optimizer) is None:
            # an overridden scaler/optimizer step must run as written
            return "scaler"
        if _flag_registry["check_nan_inf"].value:
            return "nan_check"
        if _flag_registry["benchmark"].value:
            return "benchmark"
        if _flag_registry["retain_grad_for_all_tensor"].value:
            return "retain_grad"
        for lyr in self._sublayers:
            if lyr._forward_pre_hooks or lyr._forward_post_hooks:
                return "hooks"
        for p in self._swap.params.values():
            if p._hooks:
                return "hooks"
            if p._dist_attr is not None:
                return "dist"
            if isinstance(p._data, jax.core.Tracer):
                return "tracer"
        # a layer added/removed after this engine was built would be
        # invisible to the functionalized program — cheap count gate
        if sum(1 for _ in self.network.named_parameters()) != \
                len(self._swap.params):
            return "network_changed"
        if train:
            opt = self.optimizer
            if opt is None:
                return "no_optimizer"
            if getattr(opt, "_fusable_step", True) is False:
                return "optimizer"
            from ..utils.clip_grad import clip_spec
            if clip_spec(opt._grad_clip, exact=True) is None:
                return "grad_clip"
            from ..optimizer.fused_step import _hyper_key
            if _hyper_key(opt) is None:
                return "hyper"
            # the captured tail updates the NETWORK's trainables; the
            # eager step updates the OPTIMIZER's list — they must be
            # the same set or the semantics differ
            if {id(p) for p in opt._parameter_list
                if not p.stop_gradient} != \
                    {id(p) for p in self._swap.params.values()
                     if not p.stop_gradient}:
                return "param_set"
            if any(not p.stop_gradient and p.grad is not None
                   for p in self._swap.params.values()):
                # eager backward ACCUMULATES into primed grads; the
                # captured program starts from zero — not equivalent
                return "pending_grads"
        return None

    def _fallback(self, reason: str) -> None:
        self.stats["fallbacks"][reason] = \
            self.stats["fallbacks"].get(reason, 0) + 1
        _count_fallback(reason, self._name)

    # -- signature ---------------------------------------------------------
    def _tkeys(self):
        return [k for k in sorted(self._swap.params)
                if not self._swap.params[k].stop_gradient]

    def _signature(self, kind: str, arrays, n_ins: int, tkeys,
                   scaler_statics=None) -> Optional[tuple]:
        from ..amp.auto_cast import amp_signature
        modes = tuple(lyr.training for lyr in self._sublayers)
        # n_ins is part of the key: same shapes with a different
        # input/label split are DIFFERENT programs. The AMP regime is
        # a guard too: a program traced under autocast must never
        # serve a plain call (and vice versa).
        parts: List[Any] = [kind, n_ins, modes, tuple(tkeys),
                            amp_signature()]
        for a in arrays:
            parts.append((tuple(a.shape), str(a.dtype)))
        if kind in ("train", "train_scaled"):
            from ..optimizer.fused_step import _hyper_key, _param_statics
            from ..utils.clip_grad import clip_spec
            opt = self.optimizer
            statics = _param_statics(
                opt, [self._swap.params[k] for k in tkeys])
            if statics is None and self._strict:
                return None  # caller falls back (param_static)
            parts.append((type(opt).__qualname__, _hyper_key(opt),
                          statics,
                          clip_spec(opt._grad_clip,
                                    exact=self._strict)))
        if scaler_statics is not None:
            parts.append(("scaler",) + tuple(scaler_statics))
        return tuple(parts)

    # -- batch plumbing ----------------------------------------------------
    def _arrays(self, values) -> Optional[list]:
        """Raw device/host arrays for the batch; lazy fusion chains
        hand off at the capture boundary (flush reason sot_capture)."""
        from ..core import fusion
        out = []
        for v in values:
            if isinstance(v, Tensor):
                if v._lazy is not None:
                    fusion.materialize_tensor(v, "sot_capture")
                d = v._data
                if self._strict and isinstance(d, jax.core.Tracer):
                    return None  # under an outer trace: stay eager
                out.append(d)
            elif isinstance(v, jax.Array):
                out.append(v)
            elif hasattr(v, "aval"):  # raw tracer (nested jit)
                out.append(v)
            else:
                out.append(jnp.asarray(np.asarray(v)))
        return out

    # -- overridable build hooks (the distributed step specializes) --------
    def _value_and_grads(self, loss_of, train_p, buffers, batch, labels,
                         key):
        """Trace-time hook: loss + grads of the trainable tree for one
        step. ``loss_of(tp, bufs, mb, lbls, k_) -> (primal, (loss,
        new_buffers))`` — the primal is what backward differentiates
        (the SCALED loss under a GradScaler), the aux loss is what the
        caller sees. The distributed subclass overrides this with the
        gradient-merge scan."""
        (_, (loss, new_buffers)), grads = jax.value_and_grad(
            loss_of, has_aux=True)(train_p, buffers, batch, labels, key)
        return loss, grads, new_buffers

    def _sync_grads(self, grads, tkeys):
        """Trace-time hook between backward and the optimizer tail:
        the distributed subclass emits bucketed gradient collectives
        here (first-class DAG nodes that overlap remaining backward
        compute). Single-chip base: identity."""
        return grads

    # -- program build -----------------------------------------------------
    def _build(self, kind: str, n_ins: int, scaler_statics=None):
        from .api import _notify_build, _tree_unwrap
        from ..core.autograd import no_grad
        _notify_build(self._build_kind)
        network, loss_fn, opt = self.network, self.loss_fn, self.optimizer
        swap = self._swap
        mean_reduce, cast_f32 = self._mean_reduce, self._cast_f32

        def loss_value(out, lbls):
            loss_t = loss_fn(out, *lbls) if loss_fn is not None else out
            ld = loss_t._data
            if mean_reduce and ld.ndim > 0:
                ld = ld.mean()
            if cast_f32:
                ld = ld.astype(jnp.float32)
            return ld

        if kind == "eval":
            def eval_fn(params, buffers, key, *batch):
                with no_grad(), random_mod.key_stream(key):
                    ins = tuple(Tensor(b) for b in batch[:n_ins])
                    lbls = tuple(Tensor(b) for b in batch[n_ins:])
                    out, new_buffers = swap.run(params, buffers,
                                                network.__call__, *ins)
                    ld = loss_value(out, lbls) if \
                        (loss_fn is not None and lbls) else None
                return _tree_unwrap(out), ld, new_buffers

            return jax.jit(eval_fn)

        scaled = kind == "train_scaled"
        tkeys = self._tkeys()
        trainable = set(tkeys)
        param_objs = [swap.params[k] for k in tkeys]
        from ..utils.clip_grad import clip_spec
        cspec = clip_spec(opt._grad_clip, exact=self._strict) or ()

        def run_step(params, buffers, states, lr, key, batch,
                     scale=None):
            """fwd + bwd + (unscale/check) + optimizer tail — shared
            by the plain and the GradScaler-scaled programs."""
            train_p = {k: v for k, v in params.items() if k in trainable}
            frozen_p = {k: v for k, v in params.items()
                        if k not in trainable}

            def loss_of(tp, bufs, mb, lbls, k_):
                full = {**tp, **frozen_p}
                with no_grad(), random_mod.key_stream(k_):
                    ins = tuple(Tensor(b) for b in mb)
                    lbl_t = tuple(Tensor(x) for x in lbls)
                    out, new_buffers = swap.run(full, bufs,
                                                network.__call__, *ins)
                    ld = loss_value(out, lbl_t)
                # the primal backward differentiates is the SCALED loss
                # (eager parity: scaler.scale(loss).backward()); the
                # scale is cast into the loss dtype exactly like
                # GradScaler.scale
                primal = ld if scale is None else \
                    ld * scale.astype(ld.dtype)
                return primal, (ld, new_buffers)

            loss, grads, new_buffers = self._value_and_grads(
                loss_of, train_p, buffers, tuple(batch[:n_ins]),
                tuple(batch[n_ins:]), key)
            grads = self._sync_grads(grads, tkeys)
            g_leaves = [grads[k] for k in tkeys]
            p_leaves = [params[k] for k in tkeys]
            found = None
            if scale is not None:
                # grad unscale + global finite check: the SAME numeric
                # definition as GradScaler.unscale_/try_step_scaled
                from ..optimizer.fused_step import _unscale_fn
                g_leaves, found = _unscale_fn(
                    g_leaves, jnp.float32(1.0) / scale)
            from ..optimizer.fused_step import apply_update_tail
            new_ps, new_ss = apply_update_tail(
                opt, param_objs, p_leaves, g_leaves, states, lr, cspec)
            if found is not None:
                # conditional skip ON DEVICE (the fused scaled step's
                # mask): non-finite grads keep every param/state leaf
                new_ps = [jnp.where(found, p, q)
                          for p, q in zip(p_leaves, new_ps)]
                new_ss = [{k2: jnp.where(found, st[k2], v)
                           for k2, v in ns.items()}
                          for st, ns in zip(states, new_ss)]
            new_params = dict(params)
            for k, v in zip(tkeys, new_ps):
                new_params[k] = v
            return loss, new_params, new_buffers, new_ss, found

        if not scaled:
            def step_fn(params, buffers, states, lr, rng, *batch):
                root, count = rng
                key = jax.random.fold_in(root, count)
                loss, new_params, new_buffers, new_ss, _ = run_step(
                    params, buffers, states, lr, key, batch)
                return (loss, new_params, new_buffers, new_ss,
                        (root, count + jnp.uint32(1)))

            donate = (0, 1, 2, 4) if self._donate else ()
            return jax.jit(step_fn, donate_argnums=donate)

        # train_scaled: the whole GradScaler iteration in ONE program —
        # scale, backward, unscale + finite check, masked update, and
        # the dynamic-loss-scale bookkeeping on donated 0-d carries
        from ..amp.grad_scaler import _scale_update
        dynamic, incr_ratio, decr_ratio, incr_every, decr_every = \
            scaler_statics

        def scaled_step_fn(params, buffers, states, lr, rng, carry,
                           *batch):
            root, count = rng
            key = jax.random.fold_in(root, count)
            scale, good, bad = carry
            loss, new_params, new_buffers, new_ss, found = run_step(
                params, buffers, states, lr, key, batch, scale=scale)
            if dynamic:
                new_scale, new_good, new_bad = _scale_update(
                    found, scale, good, bad,
                    jnp.float32(incr_ratio), jnp.float32(decr_ratio),
                    jnp.int32(incr_every), jnp.int32(decr_every))
            else:
                new_scale, new_good, new_bad = scale, good, bad
            return (loss, new_params, new_buffers, new_ss,
                    (root, count + jnp.uint32(1)),
                    (new_scale, new_good, new_bad), found)

        donate = (0, 1, 2, 4, 5) if self._donate else ()
        return jax.jit(scaled_step_fn, donate_argnums=donate)

    def _get_program(self, kind: str, sig, n_ins: int,
                     scaler_statics=None, arrays=None):
        """Compile-on-second-sighting (strict mode): returns the jitted
        program, or None when this signature should run eager this
        call."""
        entry = self._cache.get(sig)
        if entry is not None and entry is not _SEEN_STEP:
            self._cache.move_to_end(sig)
            self.stats["cache_hits"] += 1
            _M_hits.inc()
            return entry
        if entry is None and self._strict:
            self._cache[sig] = _SEEN_STEP
            self._trim()
            return None
        from .warmup import (ensure_executable_cache, note_program,
                             sig_to_json)
        ensure_executable_cache()
        jitted = self._build(kind, n_ins, scaler_statics)
        self._cache[sig] = jitted
        self._trim()
        self.stats["compiles"] += 1
        _M_step_compiles.inc()
        _flight.record("sot", "capture_compile", fn=self._name,
                       kind=kind)
        # warm-bundle record: enough to rebuild this program AOT at a
        # future boot (prewarm), plus the exact signature so the warm
        # program pre-populates the in-memory cache too
        note_program("captured_step", self._name, {
            "build": kind, "n_ins": n_ins,
            "batch": [[list(a.shape), str(a.dtype)]
                      for a in (arrays or [])],
            "scaler": (list(scaler_statics) if scaler_statics
                       else None),
            "sig": sig_to_json(sig)})
        return jitted

    def _trim(self):
        cap = max(int(_capture_cache_flag.value or 8), 1)
        while len(self._cache) > cap:
            self._cache.popitem(last=False)

    # -- donation-safe leaf gathering --------------------------------------
    def _opt_state_for(self, p):
        """Optimizer slot state for one param (creation hook: the
        distributed subclass co-shards freshly created slots with the
        parameter's own placement — the ZeRO contract)."""
        return self.optimizer._state_for(p)

    @staticmethod
    def _safe_leaf(v):
        if isinstance(v, Tensor):
            v = v._data
        if not isinstance(v, jax.Array):
            v = jnp.asarray(v)
        if tensor_mod.buffer_has_alias(v):
            # a live detach() snapshot shares this buffer: donation
            # would delete it under the alias — donate a copy instead
            v = jnp.copy(v)
        return v

    def _gather(self, train: bool, tkeys=None):
        """(params, buffers, states) leaves for one call, alias-copied
        for donation. Two donated leaves sharing one buffer (tied
        storage — XLA rejects double donation): strict mode returns
        None (eager fallback); non-strict (TrainStep, no eager path)
        copies the duplicate and proceeds."""
        swap, opt = self._swap, self.optimizer
        params = {k: self._safe_leaf(t._data)
                  for k, t in swap.params.items()}
        buffers = {k: self._safe_leaf(t._data)
                   for k, t in swap.buffers.items()}
        states = []
        if train:
            for k in (self._tkeys() if tkeys is None else tkeys):
                st = self._opt_state_for(swap.params[k])
                states.append({kk: self._safe_leaf(vv)
                               for kk, vv in st.items()})
        if self._donate:
            seen = set()

            def dedup(leaf):
                if id(leaf) in seen:
                    return None if self._strict else jnp.copy(leaf)
                seen.add(id(leaf))
                return leaf

            for d in (params, buffers):
                for k, leaf in d.items():
                    leaf = dedup(leaf)
                    if leaf is None:
                        return None
                    d[k] = leaf
            for st in states:
                for k, leaf in st.items():
                    leaf = dedup(leaf)
                    if leaf is None:
                        return None
                    st[k] = leaf
        return params, buffers, states

    def _next_rng(self):
        if self._rng is None or \
                self._rng_epoch != random_mod.seed_epoch():
            self._rng = (random_mod.next_key(), jnp.uint32(0))
            self._rng_epoch = random_mod.seed_epoch()
        return self._rng

    # -- entry points ------------------------------------------------------
    def step(self, inputs, labels=(), scaler=None):
        """One captured train step over ``inputs``/``labels`` (lists of
        tensors/arrays). Returns the LAZY device loss ``Tensor``, or
        ``None`` when the caller must run today's eager path (kill
        switch, gate fallback, first sighting). In non-strict mode
        (``jit.TrainStep`` — an EXPLICIT whole-step API with no eager
        fallback) the kill switch and the gates do not apply.

        With ``scaler`` (an enabled ``amp.GradScaler``) the captured
        program is the WHOLE AMP iteration: loss scale, backward,
        grad unscale + finite check, device-masked update and the
        dynamic-loss-scale bookkeeping — the scaler's scale/counters
        ride as donated 0-d device carries and the skip decision
        never syncs to host."""
        if scaler is not None and not scaler.is_enable():
            scaler = None
        if self._strict:
            if not _capture_flag.value:
                return None
            if autograd_mod._op_recorder is not None:
                return None  # an outer recorder must see the real ops
            reason = self._gate(train=True, scaler=scaler)
            if reason is not None:
                self._fallback(reason)
                return None
        scaler_statics = None
        if scaler is not None:
            scaler_statics = scaler.capture_statics(self.optimizer)
            if scaler_statics is None:
                # non-strict callers have no eager path to fall back to
                raise RuntimeError(
                    "CapturedStep: this scaler/optimizer pairing "
                    "(overridden step()/unscale_()/update(), or a "
                    "pending manual unscale_) cannot run as a captured "
                    "program")
        if self._bucket is not None:
            inputs = list(self._bucket.apply(tuple(inputs)))
        arrays = self._arrays(list(inputs) + list(labels))
        if arrays is None:
            self._fallback("tracer")
            return None
        tkeys = self._tkeys()
        kind = "train" if scaler is None else "train_scaled"
        sig = self._signature(kind, arrays, len(inputs), tkeys,
                              scaler_statics)
        if sig is None:
            self._fallback("param_static")
            return None
        jitted = self._get_program(kind, sig, len(inputs),
                                   scaler_statics, arrays=arrays)
        if jitted is None:
            self.stats["eager_steps"] += 1
            return None
        gathered = self._gather(train=True, tkeys=tkeys)
        if gathered is None:
            self._fallback("aliased")
            return None
        params, buffers, states = gathered
        from ..core import fusion
        fusion.capture_handoff()
        from ..optimizer.fused_step import _lr_device
        opt, swap = self.optimizer, self._swap
        if scaler is None:
            loss, new_params, new_buffers, new_ss, self._rng = jitted(
                params, buffers, states, _lr_device(opt),
                self._next_rng(), *arrays)
        else:
            # donated carries: a live handle on the scale buffer (a
            # held get_loss_scaling snapshot) copies before donation
            carry = tuple(self._safe_leaf(v)
                          for v in scaler.capture_carry())
            (loss, new_params, new_buffers, new_ss, self._rng,
             new_carry, found) = jitted(
                params, buffers, states, _lr_device(opt),
                self._next_rng(), carry, *arrays)
            scaler.absorb_captured(new_carry, found)
        for k, t in swap.params.items():
            t._data = new_params[k]
        for k, t in swap.buffers.items():
            t._data = new_buffers[k]
        for k, ns in zip(tkeys, new_ss):
            opt._states[id(swap.params[k])] = ns
        opt._global_step += 1
        if self._strict:  # hapi semantics: step() + clear_grad()
            for p in opt._parameter_list:
                p.grad = None
        self.stats["captured_steps"] += 1
        if _M_flag.value:
            _M_captured._v += 1  # inline fast cell: per-step hot path
        return Tensor(loss)

    def forward(self, inputs, labels=()):
        """One captured eval/inference forward. Returns ``(out, loss)``
        — ``out`` re-wrapped as Tensors, ``loss`` a lazy device scalar
        or None — or ``None`` for the eager path."""
        if not _capture_flag.value:
            return None
        if autograd_mod._op_recorder is not None:
            return None
        reason = self._gate(train=False)
        if reason is not None:
            self._fallback(reason)
            return None
        if self._bucket is not None:
            inputs = list(self._bucket.apply(tuple(inputs)))
        arrays = self._arrays(list(inputs) + list(labels))
        if arrays is None:
            self._fallback("tracer")
            return None
        sig = self._signature("eval", arrays, len(inputs),
                              self._tkeys())
        jitted = self._get_program("eval", sig, len(inputs),
                                   arrays=arrays)
        if jitted is None:
            self.stats["eager_steps"] += 1
            return None
        from ..core import fusion
        fusion.capture_handoff()
        swap = self._swap
        params = {k: t._data for k, t in swap.params.items()}
        buffers = {k: t._data for k, t in swap.buffers.items()}
        root, count = self._next_rng()
        key = jax.random.fold_in(root, count)
        self._rng = (root, count + jnp.uint32(1))
        out, loss, new_buffers = jitted(params, buffers, key, *arrays)
        for k, t in swap.buffers.items():
            t._data = new_buffers[k]
        from .api import _tree_wrap
        self.stats["captured_steps"] += 1
        if _M_flag.value:
            _M_captured._v += 1
        return _tree_wrap(out), (None if loss is None else Tensor(loss))

    def prewarm(self, entry) -> None:
        """Boot pre-warm from one warm-bundle ``captured_step`` entry:
        rebuild the recorded program and AOT-compile it over abstract
        batch args (``lower().compile()`` — with the persistent
        executable cache enabled this is a disk read, not an XLA
        compile), then pre-populate the in-memory program cache under
        the recorded signature so the first real step is a cache hit
        (strict mode's first-sighting eager run is skipped too). A
        signature that no longer matches this model/optimizer merely
        leaves an unused cache entry — the real call still compiles
        against the disk cache. Raises on unreplayable entries; the
        caller (``warmup.prewarm``) counts and continues."""
        kind = entry.get("build")
        if kind not in ("train", "eval", "train_scaled"):
            raise ValueError(f"unknown captured_step build {kind!r}")
        n_ins = int(entry.get("n_ins", 1))
        batch = [jax.ShapeDtypeStruct(tuple(s), jnp.dtype(d))
                 for s, d in entry.get("batch", [])]
        scaler_statics = entry.get("scaler")
        if scaler_statics is not None:
            scaler_statics = tuple(scaler_statics)
        jitted = self._build(kind, n_ins, scaler_statics)
        swap = self._swap
        params = {k: t._data for k, t in swap.params.items()}
        buffers = {k: t._data for k, t in swap.buffers.items()}
        # helper args reuse the live step's own constructors
        # (next_key / the 0-d uint32 counter) or pure avals, so the
        # pre-warm never compiles a helper program the bundle's
        # writer didn't already write. The key draws are rolled back
        # after: pre-warm must not advance the seeded RNG stream, or a
        # warm boot's training randomness diverges from an identically
        # seeded cold boot.
        rng_state = random_mod.get_rng_state()
        try:
            if kind == "eval":
                jitted.lower(params, buffers, random_mod.next_key(),
                             *batch).compile()
            else:
                states = []
                for k in self._tkeys():
                    st = self._opt_state_for(swap.params[k])
                    states.append({kk: self._safe_leaf(vv)
                                   for kk, vv in st.items()})
                from ..optimizer.fused_step import _lr_device
                lr = _lr_device(self.optimizer)
                rng = (random_mod.next_key(), jnp.uint32(0))
                if kind == "train":
                    jitted.lower(params, buffers, states, lr, rng,
                                 *batch).compile()
                else:
                    carry = (jax.ShapeDtypeStruct((), jnp.float32),
                             jax.ShapeDtypeStruct((), jnp.int32),
                             jax.ShapeDtypeStruct((), jnp.int32))
                    jitted.lower(params, buffers, states, lr, rng,
                                 carry, *batch).compile()
        finally:
            random_mod.set_rng_state(rng_state)
        sig = entry.get("sig")
        if sig is not None:
            from .warmup import sig_from_json
            self._cache[sig_from_json(sig)] = jitted
            self._trim()
        _flight.record("warmup", "captured_step", fn=self._name,
                       kind=kind)

    def compile_stats(self, inputs, labels=()):
        """Compile the train step for these batch shapes without running
        it and return XLA's per-device memory analysis (TrainStep's
        compile_stats contract; bench emits it as peak_hbm_bytes)."""
        arrays = self._arrays(list(inputs) + list(labels))
        jitted = self._build("train", len(inputs))
        gathered = self._gather(train=True)
        params, buffers, states = gathered
        from ..optimizer.fused_step import _lr_device
        probe_rng = (jax.random.key(0), jnp.uint32(0))
        return jitted.lower(
            params, buffers, states, _lr_device(self.optimizer),
            probe_rng, *arrays).compile().memory_analysis()


_SEEN_STEP = object()  # first-sighting marker: signature noted, ran eager
