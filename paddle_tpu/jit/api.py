"""Functionalization + compiled train/eval steps.

Core mechanism: a Layer's Parameters/buffers are leaf Tensors; swapping
their ``._data`` for JAX tracers and calling ``forward`` traces the same
Python code into an XLA program. Gradients come from ``jax.value_and_grad``
over the functionalized program, and the optimizer's pure per-param
``_update`` runs inside the same compiled step (one fused XLA executable for
fwd+bwd+opt, the shape the TPU wants).
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core import random as random_mod
from ..core.autograd import no_grad
from ..core.tensor import Parameter, Tensor


# Analysis-auditor hook (paddle_tpu.analysis.auditor): notified with
# (kind,) each time a whole-step program is (re)built — a steady-state
# training loop should build exactly once, so builds inside an audit's
# measured window are recompile churn. None outside an audit.
_build_observer = None


def _notify_build(kind: str) -> None:
    from ..observability import flight as _flight
    from .warmup import ensure_executable_cache
    # every whole-step (re)build is about to jit-compile: make sure the
    # persistent executable cache is configured first (one flag read
    # when off; builds are rare)
    ensure_executable_cache()
    _flight.record("jit", "build", kind=kind)
    obs = _build_observer
    if obs is not None:
        obs(kind)


class InputSpec:
    """ref: python/paddle/static/input.py InputSpec"""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = shape
        self.dtype = dtype
        self.name = name


def _tree_unwrap(x):
    if isinstance(x, Tensor):
        return x._data
    if isinstance(x, (list, tuple)):
        return type(x)(_tree_unwrap(v) for v in x)
    if isinstance(x, dict):
        return {k: _tree_unwrap(v) for k, v in x.items()}
    return x


def _tree_wrap(x):
    if isinstance(x, (jax.Array,)) or hasattr(x, "aval"):
        return Tensor(x)
    if isinstance(x, (list, tuple)):
        return type(x)(_tree_wrap(v) for v in x)
    if isinstance(x, dict):
        return {k: _tree_wrap(v) for k, v in x.items()}
    return x


class _Swap:
    """Temporarily install pytree values into a layer's param/buffer
    Tensors; capture buffer mutations (e.g. BN running stats) on exit."""

    def __init__(self, layer):
        self.params = dict(layer.named_parameters())
        self.buffers = dict(layer.named_buffers())

    def run(self, param_vals: Dict[str, Any], buffer_vals: Dict[str, Any],
            fn, *args, **kwargs):
        old_p = {k: t._data for k, t in self.params.items()}
        old_b = {k: t._data for k, t in self.buffers.items()}
        try:
            for k, t in self.params.items():
                t._data = param_vals[k]
            for k, t in self.buffers.items():
                if k in buffer_vals:
                    t._data = buffer_vals[k]
            out = fn(*args, **kwargs)
            new_buffers = {k: t._data for k, t in self.buffers.items()}
            return out, new_buffers
        finally:
            for k, t in self.params.items():
                t._data = old_p[k]
            for k, t in self.buffers.items():
                t._data = old_b[k]


def functionalize(layer, fn: Optional[Callable] = None):
    """Returns (apply, params, buffers):
    apply(params, buffers, *args, **kwargs) -> (out_pytree, new_buffers)
    pure in its inputs; params/buffers are {name: jnp array} pytrees."""
    swap = _Swap(layer)
    call = fn if fn is not None else layer.__call__
    params0 = {k: t._data for k, t in swap.params.items()}
    buffers0 = {k: t._data for k, t in swap.buffers.items()}

    def apply(params, buffers, *args, **kwargs):
        with no_grad():
            args_t = tuple(Tensor(a) if _is_arr(a) else a for a in args)
            kwargs_t = {k: (Tensor(v) if _is_arr(v) else v)
                        for k, v in kwargs.items()}
            out, new_buffers = swap.run(params, buffers, call, *args_t,
                                        **kwargs_t)
            return _tree_unwrap(out), new_buffers

    return apply, params0, buffers0


def _is_arr(v):
    return isinstance(v, (jax.Array, np.ndarray)) or hasattr(v, "aval")


class StaticFunction:
    """Result of to_static on a layer/function: jit-compiled forward with a
    shape/dtype-keyed compile cache (jax.jit's own cache)."""

    def __init__(self, layer_or_fn, input_spec=None, **kwargs):
        from ..nn.layer import Layer
        self._is_layer = isinstance(layer_or_fn, Layer)
        if self._is_layer:
            self._layer = layer_or_fn
            self._fn = layer_or_fn.__call__
        else:
            self._layer = getattr(layer_or_fn, "__self__", None)
            self._fn = layer_or_fn
        self.input_spec = input_spec
        self._jitted = None

    def _build(self):
        _notify_build("static_function")
        if self._layer is not None:
            apply, _, _ = functionalize(self._layer, self._fn)

            @functools.partial(jax.jit)
            def jitted(params, buffers, key, *args, **kwargs):
                with random_mod.key_stream(key):
                    out, new_buffers = apply(params, buffers, *args,
                                             **kwargs)
                return out, new_buffers
            self._jitted = jitted
            self._swap = _Swap(self._layer)
        else:
            fn = self._fn

            @functools.partial(jax.jit)
            def jitted(key, *args, **kwargs):
                with random_mod.key_stream(key), no_grad():
                    args_t = tuple(Tensor(a) if _is_arr(a) else a
                                   for a in args)
                    out = fn(*args_t, **kwargs)
                return _tree_unwrap(out)
            self._jitted = jitted

    def __call__(self, *args, **kwargs):
        if self._jitted is None:
            self._build()
        raw_args = tuple(_tree_unwrap(a) for a in args)
        raw_kwargs = {k: _tree_unwrap(v) for k, v in kwargs.items()}
        key = random_mod.next_key()
        if self._layer is not None:
            params = {k: t._data for k, t in self._swap.params.items()}
            buffers = {k: t._data for k, t in self._swap.buffers.items()}
            out, new_buffers = self._jitted(params, buffers, key, *raw_args,
                                            **raw_kwargs)
            for k, t in self._swap.buffers.items():
                t._data = new_buffers[k]
            return _tree_wrap(out)
        out = self._jitted(key, *raw_args, **raw_kwargs)
        return _tree_wrap(out)


_to_static_enabled = True


def enable_to_static(flag: bool):
    """ref: jit/api.py enable_to_static — global kill-switch: with False,
    to_static returns the function/layer untouched (pure eager), the
    reference's debugging workflow for dy2static issues."""
    global _to_static_enabled
    _to_static_enabled = bool(flag)


_D2S_LOGGER_NAME = "paddle_tpu.jit.dy2static"


def set_verbosity(level: int = 0, also_to_stdout: bool = False):
    """ref: jit/dy2static/logging_utils.py set_verbosity — verbosity of
    the dy2static/SOT transform logs (0 silences, higher = chattier)."""
    import logging
    logger = logging.getLogger(_D2S_LOGGER_NAME)
    logger.setLevel(logging.WARNING if level <= 0 else
                    logging.INFO if level == 1 else logging.DEBUG)
    if also_to_stdout and not logger.handlers:
        import sys
        logger.addHandler(logging.StreamHandler(sys.stdout))


def set_code_level(level: int = 100, also_to_stdout: bool = False):
    """ref: jit/dy2static/logging_utils.py set_code_level — how much
    transformed code to log. The SOT tracer has no source transform to
    print; at level>0 it logs each compiled trace's op count through the
    same logger (the observable analog)."""
    import logging
    logger = logging.getLogger(_D2S_LOGGER_NAME + ".code")
    logger.setLevel(logging.DEBUG if level > 0 else logging.WARNING)
    if also_to_stdout and not logger.handlers:
        import sys
        logger.addHandler(logging.StreamHandler(sys.stdout))


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, full_graph=False, bucket_policy=None, **kwargs):
    """ref: python/paddle/jit/api.py to_static.

    full_graph=False (default, the reference's SOT mode): op-level tracer
    with graph breaks — data-dependent Python control flow works; breaks
    become guards, paths replay compiled, non-replayable traces (RNG /
    in-place mutation / inner backward) fall back to eager
    (see paddle_tpu.jit.sot).

    full_graph=True (the reference's AST mode): whole-program jax.jit —
    fastest when the function is fully traceable (no data-dependent
    control flow), with proper functionalization of Layer params/buffers
    and RNG.
    """
    def decorate(fn):
        if not _to_static_enabled:
            return fn
        if full_graph:
            return StaticFunction(fn, input_spec, **kwargs)
        from .sot import SOTFunction
        from ..nn.layer import Layer
        if isinstance(fn, Layer):
            # patch forward in place so the object keeps its Layer API
            # (parameters/train/eval/state_dict, jit.save) — the
            # reference's to_static(layer) likewise returns the layer
            # with a StaticFunction forward
            sot = SOTFunction(fn.forward, bucket_policy=bucket_policy,
                              input_spec=input_spec)
            fn.forward = sot
            return fn
        return SOTFunction(fn, bucket_policy=bucket_policy,
                           input_spec=input_spec)
    if function is not None:
        return decorate(function)
    return decorate


def not_to_static(fn=None):
    return fn


def ignore_module(modules):
    return None


class TrainStep:
    """Whole-training-step compiler: loss fwd + backward + optimizer update
    as ONE XLA executable (donated params/opt-state, so updates are
    in-place in HBM).

    Usage:
        step = TrainStep(model, loss_fn, optimizer)
        loss = step(x, y)          # tensors or numpy

    loss_fn(outputs, *labels) -> scalar Tensor.

    Since Fusion III this is a thin wrapper over the SOT whole-step
    capture engine (``jit.sot.CapturedStep`` in non-strict mode: an
    EXPLICIT whole-step API, so it always captures — no eager fallback,
    no kill switch, unknown clip objects run un-clipped inside the
    trace as before). ``hapi.Model.train_batch`` rides the same
    machinery in strict mode (gated, compile-on-second-sighting).
    Optimizer slot state now lives in ``optimizer._states`` (shared
    with the eager/fused paths), so ``state_dict()`` round-trips cover
    compiled training too.
    """

    def __init__(self, model, loss_fn, optimizer, donate=True):
        from .sot import CapturedStep
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self._step = CapturedStep(
            model, loss_fn, optimizer, cast_loss_f32=True,
            donate=donate, strict=False, name="train_step",
            build_kind="train_step")

    @staticmethod
    def _split(batch):
        if len(batch) > 1:
            return list(batch[:-1]), [batch[-1]]
        return list(batch), []

    def compile_stats(self, *batch):
        """Compile the step for these batch shapes without running it and
        return XLA's per-device memory analysis (same contract as
        DistTrainStep.compile_stats; bench emits it as peak_hbm_bytes)."""
        ins, lbls = self._split(batch)
        return self._step.compile_stats(ins, lbls)

    def __call__(self, *batch):
        ins, lbls = self._split(batch)
        return self._step.step(ins, lbls)


def save(layer, path, input_spec=None, **configs):
    """paddle.jit.save — persists params + the importable factory so load
    reconstructs a runnable Layer (ref: python/paddle/jit/api.py save /
    TranslatedLayer). Shares the .pdmodel format with
    paddle_tpu.inference.save_inference_model."""
    from ..inference import save_inference_model
    save_inference_model(path, layer, input_spec=input_spec)


class TranslatedLayer:
    """ref: jit/translated_layer.py TranslatedLayer — the Layer-like
    object jit.load returns when the saved model's Python class cannot
    be imported in this process: forward runs the artifact's
    AOT-exported (StableHLO) program with the saved params/buffers.
    Built lazily over inference.Predictor's AOT path; construction is
    via TranslatedLayer.load (or jit.load's fallback), matching the
    reference's 'not created by constructor' contract."""

    def __init__(self, predictor):
        self._predictor = predictor
        self.training = False

    @staticmethod
    def load(path):
        from ..inference import Config, Predictor
        return TranslatedLayer(Predictor(Config(path)))

    def forward(self, *inputs):
        import jax.numpy as jnp

        from ..core.tensor import Tensor
        outs = self._predictor.run(*inputs)
        outs = [Tensor(jnp.asarray(o)) for o in outs]
        return outs[0] if len(outs) == 1 else outs

    def __call__(self, *inputs):
        return self.forward(*inputs)

    def eval(self):
        self.training = False
        return self

    def train(self):
        raise RuntimeError(
            "TranslatedLayer wraps a compiled inference program; it "
            "cannot be put in train mode (re-train from the original "
            "Layer class)")


def load(path, **configs):
    """Returns a reconstructed Layer in eval mode (ref: jit.load →
    TranslatedLayer). If the artifact carries an AOT export and the
    original class is NOT importable here, a TranslatedLayer serves it
    instead. Legacy .pdparams artifacts (raw state-dicts, not
    reconstructable Layers) fail loudly with the right tool named."""
    import os

    from ..inference import load_inference_model
    if not os.path.exists(path + ".pdmodel") and \
            os.path.exists(path + ".pdparams"):
        raise ValueError(
            f"{path}.pdparams is a legacy weights-only artifact and "
            "cannot be reconstructed into a Layer; load it with "
            "paddle_tpu.load() and apply set_state_dict on your model")
    try:
        return load_inference_model(path)
    except (ImportError, AttributeError, ModuleNotFoundError) as e:
        from ..inference import _load
        payload = _load(path + ".pdmodel", return_numpy=False)
        if payload.get("aot"):
            return TranslatedLayer.load(path)
        raise ValueError(
            f"cannot reconstruct {payload.get('class_name')} ({e}) and "
            f"the artifact has no AOT export — re-save with "
            f"save_inference_model(aot=True) to serve without the "
            f"class") from e
