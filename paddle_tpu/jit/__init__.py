"""paddle.jit equivalent: one compilation path (trace -> StableHLO -> XLA).

ref: python/paddle/jit/{api.py,dy2static,sot}. The reference needs an AST
transpiler + bytecode tracer (SOT) because its eager semantics are op-by-op
C++ dispatch; here every op is already a pure JAX call on Tensor-held arrays,
so "to_static" is just functionalization + jax.jit — the design SURVEY.md §7
step 3 calls for (replacing eager engine + PirInterpreter + CINN with one
trace path).
"""
from .api import to_static, functionalize, TrainStep, save, load, not_to_static  # noqa: F401
from .api import ignore_module, TranslatedLayer, enable_to_static  # noqa: F401
from .api import set_code_level, set_verbosity  # noqa: F401
from .sot import sot_compile, SOTFunction, BucketPolicy  # noqa: F401
from .sot import capture, CapturedStep, capture_jit  # noqa: F401
from . import warmup  # noqa: F401  — hot start: executable cache + pre-warm
