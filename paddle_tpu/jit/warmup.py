"""Hot start: persistent executable cache + warm-bundle boot pre-warm.

Every compile cache in the framework — CapturedStep whole-step
programs, SOT segments, fusion-chain programs, fused optimizer steps,
the serving decode/prefill/spec executables — historically died with
the process, so a restarted trainer or a freshly rolled serving
replica paid full retrace+compile before its first useful step (the
~2.9ms vs ~305ms gap on the capture bench). This module closes that
gap in two layers:

- **Persistent executable cache** (``FLAGS_executable_cache_dir``):
  wires JAX's persistent compilation cache under every ``jax.jit`` the
  framework issues, so compiled XLA artifacts live on DISK keyed by
  program content — a restarted process re-traces (cheap Python) but
  never re-compiles a program any earlier process already built.
  :func:`ensure_executable_cache` is called from the compile-issuing
  seams (CapturedStep builds, ``capture_jit``, fusion programs, the
  fused optimizer step, ``jit.api`` builds, inference predictors) and
  from ``paddle_tpu`` import, so enabling the flag — by env var before
  boot or ``set_flags`` at runtime — covers everything after it.
  Counters ``executable_cache.{hits,misses,writes}_total`` are
  installed ONLY when the flag is set; the flags-off path is one
  string compare.

- **Warm bundle + boot pre-warm** (``FLAGS_warmup_bundle``): the
  compile-issuing seams also :func:`note_program` the signature of
  every program a run actually built (the guard tuples CapturedStep
  computes, the serving engines' program geometry).
  :func:`export_bundle` writes them as a versioned JSON manifest
  beside the XLA cache dir; :func:`prewarm` replays a bundle at boot
  through the AOT seams (abstract args -> ``lower().compile()``), so
  a replica is 100%-cache-hit — disk reads, zero fresh XLA compiles —
  before it admits its first request. ``Model.prepare(warm_bundle=)``
  and ``inference.serve(warm_bundle=)`` both take a bundle (path or
  loaded dict); a truncated/corrupt bundle or an unreplayable entry
  degrades to cold compile with a counted
  ``warmup.failures_total{reason}`` — pre-warm failure is never a
  boot failure.

Fault-injection site: ``warmup.write`` (the bundle writer, same
truncated-write contract as ``checkpoint.write``).
"""
from __future__ import annotations

import json
import os
import stat as _stat
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional

from ..core.flags import (_registry as _flag_registry, define_flag,
                          flag_value)
from ..observability import flight as _flight
from ..observability import metrics as _om
from ..utils import fault_injection as _fi

__all__ = ["ensure_executable_cache", "cache_stats", "note_program",
           "recorded", "clear_recorded", "export_bundle", "load_bundle",
           "prewarm", "gc_cache_dir", "BUNDLE_VERSION"]

define_flag(
    "executable_cache_dir", "",
    "Directory for JAX's persistent compilation cache: every jax.jit "
    "the framework issues (captured steps, SOT segments, fusion "
    "programs, fused optimizer steps, serving decode/prefill/spec "
    "executables) writes/reads disk-backed compiled artifacts there, "
    "so a restarted process re-traces but does not re-compile. Empty "
    "(default) = off. Counters executable_cache.{hits,misses,writes}"
    "_total are live only while enabled")
define_flag(
    "warmup_bundle", "",
    "Default warm-bundle manifest path for boot pre-warm: consumers "
    "that take warm_bundle= (Model.prepare, inference.serve, "
    "warmup.prewarm) fall back to this path when none is passed. "
    "Empty (default) = no automatic pre-warm")
define_flag(
    "executable_cache_gc_days", 0,
    "Age-based GC of the persistent executable cache dir: entries "
    "whose last hit (atime, falling back to mtime) is older than "
    "this many days are evicted — counted "
    "executable_cache.evicted_total — opportunistically whenever "
    "ensure_executable_cache (re)configures the cache, or explicitly "
    "via warmup.gc_cache_dir(). 0 (default) = never evict")

_dir_flag = _flag_registry["executable_cache_dir"]
_bundle_flag = _flag_registry["warmup_bundle"]

BUNDLE_VERSION = 1
_BUNDLE_KEY = "__paddle_tpu_warm_bundle__"
_MAX_RECORDED = 512

_M = _om.scope("executable_cache")
_M_hits = _M.counter(
    "hits_total",
    "Compiles served from the persistent executable cache (disk "
    "artifact reused; no XLA compile ran)")
_M_misses = _M.counter(
    "misses_total",
    "Compiles that missed the persistent executable cache (fresh XLA "
    "compile; corrupt/unreadable entries count here too)")
_M_writes = _M.counter(
    "writes_total",
    "Compiled executables written into the persistent cache dir")
_M_evicted = _M.counter(
    "evicted_total",
    "Persistent-cache entries evicted by last-hit age "
    "(FLAGS_executable_cache_gc_days / warmup.gc_cache_dir)")
_W = _om.scope("warmup")
_M_programs = _W.counter(
    "programs_total",
    "Programs successfully pre-warmed from a warm bundle at boot")
_M_failures = _W.counter(
    "failures_total",
    "Warm-bundle failures by reason (missing/corrupt/version/program) "
    "— every one degrades to cold compile, never a boot failure")

# enable-once state: the configured dir (None = cache off) and whether
# the counting wrappers are installed (they stay installed; the flag
# re-check inside them is not needed because a disabled cache never
# reaches the wrapped functions)
_state: Dict[str, Any] = {"dir": None, "wrapped": False}


def ensure_executable_cache() -> bool:
    """Configure JAX's persistent compilation cache from
    ``FLAGS_executable_cache_dir``; returns True while enabled. Called
    from every compile-issuing seam (and ``paddle_tpu`` import) — the
    flags-off path is one cached flag read + string compare. Flipping
    the flag at runtime reconfigures on the next compile."""
    d = str(_dir_flag.value or "").strip() or None
    if _state["dir"] == d:
        return d is not None
    import jax
    from jax._src import compilation_cache as _cc
    if d is None:
        jax.config.update("jax_compilation_cache_dir", None)
    else:
        os.makedirs(d, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", d)
        # cache EVERY program: the framework's small per-step/decode
        # executables are exactly what a restarted replica re-pays, and
        # jax's defaults (>=1s compile time) would skip all of them
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                          -1)
        if not _state["wrapped"]:
            _install_counters(_cc)
            _state["wrapped"] = True
    try:
        # clear the checked-once latch: a compile that ran BEFORE the
        # flag was set (model init) must not pin the cache off forever
        _cc.reset_cache()
    except Exception:  # noqa: BLE001 — cache config is best-effort
        pass
    _state["dir"] = d
    _flight.record("warmup", "cache_configured", dir=d or "<off>")
    if d is not None:
        # opportunistic age GC: reconfiguration is the natural "a
        # replica just booted against this dir" moment, and it is
        # cold-path (the checked-once latch above guards the hot one)
        try:
            gc_cache_dir(directory=d)
        except Exception:  # noqa: BLE001 — GC must never block boot
            pass
    return d is not None


def gc_cache_dir(max_age_days: Optional[float] = None,
                 directory: Optional[str] = None) -> int:
    """Evict persistent-executable-cache entries by LAST-HIT age: a
    regular file in the cache dir whose newest of (atime, mtime) is
    older than ``max_age_days`` (default
    ``FLAGS_executable_cache_gc_days``; <= 0 disables) is removed and
    counted into ``executable_cache.evicted_total``. Warm-bundle
    manifests (``*.json``) and subdirectories are never touched — only
    the XLA cache's opaque artifact files age out. Returns the evicted
    count; all I/O errors degrade to keeping the entry."""
    if max_age_days is None:
        max_age_days = flag_value("executable_cache_gc_days")
    try:
        age = float(max_age_days)
    except (TypeError, ValueError):
        return 0
    d = directory or (str(_dir_flag.value or "").strip() or None)
    if not d or age <= 0:
        return 0
    cutoff = time.time() - age * 86400.0
    try:
        names = os.listdir(d)
    except OSError:
        return 0
    removed = 0
    for name in names:
        if name.endswith(".json"):
            continue  # warm bundles are manifests, not cache entries
        path = os.path.join(d, name)
        try:
            st = os.stat(path)
            if not _stat.S_ISREG(st.st_mode):
                continue
            if max(st.st_atime, st.st_mtime) < cutoff:
                os.remove(path)
                removed += 1
        except OSError:
            continue  # raced/unreadable: keep it, try next boot
    if removed:
        _M_evicted.inc(removed)
        _flight.record("warmup", "cache_gc", dir=os.path.basename(d),
                       evicted=removed, max_age_days=age)
    return removed


def _install_counters(_cc) -> None:
    """Count hits/misses/writes precisely by wrapping the persistent
    cache's get/put seam (jax emits no write/miss monitoring events).
    A corrupt entry raising on read counts as a miss — jax's caller
    already degrades it to a fresh compile."""
    orig_get = _cc.get_executable_and_time
    orig_put = _cc.put_executable_and_time

    def counted_get(*a, **k):
        try:
            executable, t = orig_get(*a, **k)
        except Exception:
            _M_misses.inc()
            raise
        (_M_hits if executable is not None else _M_misses).inc()
        return executable, t

    def counted_put(*a, **k):
        out = orig_put(*a, **k)
        _M_writes.inc()
        return out

    _cc.get_executable_and_time = counted_get
    _cc.put_executable_and_time = counted_put


def cache_stats() -> Dict[str, int]:
    """{hits, misses, writes} of the persistent executable cache."""
    return {"hits": int(_M_hits.value()),
            "misses": int(_M_misses.value()),
            "writes": int(_M_writes.value())}


# ---------------------------------------------------------------------------
# signature <-> JSON: CapturedStep signatures are nested tuples of
# hashable scalars; JSON round-trips them as nested lists, so a deep
# list->tuple conversion restores the exact tuple
# ---------------------------------------------------------------------------

def sig_to_json(sig):
    if isinstance(sig, tuple):
        return [sig_to_json(v) for v in sig]
    return sig


def sig_from_json(obj):
    if isinstance(obj, list):
        return tuple(sig_from_json(v) for v in obj)
    return obj


# ---------------------------------------------------------------------------
# recording: what did this run actually compile?
# ---------------------------------------------------------------------------

# insertion-ordered, key = canonical JSON of the entry (dedup), bounded;
# compile seams on worker threads (serving loops) record concurrently
_recorded: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()


def _recorded_lock():
    from ..analysis.locks import make_lock
    return make_lock("jit.warmup.recorded")


_rec_lock = _recorded_lock()


def note_program(kind: str, name: str, entry: Dict[str, Any]) -> None:
    """Record one compiled program's replayable signature (called from
    the compile seams — compile events are rare and slow, so this is
    never hot-path cost). Non-JSON-serializable entries drop their
    ``sig`` first, then are skipped entirely — recording is
    best-effort, the disk cache alone already guarantees no fresh
    compiles on restart."""
    entry = dict(entry)
    entry["kind"] = kind
    entry["name"] = name
    try:
        key = json.dumps(entry, sort_keys=True)
    except (TypeError, ValueError):
        entry.pop("sig", None)
        try:
            key = json.dumps(entry, sort_keys=True)
        except (TypeError, ValueError):
            return
    with _rec_lock:
        if key in _recorded:
            return
        _recorded[key] = entry
        while len(_recorded) > _MAX_RECORDED:
            _recorded.popitem(last=False)


def recorded() -> List[Dict[str, Any]]:
    with _rec_lock:
        return [dict(e) for e in _recorded.values()]


def clear_recorded() -> None:
    with _rec_lock:
        _recorded.clear()


# ---------------------------------------------------------------------------
# bundle export / load
# ---------------------------------------------------------------------------

def _default_bundle_path() -> Optional[str]:
    p = str(_bundle_flag.value or "").strip()
    if p:
        return p
    d = str(_dir_flag.value or "").strip()
    if d:
        return os.path.join(d, "warm_bundle.json")
    return None


def export_bundle(path: Optional[str] = None) -> str:
    """Write the recorded program signatures as a versioned JSON
    manifest (default: ``<FLAGS_executable_cache_dir>/warm_bundle.json``
    — beside the XLA cache dir it indexes). Atomic write-then-rename
    through the ``warmup.write`` fault-injection site; a kill/truncate
    mid-write leaves no (partial) bundle behind."""
    import jax
    path = path or _default_bundle_path()
    if not path:
        raise ValueError(
            "export_bundle needs a path (or FLAGS_executable_cache_dir/"
            "FLAGS_warmup_bundle to derive one)")
    bundle = {_BUNDLE_KEY: BUNDLE_VERSION,
              "jax": jax.__version__,
              "entries": recorded()}
    blob = json.dumps(bundle, sort_keys=True, indent=1).encode()
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            _fi.write_bytes("warmup.write", f, blob)
            f.flush()
        os.replace(tmp, path)
    except Exception:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
    _flight.record("warmup", "bundle_exported", path=os.path.basename(path),
                   entries=len(bundle["entries"]))
    return path


def _fail(reason: str, **attrs) -> None:
    _M_failures.inc(reason=reason)
    _flight.record("warmup", "bundle_failed", reason=reason, **attrs)


def load_bundle(path: Optional[str] = None) -> Optional[Dict[str, Any]]:
    """Load a warm-bundle manifest; ``None`` (with a counted
    ``warmup.failures_total{reason}``) for anything unusable —
    missing, truncated, corrupt, or a version this build does not
    understand. The cold path is the fallback, never a crash."""
    path = path or _default_bundle_path()
    if not path:
        return None
    base = os.path.basename(path)
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError:
        _fail("missing", path=base)
        return None
    try:
        bundle = json.loads(raw.decode("utf-8"))
    except (ValueError, UnicodeDecodeError):
        _fail("corrupt", path=base)
        return None
    if not isinstance(bundle, dict) or \
            not isinstance(bundle.get("entries"), list):
        _fail("corrupt", path=base)
        return None
    version = bundle.get(_BUNDLE_KEY)
    if not isinstance(version, int) or version > BUNDLE_VERSION:
        _fail("version", path=base, version=str(version))
        return None
    return bundle


# ---------------------------------------------------------------------------
# boot pre-warm
# ---------------------------------------------------------------------------

def prewarm(bundle=None, captured=None, engine=None) -> Dict[str, int]:
    """Replay a warm bundle's recorded programs at boot through the AOT
    seams (abstract args -> ``lower().compile()``), so the process is
    100%-persistent-cache-hit before its first real step/request.

    ``bundle``: a loaded bundle dict, a manifest path, or None (the
    ``FLAGS_warmup_bundle`` / cache-dir default). ``captured``: a
    ``CapturedStep`` (or ``jit.TrainStep``) to replay
    ``captured_step`` entries into. ``engine``: a serving decode
    engine to replay ``serving`` entries into. Entries without a
    matching target are skipped; every per-entry failure is counted
    (``warmup.failures_total{reason=program}``) and pre-warm
    continues — this function never raises for bundle content."""
    if bundle is None or isinstance(bundle, str):
        bundle = load_bundle(bundle)
    out = {"programs": 0, "failures": 0, "skipped": 0}
    if not bundle:
        return out
    ensure_executable_cache()
    step_target = getattr(captured, "_step", captured)
    for entry in bundle.get("entries", []):
        if not isinstance(entry, dict):
            out["skipped"] += 1
            continue
        kind = entry.get("kind")
        try:
            if kind == "captured_step" and step_target is not None:
                step_target.prewarm(entry)
                out["programs"] += 1
            elif kind == "serving" and engine is not None:
                res = engine._prewarm_entry(entry)
                if res == "stale":
                    # bundle written by a DIFFERENTLY-configured
                    # replica (slots/blocks/buckets/spec_k): replaying
                    # would compile fresh programs at boot while
                    # claiming warmth — degrade instead, counted
                    out["failures"] += 1
                    _M_failures.inc(reason="stale")
                    _flight.record("warmup", "bundle_failed",
                                   reason="stale",
                                   fn=str(entry.get("name", "")))
                elif res:
                    out["programs"] += 1
                else:
                    out["skipped"] += 1
            else:
                out["skipped"] += 1
        except Exception as e:  # noqa: BLE001 — degrade to cold compile
            out["failures"] += 1
            _M_failures.inc(reason="program")
            _flight.record("warmup", "program_failed",
                           fn=str(entry.get("name", "")),
                           error=type(e).__name__)
    if out["programs"]:
        _M_programs.inc(out["programs"])
    _flight.record("warmup", "prewarm", **out)
    return out
