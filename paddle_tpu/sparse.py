"""paddle.sparse equivalent: COO/CSR tensors over jax.experimental.sparse.

ref: python/paddle/sparse/ (creation.py sparse_coo_tensor/sparse_csr_tensor,
unary/binary ops, nn.functional) + phi/core/sparse_coo_tensor.h. The BCOO
format is XLA's sparse representation; matmul/elementwise dispatch through
it, densifying where the TPU path prefers dense compute (small nnz ratio
decisions belong to the caller, as in the reference).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from .core.autograd import apply_op
from .core.tensor import Tensor

__all__ = [
    "sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor",
    "is_same_shape", "add", "multiply", "matmul", "masked_matmul", "relu",
]


class SparseCooTensor(Tensor):
    """Tensor whose _data is a BCOO array (ref: sparse_coo_tensor.h:49 —
    indices + values + dims). Dense Tensor methods that densify go through
    .to_dense()."""

    @property
    def nnz(self):
        return int(self._data.nse)

    def indices(self):
        return Tensor(jnp.swapaxes(self._data.indices, 0, 1))

    def values(self):
        return Tensor(self._data.data)

    def to_dense(self):
        return apply_op(lambda d: d.todense(), self, op_name="coo_to_dense")

    def is_sparse(self):
        return True

    def is_sparse_coo(self):
        return True


def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      place=None, stop_gradient=True):
    """ref: sparse/creation.py sparse_coo_tensor(indices [ndim, nnz],
    values [nnz])."""
    idx = np.asarray(indices._data if isinstance(indices, Tensor)
                     else indices)
    val = values._data if isinstance(values, Tensor) else jnp.asarray(
        np.asarray(values))
    if dtype is not None:
        val = val.astype(dtype)
    if shape is None:
        shape = tuple(int(m) + 1 for m in idx.max(axis=1))
    coo = jsparse.BCOO((val, jnp.asarray(idx.T)), shape=tuple(shape))
    return SparseCooTensor(coo, stop_gradient=stop_gradient)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      place=None, stop_gradient=True):
    """ref: sparse/creation.py sparse_csr_tensor — stored as BCOO
    internally (csr -> coo expansion), same API surface."""
    crows_np = np.asarray(crows._data if isinstance(crows, Tensor)
                          else crows)
    cols_np = np.asarray(cols._data if isinstance(cols, Tensor) else cols)
    rows = np.repeat(np.arange(len(crows_np) - 1),
                     np.diff(crows_np))
    idx = np.stack([rows, cols_np])
    return sparse_coo_tensor(idx, values, shape, dtype,
                             stop_gradient=stop_gradient)


def is_same_shape(x, y) -> bool:
    return tuple(x.shape) == tuple(y.shape)


def _coo(x):
    if isinstance(x, SparseCooTensor):
        return x
    raise TypeError(f"expected SparseCooTensor, got {type(x).__name__}")


def add(x, y):
    """ref: sparse/binary.py add."""
    def f(a, b):
        return (a.todense() if isinstance(a, jsparse.BCOO) else a) + \
               (b.todense() if isinstance(b, jsparse.BCOO) else b)
    out = apply_op(f, x, y, op_name="sparse_add")
    return out


def multiply(x, y):
    def f(a, b):
        return (a.todense() if isinstance(a, jsparse.BCOO) else a) * \
               (b.todense() if isinstance(b, jsparse.BCOO) else b)
    return apply_op(f, x, y, op_name="sparse_multiply")


def matmul(x, y):
    """Sparse @ dense (ref: sparse/matmul.py) — BCOO dot_general keeps the
    sparse operand sparse through XLA."""
    def f(a, b):
        if isinstance(a, jsparse.BCOO):
            return jsparse.bcoo_dot_general(
                a, b, dimension_numbers=(([a.ndim - 1], [0]), ([], [])))
        return a @ b
    return apply_op(f, x, y, op_name="sparse_matmul")


def masked_matmul(x, y, mask):
    """Dense @ dense with sparse output mask (ref: sparse/matmul.py
    masked_matmul)."""
    def f(a, b, m):
        dense = a @ b
        return jnp.where(m.todense() != 0, dense, 0.0)
    return apply_op(f, x, y, mask, op_name="masked_matmul")


def relu(x):
    def f(a):
        if isinstance(a, jsparse.BCOO):
            return jsparse.BCOO((jax.nn.relu(a.data), a.indices),
                                shape=a.shape)
        return jax.nn.relu(a)
    out = apply_op(f, x, op_name="sparse_relu")
    if isinstance(x, SparseCooTensor):
        out = SparseCooTensor(out._data, stop_gradient=out.stop_gradient,
                              node=out._node, out_index=out._out_index)
    return out
