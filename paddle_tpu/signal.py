"""paddle.signal equivalent: STFT / iSTFT.

ref: python/paddle/signal.py (stft :153, istft :310, frame :27,
overlap_add :101) — frame + window + FFT composition, built on jnp so it
lowers to XLA FFT kernels. One framing-index helper and one vectorized
scatter-add reconstruction are shared by frame/overlap_add/stft/istft.
"""
from __future__ import annotations

import jax.numpy as jnp

from .core.autograd import apply_op
from .core.tensor import Tensor

__all__ = ["stft", "istft", "frame", "overlap_add"]


def _frame_idx(n: int, frame_length: int, hop_length: int):
    """[num_frames, frame_length] gather indices."""
    num = 1 + (n - frame_length) // hop_length
    return (jnp.arange(frame_length)[None, :] +
            hop_length * jnp.arange(num)[:, None])


def _overlap_add_last(frames, hop_length: int):
    """frames [..., frame_length, num] -> [..., out_len] via ONE
    scatter-add (duplicate indices sum)."""
    frame_length, num = frames.shape[-2], frames.shape[-1]
    out_len = frame_length + hop_length * (num - 1)
    idx = _frame_idx(out_len, frame_length, hop_length)  # [num, fl]
    flat = jnp.moveaxis(frames, -1, -2)                  # [..., num, fl]
    flat = flat.reshape(frames.shape[:-2] + (num * frame_length,))
    out = jnp.zeros(frames.shape[:-2] + (out_len,), frames.dtype)
    return out.at[..., idx.reshape(-1)].add(flat)


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """ref: signal.py:27. axis=-1: [..., frame_length, num_frames];
    axis=0: [num_frames, frame_length, ...]."""
    if axis not in (0, -1):
        raise ValueError("frame: axis must be 0 or -1")

    def impl(a):
        if axis == 0:
            idx = _frame_idx(a.shape[0], frame_length, hop_length)
            return a[idx]                      # [num, frame_length, ...]
        idx = _frame_idx(a.shape[-1], frame_length, hop_length)
        return jnp.moveaxis(a[..., idx], -2, -1)  # [..., fl, num]
    return apply_op(impl, x, op_name="frame")


def overlap_add(x, hop_length, axis=-1, name=None):
    """ref: signal.py:101. Inverse of frame for the same axis convention."""
    if axis not in (0, -1):
        raise ValueError("overlap_add: axis must be 0 or -1")

    def impl(a):
        if axis == 0:                          # [num, frame_length, ...]
            moved = jnp.moveaxis(jnp.moveaxis(a, 0, -1), 0, -2)
            out = _overlap_add_last(moved, hop_length)
            return jnp.moveaxis(out, -1, 0)
        return _overlap_add_last(a, hop_length)
    return apply_op(impl, x, op_name="overlap_add")


def _full_window(window, n_fft: int, win_length: int, dtype):
    """Center a win_length window inside n_fft; window=None means a
    rectangular window of win_length samples (NOT n_fft — ref contract)."""
    w = jnp.ones((win_length,), dtype) if window is None \
        else window.astype(dtype)
    wfull = jnp.zeros((n_fft,), dtype)
    off = (n_fft - win_length) // 2
    return wfull.at[off:off + win_length].set(w)


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False, onesided=True,
         name=None):
    """ref: signal.py:153. x: [B, T] or [T] real -> complex spectrogram
    [B, n_fft//2+1, num_frames] (onesided)."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft

    def impl(a, w):
        squeeze = a.ndim == 1
        if squeeze:
            a = a[None]
        if center:
            pad = n_fft // 2
            a = jnp.pad(a, [(0, 0), (pad, pad)], mode=pad_mode)
        idx = _frame_idx(a.shape[-1], n_fft, hop_length)
        frames = a[:, idx]                      # [B, num, n_fft]
        frames = frames * _full_window(w, n_fft, win_length,
                                       a.dtype)[None, None, :]
        spec = jnp.fft.rfft(frames, axis=-1) if onesided else \
            jnp.fft.fft(frames, axis=-1)
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        spec = jnp.swapaxes(spec, -2, -1)       # [B, freq, num]
        return spec[0] if squeeze else spec

    w = window._data if isinstance(window, Tensor) else window
    return apply_op(lambda a: impl(a, w), x, op_name="stft")


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    """ref: signal.py:310. Inverse via one vectorized overlap-add with
    window-square normalization. return_complex requires onesided=False
    and keeps the imaginary part."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if return_complex and onesided:
        raise ValueError(
            "istft: return_complex requires onesided=False (ref contract)")

    def impl(spec, w):
        squeeze = spec.ndim == 2
        if squeeze:
            spec = spec[None]
        spec = jnp.swapaxes(spec, -2, -1)       # [B, num, freq]
        if normalized:
            spec = spec * jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
        if onesided:
            frames = jnp.fft.irfft(spec, n=n_fft, axis=-1)
        else:
            frames = jnp.fft.ifft(spec, axis=-1)
            if not return_complex:
                frames = frames.real
        wfull = _full_window(
            w, n_fft, win_length,
            frames.real.dtype if jnp.iscomplexobj(frames) else frames.dtype)
        frames = frames * wfull[None, None, :]
        num = frames.shape[1]
        out = _overlap_add_last(jnp.moveaxis(frames, 1, -1), hop_length)
        norm = _overlap_add_last(
            jnp.broadcast_to((wfull * wfull)[:, None], (n_fft, num)),
            hop_length)
        out = out / jnp.maximum(norm, 1e-11)[None, :]
        out_len = n_fft + hop_length * (num - 1)
        if center:
            pad = n_fft // 2
            out = out[:, pad:out_len - pad]
        if length is not None:
            out = out[:, :length]
        return out[0] if squeeze else out

    w = window._data if isinstance(window, Tensor) else window
    return apply_op(lambda a: impl(a, w), x, op_name="istft")
