"""paddle.onnx equivalent (ref: python/paddle/onnx/__init__.py).

The reference's export delegates to the external ``paddle2onnx``
package and raises if it's missing; this build mirrors that contract.
The TPU-native serialized format is paddle_tpu.jit.save /
inference.save_inference_model (StableHLO AOT artifacts), which serve
the deployment role ONNX plays in the reference stack.
"""
from __future__ import annotations

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, **configs):
    """ref: onnx/export.py export — requires paddle2onnx, exactly as
    the reference does."""
    try:
        import paddle2onnx  # noqa: F401
    except ImportError as e:
        raise ImportError(
            "paddle.onnx.export requires the paddle2onnx package "
            "(unavailable in this build). For a deployable serialized "
            "model use paddle_tpu.jit.save or "
            "paddle_tpu.inference.save_inference_model(aot=True) — the "
            "StableHLO artifact serves without the model class "
            "importable.") from e
    raise NotImplementedError(
        "paddle2onnx found, but ONNX emission from the TPU build's "
        "StableHLO programs is not implemented")
