"""Attention functionals.

ref: python/paddle/nn/functional/flash_attention.py (flash_attention,
scaled_dot_product_attention). On TPU the fused path is the Pallas flash
kernel (paddle_tpu.ops.pallas.flash_attention); the reference implementation
here is plain jnp, used on CPU and as the numeric oracle in tests.
"""
from __future__ import annotations


import jax
import jax.numpy as jnp

from ...core.autograd import apply_op
from ...core.tensor import Tensor
from ...core import random as random_mod


def _sdpa_reference(q, k, v, mask=None, causal=False, scale=None,
                    dropout_p=0.0, dropout_key=None):
    """Thin delegate to the single sdpa oracle in ops.pallas.flash_attention
    (one copy of the softmax+dropout algebra to keep in sync)."""
    from ...ops.pallas.flash_attention import _sdpa_xla
    m = mask.astype(jnp.float32) if mask is not None else None
    return _sdpa_xla(q, k, v, causal=causal, scale=scale, mask=m,
                     dropout_p=dropout_p, dropout_key=dropout_key)


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, name=None):
    """Layout [batch, seq, heads, head_dim], matching the reference API."""
    md = attn_mask._data if isinstance(attn_mask, Tensor) else attn_mask
    drop = dropout_p if training else 0.0

    if _should_use_flash(query) and md is None and drop < 1.0:
        from ...ops.pallas.flash_attention import flash_attention_fwd
        if drop > 0.0:
            # the key rides as a marked arg (same contract as F.dropout)
            # so static Program replay refills a FRESH key per run — a
            # closure-captured seed would freeze the mask across runs.
            # Under jit the key is traced off the step key per step.
            from .common import _rng_key_tensor
            key_t = _rng_key_tensor()

            def f(q, k, v, rng_key):
                return flash_attention_fwd(
                    q, k, v, causal=is_causal, dropout_p=float(drop),
                    seed=random_mod.derive_seed(rng_key))
            return apply_op(f, query, key, value, key_t,
                            op_name="flash_attention")
        return apply_op(
            lambda q, k, v: flash_attention_fwd(q, k, v, causal=is_causal),
            query, key, value, op_name="flash_attention")

    if drop > 0.0:
        # same marked-arg contract as the flash path: a closure-captured
        # key would freeze the dropout mask across compiled steps and
        # static replays
        from .common import _rng_key_tensor
        key_t = _rng_key_tensor()

        def f_drop(q, k, v, rng_key):
            return _sdpa_reference(q, k, v, mask=md, causal=is_causal,
                                   dropout_p=drop, dropout_key=rng_key)
        return apply_op(f_drop, query, key, value, key_t, op_name="sdpa")

    def f(q, k, v):
        return _sdpa_reference(q, k, v, mask=md, causal=is_causal)
    return apply_op(f, query, key, value, op_name="sdpa")


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None,
                    rng_name="", training=True, name=None):
    """ref: nn/functional/flash_attention.py flash_attention — same
    signature; returns (out, softmax-or-None) tuple for parity."""
    out = scaled_dot_product_attention(query, key, value, None, dropout,
                                       causal, training)
    return out, None


def flash_attn_qkvpacked(qkv, dropout=0.0, causal=False,
                         return_softmax=False, fixed_seed_offset=None,
                         rng_name="", training=True, name=None):
    """ref: nn/functional/flash_attention.py flash_attn_qkvpacked —
    qkv [B, L, 3, H, D]."""
    def f(p):
        return p[:, :, 0], p[:, :, 1], p[:, :, 2]
    q, k, v = apply_op(f, qkv, op_name="qkv_unpack")
    out, sm = flash_attention(q, k, v, dropout=dropout, causal=causal,
                              return_softmax=return_softmax,
                              training=training)
    return out, sm


def flash_attn_varlen_qkvpacked(qkv, cu_seqlens_q, cu_seqlens_k,
                                max_seqlen_q, max_seqlen_k, scale,
                                dropout=0.0, causal=False,
                                return_softmax=False,
                                fixed_seed_offset=None, rng_name="",
                                varlen_padded=True, training=True,
                                name=None):
    """Varlen packed attention: sequences packed along dim 0, delimited by
    cu_seqlens; attention never crosses a sequence boundary.

    ref: python/paddle/nn/functional/flash_attention.py:792. TPU-native:
    cu_seqlens become per-token segment ids fed to the segment-masked
    Pallas flash kernel (paddle_tpu.ops.pallas.flash_attention,
    flash_attention_segmented) — tiles where seg_q != seg_k contribute
    nothing, so packing costs no extra FLOPs materialization.
    qkv: [total_tokens, 3, H, D]; returns [total_tokens, H, D].

    For packed qkv the q and k boundaries coincide, so segment ids derive
    from cu_seqlens_q alone; max_seqlen_q/k and varlen_padded are accepted
    for signature parity but unused (the segment mask makes them moot).
    A cu_seqlens_k that differs from cu_seqlens_q is rejected — silently
    masking with q boundaries would be wrong for that caller.
    """
    from ...ops.pallas.flash_attention import flash_attention_segmented

    if cu_seqlens_k is not None and cu_seqlens_k is not cu_seqlens_q:
        import jax as _jax
        import numpy as _np
        cq = (cu_seqlens_q._data if hasattr(cu_seqlens_q, "_data")
              else cu_seqlens_q)
        ck = (cu_seqlens_k._data if hasattr(cu_seqlens_k, "_data")
              else cu_seqlens_k)
        # traced values can't be compared on the host — trust the caller
        # under jit (eager use, the common path, is still validated)
        if not (isinstance(cq, _jax.core.Tracer)
                or isinstance(ck, _jax.core.Tracer)):
            cq, ck = _np.asarray(cq), _np.asarray(ck)
            if cq.shape != ck.shape or (cq != ck).any():
                raise ValueError(
                    "flash_attn_varlen_qkvpacked: cu_seqlens_k differs "
                    "from cu_seqlens_q, but packed qkv shares one set of "
                    "sequence boundaries — masking would be wrong. Use "
                    "the unpacked varlen API for cross-attention layouts.")

    def f(p, cu_arr):
        total = p.shape[0]
        # segment id per token: number of boundaries at or before it
        seg = jnp.searchsorted(cu_arr[1:], jnp.arange(total), side="right")
        q, k, v = p[:, 0], p[:, 1], p[:, 2]     # [total, H, D]
        out = flash_attention_segmented(
            q[None], k[None], v[None], seg[None].astype(jnp.int32),
            causal, scale)
        return out[0]

    out = apply_op(f, qkv, cu_seqlens_q, op_name="flash_attn_varlen")
    return out, None


def flashmask_attention(query, key, value, startend_row_indices,
                        dropout=0.0, causal=False, window_size=None,
                        return_softmax_lse=False, return_seed_offset=False,
                        fixed_seed_offset=None, rng_name="", training=True,
                        name=None):
    """FlashMask: column-wise sparse mask representation.

    ref: python/paddle/nn/functional/flash_attention.py:1098
    flashmask_attention. startend_row_indices [B, H|1, Lk, C]:
      C=1 (causal): rows >= LTS masked;
      C=2 (causal): rows in [LTS, LTE) masked;
      C=2 (non-causal): rows >= LTS and rows < UTE masked;
      C=4: rows in [LTS, LTE) or [UTS, UTE) masked.
    TPU-native fallback expands the column encoding to an additive mask
    under jit (XLA fuses it into the attention); the Pallas tile-skip
    path is future work tracked with the sparse-attention kernel.
    """
    def f(q, k, v, se):
        lq, lk = q.shape[1], k.shape[1]
        rows = jnp.arange(lq).reshape(1, 1, lq, 1)   # i (query/row)
        cols = jnp.arange(lk).reshape(1, 1, 1, lk)   # j (key/col)
        se = se.astype(jnp.int32)                     # [B, H1, Lk, C]
        c = se.shape[-1]
        lts = se[..., 0][:, :, None, :]               # [B, H1, 1, Lk]
        if causal:
            if c == 1:
                masked = rows >= lts
            elif c == 2:
                lte = se[..., 1][:, :, None, :]
                masked = (rows >= lts) & (rows < lte)
            else:
                raise ValueError(
                    f"causal flashmask expects 1 or 2 columns, got {c}")
        else:
            if c == 2:
                ute = se[..., 1][:, :, None, :]
                masked = (rows >= lts) | (rows < ute)
            elif c == 4:
                lte = se[..., 1][:, :, None, :]
                uts = se[..., 2][:, :, None, :]
                ute = se[..., 3][:, :, None, :]
                masked = ((rows >= lts) & (rows < lte)) | \
                         ((rows >= uts) & (rows < ute))
            else:
                raise ValueError(
                    f"non-causal flashmask expects 2 or 4 columns, got {c}")
        if window_size is not None:
            # sliding window (left, right): only keys within
            # [i - left, i + right] may attend
            left, right = (window_size if isinstance(window_size,
                                                     (tuple, list))
                           else (window_size, window_size))
            masked = masked | (cols < rows - int(left)) | \
                (cols > rows + int(right))
        mask = jnp.where(masked, -1e30, 0.0).astype(jnp.float32)
        return _sdpa_reference(q, k, v, mask=mask, causal=causal)

    out = apply_op(f, query, key, value, startend_row_indices,
                   op_name="flashmask_attention")
    if return_softmax_lse or return_seed_offset:
        extras = [None] * (int(return_softmax_lse) +
                           int(return_seed_offset))
        return (out, *extras)
    return out


def _should_use_flash(q) -> bool:
    """True when the attention should route to the Pallas flash kernel.
    Traced values (inside jit/TrainStep) carry no devices — fall back to
    the default backend, NOT False: a compiled step on TPU must still
    take the fused path (this was exactly the BERT slow-path bug)."""
    import jax as _jax
    data = q._data if isinstance(q, Tensor) else q
    try:
        plats = {d.platform for d in data.devices()}
    except Exception:
        plats = set()
    if not plats:
        plats = {_jax.default_backend()}
    return any(p in ("tpu", "axon") for p in plats)
