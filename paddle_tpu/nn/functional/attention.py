"""Attention functionals.

ref: python/paddle/nn/functional/flash_attention.py (flash_attention,
scaled_dot_product_attention). On TPU the fused path is the Pallas flash
kernel (paddle_tpu.ops.pallas.flash_attention); the reference implementation
here is plain jnp, used on CPU and as the numeric oracle in tests.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...core.autograd import apply_op
from ...core.tensor import Tensor
from ...core import random as random_mod


def _sdpa_reference(q, k, v, mask=None, causal=False, scale=None,
                    dropout_p=0.0, dropout_key=None):
    # q,k,v: [B, L, H, D] (paddle flash-attention layout)
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    qt = jnp.swapaxes(q, 1, 2)  # [B, H, L, D]
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    logits = jnp.einsum("bhqd,bhkd->bhqk", qt, kt).astype(jnp.float32) * s
    if causal:
        ql, kl = logits.shape[-2], logits.shape[-1]
        cm = jnp.tril(jnp.ones((ql, kl), bool), k=kl - ql)
        logits = jnp.where(cm, logits, -1e30)
    if mask is not None:
        logits = logits + mask.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    if dropout_p > 0.0 and dropout_key is not None:
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout_p,
                                    probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p), 0.0)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vt)
    return jnp.swapaxes(out, 1, 2)  # back to [B, L, H, D]


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, name=None):
    """Layout [batch, seq, heads, head_dim], matching the reference API."""
    md = attn_mask._data if isinstance(attn_mask, Tensor) else attn_mask
    drop = dropout_p if training else 0.0

    if _should_use_flash(query) and md is None and drop == 0.0:
        from ...ops.pallas.flash_attention import flash_attention_fwd
        return apply_op(
            lambda q, k, v: flash_attention_fwd(q, k, v, causal=is_causal),
            query, key, value, op_name="flash_attention")

    dropout_key = random_mod.next_key() if drop > 0.0 else None

    def f(q, k, v):
        return _sdpa_reference(q, k, v, mask=md, causal=is_causal,
                               dropout_p=drop, dropout_key=dropout_key)
    return apply_op(f, query, key, value, op_name="sdpa")


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None,
                    rng_name="", training=True, name=None):
    """ref: nn/functional/flash_attention.py flash_attention — same
    signature; returns (out, softmax-or-None) tuple for parity."""
    out = scaled_dot_product_attention(query, key, value, None, dropout,
                                       causal, training)
    return out, None


def _should_use_flash(q) -> bool:
    import jax as _jax
    try:
        dev = (q._data.devices() if isinstance(q, Tensor) else set()) or set()
        plats = {d.platform for d in dev}
        if not plats:
            plats = {_jax.default_backend()}
        return any(p in ("tpu", "axon") for p in plats)
    except Exception:
        return False
