"""Vision functionals: affine_grid, grid_sample, temporal_shift.

ref: python/paddle/nn/functional/vision.py:140 (affine_grid), grid_sample
(same file), extension.py:247 (temporal_shift). TPU-native: pure gather
algebra — XLA lowers the index arithmetic + gathers onto the VPU; no
cudnn sampler analog needed.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.autograd import apply_op

__all__ = ["affine_grid", "grid_sample", "temporal_shift"]


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """ref: vision.py affine_grid — theta [N,2,3] + out [N,C,H,W] ->
    sampling grid [N,H,W,2] (or the 5-D/3-D variant [N,D,H,W,3])."""
    if hasattr(out_shape, "numpy"):
        out_shape = [int(v) for v in out_shape.numpy().tolist()]
    out_shape = [int(v) for v in out_shape]
    nd = len(out_shape) - 2  # 2 (H,W) or 3 (D,H,W)

    def f(th):
        sizes = out_shape[2:]

        def axis_coords(n):
            if align_corners:
                return jnp.linspace(-1.0, 1.0, n)
            step = 2.0 / n
            return jnp.linspace(-1.0 + step / 2, 1.0 - step / 2, n)

        axes = [axis_coords(s) for s in sizes]
        mesh = jnp.meshgrid(*axes, indexing="ij")  # each [*sizes]
        # grid last-dim order is (x, y[, z]) = (W, H[, D]) — reversed
        coords = jnp.stack(list(reversed(mesh)) + [jnp.ones_like(mesh[0])],
                           axis=-1)  # [*sizes, nd+1]
        # [N, *sizes, nd] = coords @ theta^T
        out = jnp.einsum("...k,njk->n...j", coords, th)
        return out.astype(th.dtype)

    return apply_op(f, theta, op_name="affine_grid")


def _reflect(coord, lo, hi):
    """Reflection padding coordinate fold (align_corners grid units)."""
    rng = hi - lo
    if rng <= 0:
        return jnp.zeros_like(coord)
    double = 2 * rng
    coord = jnp.abs((coord - lo) % double)
    return jnp.where(coord > rng, double - coord, coord) + lo


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """ref: vision.py grid_sample — NCHW x [N,C,H,W] sampled at
    grid [N,Ho,Wo,2] ((x,y) in [-1,1]); 5-D NCDHW with grid [...,3] too."""
    if mode not in ("bilinear", "nearest"):
        raise ValueError(f"grid_sample mode must be bilinear|nearest, "
                         f"got {mode}")
    if padding_mode not in ("zeros", "border", "reflection"):
        raise ValueError(f"bad padding_mode {padding_mode}")

    def f(a, g):
        nd = g.shape[-1]
        spatial = a.shape[2:]  # (H, W) or (D, H, W)
        if len(spatial) != nd:
            raise ValueError(
                f"grid last dim {nd} does not match input rank {a.ndim}")
        g = g.astype(jnp.float32)
        # unnormalize each coordinate; grid order (x, y[, z]) maps to
        # spatial axes reversed
        coords = []
        for i in range(nd):
            size = spatial[nd - 1 - i]
            c = g[..., i]
            if align_corners:
                c = (c + 1) / 2 * (size - 1)
            else:
                c = ((c + 1) * size - 1) / 2
            coords.append(c)
        coords = coords[::-1]  # now ordered like spatial axes

        def fold(c, size):
            if padding_mode == "border":
                return jnp.clip(c, 0, size - 1), None
            if padding_mode == "reflection":
                if align_corners:
                    c = _reflect(c, 0.0, float(size - 1))
                else:
                    c = _reflect(c, -0.5, size - 0.5)
                    c = jnp.clip(c, 0, size - 1)
                return c, None
            # zeros: keep, mask later
            valid = (c >= -1) & (c <= size)  # loose; exact mask per corner
            return c, valid

        folded = []
        for c, size in zip(coords, spatial):
            c2, _ = fold(c, size)
            folded.append(c2)

        def gather_at(idxs):
            """idxs: list of integer index arrays [N, *out_sp]; returns
            gathered values [N, C, *out_sp] with zero padding mask."""
            valid = None
            cl = []
            for idx, size in zip(idxs, spatial):
                v = (idx >= 0) & (idx < size)
                valid = v if valid is None else (valid & v)
                cl.append(jnp.clip(idx, 0, size - 1))
            n = a.shape[0]
            bidx = jnp.arange(n).reshape((n,) + (1,) * (cl[0].ndim - 1))
            bidx = jnp.broadcast_to(bidx, cl[0].shape)
            # a: [N, C, *spatial] -> take per batch
            moved = jnp.moveaxis(a, 1, -1)  # [N, *spatial, C]
            vals = moved[(bidx,) + tuple(cl)]  # [N, *out_sp, C]
            if padding_mode == "zeros":
                vals = jnp.where(valid[..., None], vals, 0.0)
            return jnp.moveaxis(vals, -1, 1)

        if mode == "nearest":
            idxs = [jnp.round(c).astype(jnp.int32) for c in folded]
            return gather_at(idxs).astype(a.dtype)

        # bilinear / trilinear
        lows = [jnp.floor(c) for c in folded]
        fracs = [c - lo for c, lo in zip(folded, lows)]
        lows = [lo.astype(jnp.int32) for lo in lows]
        out = None
        for corner in range(2 ** nd):
            idxs, w = [], None
            for d in range(nd):
                hi = (corner >> d) & 1
                idxs.append(lows[d] + hi)
                wd = fracs[d] if hi else (1.0 - fracs[d])
                w = wd if w is None else w * wd
            v = gather_at(idxs)
            contrib = v * w[:, None]
            out = contrib if out is None else out + contrib
        return out.astype(a.dtype)

    return apply_op(f, x, grid, op_name="grid_sample")


def temporal_shift(x, seg_num, shift_ratio=0.25, name=None,
                   data_format="NCHW"):
    """ref: extension.py:247 temporal_shift (TSM)."""
    if data_format not in ("NCHW", "NHWC"):
        raise ValueError(f"bad data_format {data_format}")

    def f(a):
        if data_format == "NHWC":
            a = jnp.transpose(a, (0, 3, 1, 2))
        nt, c, h, w = a.shape
        n = nt // seg_num
        r = a.reshape(n, seg_num, c, h, w)
        c1 = int(c * shift_ratio)
        c2 = int(c * 2 * shift_ratio)
        pad = jnp.pad(r, ((0, 0), (1, 1), (0, 0), (0, 0), (0, 0)))
        s1 = pad[:, :seg_num, :c1]           # shift from t-1
        s2 = pad[:, 2:, c1:c2]               # shift from t+1
        s3 = pad[:, 1:seg_num + 1, c2:]      # unshifted
        out = jnp.concatenate([s1, s2, s3], axis=2).reshape(nt, c, h, w)
        if data_format == "NHWC":
            out = jnp.transpose(out, (0, 2, 3, 1))
        return out

    return apply_op(f, x, op_name="temporal_shift")
