"""Loss functionals. ref: python/paddle/nn/functional/loss.py"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.autograd import apply_op
from ...core.tensor import Tensor


def _reduce(v, reduction, weight_sum=None):
    if reduction == "mean":
        if weight_sum is not None:
            return jnp.sum(v) / weight_sum
        return jnp.mean(v)
    if reduction == "sum":
        return jnp.sum(v)
    return v


def mse_loss(input, label, reduction="mean", name=None):
    return apply_op(
        lambda a, b: _reduce(jnp.square(a - b), reduction), input, label,
        op_name="mse_loss")


def l1_loss(input, label, reduction="mean", name=None):
    return apply_op(
        lambda a, b: _reduce(jnp.abs(a - b), reduction), input, label,
        op_name="l1_loss")


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def f(a, b):
        d = jnp.abs(a - b)
        v = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
        # paddle multiplies by delta
        return _reduce(v * delta, reduction)
    return apply_op(f, input, label, op_name="smooth_l1_loss")


def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0, name=None):
    """ref: nn/functional/loss.py cross_entropy (softmax+NLL fused).

    On TPU this lowers to one fused XLA computation; the reference's
    c_softmax_with_cross_entropy TP variant lives in distributed.mp_layers.
    """
    wd = weight._data if isinstance(weight, Tensor) else weight

    def f(logits, lbl):
        if use_softmax:
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=axis)
        else:
            logp = jnp.log(jnp.maximum(logits.astype(jnp.float32), 1e-30))
        if soft_label or (lbl.ndim == logits.ndim and
                          lbl.shape[axis] == logits.shape[axis] and
                          jnp.issubdtype(lbl.dtype, jnp.floating)):
            tgt = lbl.astype(jnp.float32)
            if label_smoothing > 0.0:
                k = logits.shape[axis]
                tgt = (1 - label_smoothing) * tgt + label_smoothing / k
            per = -jnp.sum(tgt * logp, axis=axis)
            if wd is not None:
                cls_w = jnp.sum(tgt * wd, axis=axis)
                per = per * cls_w
            return _reduce(per, reduction)
        # hard labels
        lbl_idx = lbl.astype(jnp.int32)
        squeeze = (lbl_idx.ndim == logits.ndim and
                   lbl_idx.shape[axis] == 1)
        if squeeze:
            lbl_idx = jnp.squeeze(lbl_idx, axis)
        k = logits.shape[axis]
        if label_smoothing > 0.0:
            oh = jax.nn.one_hot(lbl_idx, k, axis=axis, dtype=jnp.float32)
            tgt = (1 - label_smoothing) * oh + label_smoothing / k
            per = -jnp.sum(tgt * logp, axis=axis)
        else:
            moved = jnp.moveaxis(logp, axis, -1)
            per = -jnp.take_along_axis(
                moved, lbl_idx[..., None], axis=-1)[..., 0]
        valid = lbl_idx != ignore_index
        per = jnp.where(valid, per, 0.0)
        if wd is not None:
            w_per = jnp.take(wd, jnp.clip(lbl_idx, 0, k - 1)) * valid
            per = per * w_per
            return _reduce(per, reduction,
                           weight_sum=jnp.sum(w_per)
                           if reduction == "mean" else None)
        if reduction == "mean":
            return jnp.sum(per) / jnp.maximum(jnp.sum(valid), 1)
        return _reduce(per, reduction)
    return apply_op(f, input, label, op_name="cross_entropy")


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    from .activation import softmax as softmax_fn
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none",
                         axis=axis)
    # paddle keeps a size-1 class dim on the returned loss
    loss = loss.unsqueeze(axis)
    if return_softmax:
        return loss, softmax_fn(logits, axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None):
    wd = weight._data if isinstance(weight, Tensor) else weight

    def f(logp, lbl):
        lbl_idx = lbl.astype(jnp.int32)
        moved = jnp.moveaxis(logp, 1, -1)
        per = -jnp.take_along_axis(moved, lbl_idx[..., None],
                                   axis=-1)[..., 0]
        valid = lbl_idx != ignore_index
        per = jnp.where(valid, per, 0.0)
        if wd is not None:
            w_per = jnp.take(wd, jnp.clip(lbl_idx, 0, logp.shape[1] - 1))
            w_per = w_per * valid
            per = per * w_per
            if reduction == "mean":
                return jnp.sum(per) / jnp.sum(w_per)
        if reduction == "mean":
            return jnp.sum(per) / jnp.maximum(jnp.sum(valid), 1)
        return _reduce(per, reduction)
    return apply_op(f, input, label, op_name="nll_loss")


def binary_cross_entropy(input, label, weight=None, reduction="mean",
                         name=None):
    args = [input, label] + ([weight] if weight is not None else [])

    def f(p, y, *w):
        eps = 1e-12
        v = -(y * jnp.log(jnp.maximum(p, eps)) +
              (1 - y) * jnp.log(jnp.maximum(1 - p, eps)))
        if w:
            v = v * w[0]
        return _reduce(v, reduction)
    return apply_op(f, *args, op_name="binary_cross_entropy")


def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None,
                                     name=None):
    pw = pos_weight._data if isinstance(pos_weight, Tensor) else pos_weight
    args = [logit, label] + ([weight] if weight is not None else [])

    def f(z, y, *w):
        # numerically-stable BCE-with-logits
        neg_abs = -jnp.abs(z)
        base = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(neg_abs))
        if pw is not None:
            log_sig = jax.nn.log_sigmoid(z)
            log_sig_neg = jax.nn.log_sigmoid(-z)
            base = -(pw * y * log_sig + (1 - y) * log_sig_neg)
        if w:
            base = base * w[0]
        return _reduce(base, reduction)
    return apply_op(f, *args, op_name="bce_with_logits")


def kl_div(input, label, reduction="mean", log_target=False, name=None):
    def f(logp, tgt):
        if log_target:
            v = jnp.exp(tgt) * (tgt - logp)
        else:
            v = tgt * (jnp.log(jnp.maximum(tgt, 1e-12)) - logp)
        if reduction == "batchmean":
            return jnp.sum(v) / logp.shape[0]
        return _reduce(v, reduction)
    return apply_op(f, input, label, op_name="kl_div")


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean",
                         name=None):
    def f(a, y):
        v = jnp.where(y == 1, a, jnp.maximum(0.0, margin - a))
        return _reduce(v, reduction)
    return apply_op(f, input, label, op_name="hinge_embedding_loss")


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",
                        name=None):
    def f(a, b, y):
        v = jnp.maximum(0.0, -y * (a - b) + margin)
        return _reduce(v, reduction)
    return apply_op(f, input, other, label, op_name="margin_ranking_loss")


def cosine_embedding_loss(input1, input2, label, margin=0.0,
                          reduction="mean", name=None):
    def f(a, b, y):
        cos = (jnp.sum(a * b, -1) /
               jnp.maximum(jnp.linalg.norm(a, axis=-1) *
                           jnp.linalg.norm(b, axis=-1), 1e-12))
        v = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(v, reduction)
    return apply_op(f, input1, input2, label,
                    op_name="cosine_embedding_loss")


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean",
                        name=None):
    def f(a, pos, neg):
        dp = jnp.linalg.norm(a - pos + epsilon, ord=p, axis=-1)
        dn = jnp.linalg.norm(a - neg + epsilon, ord=p, axis=-1)
        if swap:
            dn2 = jnp.linalg.norm(pos - neg + epsilon, ord=p, axis=-1)
            dn = jnp.minimum(dn, dn2)
        return _reduce(jnp.maximum(dp - dn + margin, 0.0), reduction)
    return apply_op(f, input, positive, negative,
                    op_name="triplet_margin_loss")


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC via the standard forward algorithm in log space (lax.scan)."""
    def f(lp, lbl, in_len, lbl_len):
        # lp: [T, B, C] logits -> log prob
        logp = jax.nn.log_softmax(lp.astype(jnp.float32), axis=-1)
        T, B, C = logp.shape
        S = lbl.shape[1]
        ext = jnp.full((B, 2 * S + 1), blank, jnp.int32)
        ext = ext.at[:, 1::2].set(lbl.astype(jnp.int32))
        L = 2 * S + 1
        neg_inf = -1e30
        alpha0 = jnp.full((B, L), neg_inf)
        alpha0 = alpha0.at[:, 0].set(logp[0, :, blank])
        alpha0 = alpha0.at[:, 1].set(
            jnp.take_along_axis(logp[0], ext[:, 1:2], axis=1)[:, 0])

        same = jnp.concatenate(
            [jnp.ones((B, 2), bool),
             ext[:, 2:] == ext[:, :-2]], axis=1)

        def step(alpha, logp_t):
            a0 = alpha
            a1 = jnp.concatenate(
                [jnp.full((B, 1), neg_inf), alpha[:, :-1]], axis=1)
            a2 = jnp.concatenate(
                [jnp.full((B, 2), neg_inf), alpha[:, :-2]], axis=1)
            a2 = jnp.where(same, neg_inf, a2)
            m = jnp.maximum(jnp.maximum(a0, a1), a2)
            new = m + jnp.log(
                jnp.exp(a0 - m) + jnp.exp(a1 - m) + jnp.exp(a2 - m))
            emit = jnp.take_along_axis(logp_t, ext, axis=1)
            new = new + emit
            return new, new

        _, alphas = jax.lax.scan(step, alpha0, logp[1:])
        alphas = jnp.concatenate([alpha0[None], alphas], axis=0)
        t_idx = jnp.clip(in_len.astype(jnp.int32) - 1, 0, T - 1)
        final = alphas[t_idx, jnp.arange(B)]  # [B, L]
        end1 = 2 * lbl_len.astype(jnp.int32)
        end2 = 2 * lbl_len.astype(jnp.int32) - 1
        f1 = jnp.take_along_axis(final, end1[:, None], axis=1)[:, 0]
        f2 = jnp.take_along_axis(final, jnp.maximum(end2, 0)[:, None],
                                 axis=1)[:, 0]
        m = jnp.maximum(f1, f2)
        ll = m + jnp.log(jnp.exp(f1 - m) + jnp.exp(f2 - m))
        loss = -ll
        if reduction == "mean":
            return jnp.mean(loss / jnp.maximum(lbl_len, 1))
        return _reduce(loss, reduction)
    return apply_op(f, log_probs, labels, input_lengths, label_lengths,
                    op_name="ctc_loss")


def soft_margin_loss(input, label, reduction="mean", name=None):
    """ref: loss.py soft_margin_loss: log(1 + exp(-label * input)),
    computed as softplus(-label*input) for overflow stability."""
    return apply_op(
        lambda a, b: _reduce(jax.nn.softplus(-b * a), reduction),
        input, label, op_name="soft_margin_loss")


def multi_label_soft_margin_loss(input, label, weight=None,
                                 reduction="mean", name=None):
    """ref: loss.py multi_label_soft_margin_loss (mean over classes of
    BCE-with-logits terms)."""
    def f(a, b, *w):
        term = (b * jax.nn.log_sigmoid(a)
                + (1 - b) * jax.nn.log_sigmoid(-a))
        if w:
            term = term * w[0]
        return _reduce(-term.mean(-1), reduction)
    args = [weight] if weight is not None else []
    return apply_op(f, input, label, *args,
                    op_name="multi_label_soft_margin_loss")


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean", name=None):
    """ref: loss.py multi_margin_loss (multi-class hinge)."""
    def f(a, lbl, *w):
        n, c = a.shape
        correct = jnp.take_along_axis(a, lbl[:, None], 1)
        m = jnp.maximum(0.0, margin - correct + a)
        if p != 1:
            m = m ** p
        if w:
            m = m * jnp.take(w[0], lbl)[:, None]
        mask = 1.0 - jax.nn.one_hot(lbl, c, dtype=a.dtype)
        return _reduce((m * mask).sum(-1) / c, reduction)
    args = [weight] if weight is not None else []
    return apply_op(f, input, label, *args, op_name="multi_margin_loss")


def poisson_nll_loss(input, label, log_input=True, full=False,
                     epsilon=1e-8, reduction="mean", name=None):
    """ref: loss.py poisson_nll_loss."""
    def f(a, b):
        if log_input:
            v = jnp.exp(a) - b * a
        else:
            v = a - b * jnp.log(a + epsilon)
        if full:
            stirling = (b * jnp.log(b) - b
                        + 0.5 * jnp.log(2 * jnp.pi * b))
            v = v + jnp.where(b > 1, stirling, 0.0)
        return _reduce(v, reduction)
    return apply_op(f, input, label, op_name="poisson_nll_loss")


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean", name=None):
    """ref: loss.py gaussian_nll_loss."""
    def f(a, b, var):
        var = jnp.maximum(var, epsilon)
        v = 0.5 * (jnp.log(var) + (a - b) ** 2 / var)
        if full:
            v = v + 0.5 * jnp.log(2 * jnp.asarray(jnp.pi))
        return _reduce(v, reduction)
    return apply_op(f, input, label, variance, op_name="gaussian_nll_loss")
