"""Loss functionals. ref: python/paddle/nn/functional/loss.py"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.autograd import apply_op
from ...core.tensor import Tensor


def _reduce(v, reduction, weight_sum=None):
    if reduction == "mean":
        if weight_sum is not None:
            return jnp.sum(v) / weight_sum
        return jnp.mean(v)
    if reduction == "sum":
        return jnp.sum(v)
    return v


def mse_loss(input, label, reduction="mean", name=None):
    return apply_op(
        lambda a, b: _reduce(jnp.square(a - b), reduction), input, label,
        op_name="mse_loss")


def l1_loss(input, label, reduction="mean", name=None):
    return apply_op(
        lambda a, b: _reduce(jnp.abs(a - b), reduction), input, label,
        op_name="l1_loss")


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def f(a, b):
        d = jnp.abs(a - b)
        v = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
        # paddle multiplies by delta
        return _reduce(v * delta, reduction)
    return apply_op(f, input, label, op_name="smooth_l1_loss")


def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0, name=None):
    """ref: nn/functional/loss.py cross_entropy (softmax+NLL fused).

    On TPU this lowers to one fused XLA computation; the reference's
    c_softmax_with_cross_entropy TP variant lives in distributed.mp_layers.
    """
    wd = weight._data if isinstance(weight, Tensor) else weight

    def f(logits, lbl):
        # big-vocab hard-label mean: chunked-CE custom VJP — never
        # materializes the fp32 [N, V] log-softmax (the top HBM
        # allocation of an MLM/LM step at V=30k+; ref fused
        # c_softmax_with_cross_entropy role)
        hard = not (lbl.ndim == logits.ndim and
                    lbl.shape[axis] == logits.shape[axis] and
                    jnp.issubdtype(lbl.dtype, jnp.floating))
        if (use_softmax and not soft_label and hard and wd is None
                and label_smoothing == 0.0 and reduction == "mean"
                and axis in (-1, logits.ndim - 1)
                and logits.ndim in (2, 3)
                and logits.shape[-1] >= 4096):
            from ...ops.fused_ce import fused_softmax_ce_mean
            lbl_idx = lbl.astype(jnp.int32)
            if (lbl_idx.ndim == logits.ndim and
                    lbl_idx.shape[-1] == 1):
                lbl_idx = jnp.squeeze(lbl_idx, -1)
            if lbl_idx.ndim == logits.ndim - 1:
                lg3 = logits if logits.ndim == 3 else logits[None]
                lb3 = lbl_idx if lbl_idx.ndim == 2 else lbl_idx[None]
                return fused_softmax_ce_mean(lg3, lb3, ignore_index)
        if use_softmax:
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=axis)
        else:
            logp = jnp.log(jnp.maximum(logits.astype(jnp.float32), 1e-30))
        if soft_label or (lbl.ndim == logits.ndim and
                          lbl.shape[axis] == logits.shape[axis] and
                          jnp.issubdtype(lbl.dtype, jnp.floating)):
            tgt = lbl.astype(jnp.float32)
            if label_smoothing > 0.0:
                k = logits.shape[axis]
                tgt = (1 - label_smoothing) * tgt + label_smoothing / k
            per = -jnp.sum(tgt * logp, axis=axis)
            if wd is not None:
                cls_w = jnp.sum(tgt * wd, axis=axis)
                per = per * cls_w
            return _reduce(per, reduction)
        # hard labels
        lbl_idx = lbl.astype(jnp.int32)
        squeeze = (lbl_idx.ndim == logits.ndim and
                   lbl_idx.shape[axis] == 1)
        if squeeze:
            lbl_idx = jnp.squeeze(lbl_idx, axis)
        k = logits.shape[axis]
        if label_smoothing > 0.0:
            oh = jax.nn.one_hot(lbl_idx, k, axis=axis, dtype=jnp.float32)
            tgt = (1 - label_smoothing) * oh + label_smoothing / k
            per = -jnp.sum(tgt * logp, axis=axis)
        else:
            moved = jnp.moveaxis(logp, axis, -1)
            per = -jnp.take_along_axis(
                moved, lbl_idx[..., None], axis=-1)[..., 0]
        valid = lbl_idx != ignore_index
        per = jnp.where(valid, per, 0.0)
        if wd is not None:
            w_per = jnp.take(wd, jnp.clip(lbl_idx, 0, k - 1)) * valid
            per = per * w_per
            return _reduce(per, reduction,
                           weight_sum=jnp.sum(w_per)
                           if reduction == "mean" else None)
        if reduction == "mean":
            return jnp.sum(per) / jnp.maximum(jnp.sum(valid), 1)
        return _reduce(per, reduction)
    return apply_op(f, input, label, op_name="cross_entropy")


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    from .activation import softmax as softmax_fn
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none",
                         axis=axis)
    # paddle keeps a size-1 class dim on the returned loss
    loss = loss.unsqueeze(axis)
    if return_softmax:
        return loss, softmax_fn(logits, axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None):
    wd = weight._data if isinstance(weight, Tensor) else weight

    def f(logp, lbl):
        lbl_idx = lbl.astype(jnp.int32)
        moved = jnp.moveaxis(logp, 1, -1)
        per = -jnp.take_along_axis(moved, lbl_idx[..., None],
                                   axis=-1)[..., 0]
        valid = lbl_idx != ignore_index
        per = jnp.where(valid, per, 0.0)
        if wd is not None:
            w_per = jnp.take(wd, jnp.clip(lbl_idx, 0, logp.shape[1] - 1))
            w_per = w_per * valid
            per = per * w_per
            if reduction == "mean":
                return jnp.sum(per) / jnp.sum(w_per)
        if reduction == "mean":
            return jnp.sum(per) / jnp.maximum(jnp.sum(valid), 1)
        return _reduce(per, reduction)
    return apply_op(f, input, label, op_name="nll_loss")


def binary_cross_entropy(input, label, weight=None, reduction="mean",
                         name=None):
    args = [input, label] + ([weight] if weight is not None else [])

    def f(p, y, *w):
        eps = 1e-12
        v = -(y * jnp.log(jnp.maximum(p, eps)) +
              (1 - y) * jnp.log(jnp.maximum(1 - p, eps)))
        if w:
            v = v * w[0]
        return _reduce(v, reduction)
    return apply_op(f, *args, op_name="binary_cross_entropy")


def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None,
                                     name=None):
    pw = pos_weight._data if isinstance(pos_weight, Tensor) else pos_weight
    args = [logit, label] + ([weight] if weight is not None else [])

    def f(z, y, *w):
        # numerically-stable BCE-with-logits
        neg_abs = -jnp.abs(z)
        base = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(neg_abs))
        if pw is not None:
            log_sig = jax.nn.log_sigmoid(z)
            log_sig_neg = jax.nn.log_sigmoid(-z)
            base = -(pw * y * log_sig + (1 - y) * log_sig_neg)
        if w:
            base = base * w[0]
        return _reduce(base, reduction)
    return apply_op(f, *args, op_name="bce_with_logits")


def kl_div(input, label, reduction="mean", log_target=False, name=None):
    def f(logp, tgt):
        if log_target:
            v = jnp.exp(tgt) * (tgt - logp)
        else:
            v = tgt * (jnp.log(jnp.maximum(tgt, 1e-12)) - logp)
        if reduction == "batchmean":
            return jnp.sum(v) / logp.shape[0]
        return _reduce(v, reduction)
    return apply_op(f, input, label, op_name="kl_div")


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean",
                         name=None):
    def f(a, y):
        v = jnp.where(y == 1, a, jnp.maximum(0.0, margin - a))
        return _reduce(v, reduction)
    return apply_op(f, input, label, op_name="hinge_embedding_loss")


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",
                        name=None):
    def f(a, b, y):
        v = jnp.maximum(0.0, -y * (a - b) + margin)
        return _reduce(v, reduction)
    return apply_op(f, input, other, label, op_name="margin_ranking_loss")


def cosine_embedding_loss(input1, input2, label, margin=0.0,
                          reduction="mean", name=None):
    def f(a, b, y):
        cos = (jnp.sum(a * b, -1) /
               jnp.maximum(jnp.linalg.norm(a, axis=-1) *
                           jnp.linalg.norm(b, axis=-1), 1e-12))
        v = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(v, reduction)
    return apply_op(f, input1, input2, label,
                    op_name="cosine_embedding_loss")


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean",
                        name=None):
    def f(a, pos, neg):
        dp = jnp.linalg.norm(a - pos + epsilon, ord=p, axis=-1)
        dn = jnp.linalg.norm(a - neg + epsilon, ord=p, axis=-1)
        if swap:
            dn2 = jnp.linalg.norm(pos - neg + epsilon, ord=p, axis=-1)
            dn = jnp.minimum(dn, dn2)
        return _reduce(jnp.maximum(dp - dn + margin, 0.0), reduction)
    return apply_op(f, input, positive, negative,
                    op_name="triplet_margin_loss")


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC via the standard forward algorithm in log space (lax.scan)."""
    def f(lp, lbl, in_len, lbl_len):
        # lp: [T, B, C] logits -> log prob
        logp = jax.nn.log_softmax(lp.astype(jnp.float32), axis=-1)
        T, B, C = logp.shape
        S = lbl.shape[1]
        ext = jnp.full((B, 2 * S + 1), blank, jnp.int32)
        ext = ext.at[:, 1::2].set(lbl.astype(jnp.int32))
        L = 2 * S + 1
        neg_inf = -1e30
        alpha0 = jnp.full((B, L), neg_inf)
        alpha0 = alpha0.at[:, 0].set(logp[0, :, blank])
        alpha0 = alpha0.at[:, 1].set(
            jnp.take_along_axis(logp[0], ext[:, 1:2], axis=1)[:, 0])

        same = jnp.concatenate(
            [jnp.ones((B, 2), bool),
             ext[:, 2:] == ext[:, :-2]], axis=1)

        def step(alpha, logp_t):
            a0 = alpha
            a1 = jnp.concatenate(
                [jnp.full((B, 1), neg_inf), alpha[:, :-1]], axis=1)
            a2 = jnp.concatenate(
                [jnp.full((B, 2), neg_inf), alpha[:, :-2]], axis=1)
            a2 = jnp.where(same, neg_inf, a2)
            m = jnp.maximum(jnp.maximum(a0, a1), a2)
            new = m + jnp.log(
                jnp.exp(a0 - m) + jnp.exp(a1 - m) + jnp.exp(a2 - m))
            emit = jnp.take_along_axis(logp_t, ext, axis=1)
            new = new + emit
            return new, new

        _, alphas = jax.lax.scan(step, alpha0, logp[1:])
        alphas = jnp.concatenate([alpha0[None], alphas], axis=0)
        t_idx = jnp.clip(in_len.astype(jnp.int32) - 1, 0, T - 1)
        final = alphas[t_idx, jnp.arange(B)]  # [B, L]
        end1 = 2 * lbl_len.astype(jnp.int32)
        end2 = 2 * lbl_len.astype(jnp.int32) - 1
        f1 = jnp.take_along_axis(final, end1[:, None], axis=1)[:, 0]
        f2 = jnp.take_along_axis(final, jnp.maximum(end2, 0)[:, None],
                                 axis=1)[:, 0]
        m = jnp.maximum(f1, f2)
        ll = m + jnp.log(jnp.exp(f1 - m) + jnp.exp(f2 - m))
        loss = -ll
        if reduction == "mean":
            return jnp.mean(loss / jnp.maximum(lbl_len, 1))
        return _reduce(loss, reduction)
    return apply_op(f, log_probs, labels, input_lengths, label_lengths,
                    op_name="ctc_loss")


def soft_margin_loss(input, label, reduction="mean", name=None):
    """ref: loss.py soft_margin_loss: log(1 + exp(-label * input)),
    computed as softplus(-label*input) for overflow stability."""
    return apply_op(
        lambda a, b: _reduce(jax.nn.softplus(-b * a), reduction),
        input, label, op_name="soft_margin_loss")


def multi_label_soft_margin_loss(input, label, weight=None,
                                 reduction="mean", name=None):
    """ref: loss.py multi_label_soft_margin_loss (mean over classes of
    BCE-with-logits terms)."""
    def f(a, b, *w):
        term = (b * jax.nn.log_sigmoid(a)
                + (1 - b) * jax.nn.log_sigmoid(-a))
        if w:
            term = term * w[0]
        return _reduce(-term.mean(-1), reduction)
    args = [weight] if weight is not None else []
    return apply_op(f, input, label, *args,
                    op_name="multi_label_soft_margin_loss")


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean", name=None):
    """ref: loss.py multi_margin_loss (multi-class hinge)."""
    def f(a, lbl, *w):
        n, c = a.shape
        correct = jnp.take_along_axis(a, lbl[:, None], 1)
        m = jnp.maximum(0.0, margin - correct + a)
        if p != 1:
            m = m ** p
        if w:
            m = m * jnp.take(w[0], lbl)[:, None]
        mask = 1.0 - jax.nn.one_hot(lbl, c, dtype=a.dtype)
        return _reduce((m * mask).sum(-1) / c, reduction)
    args = [weight] if weight is not None else []
    return apply_op(f, input, label, *args, op_name="multi_margin_loss")


def poisson_nll_loss(input, label, log_input=True, full=False,
                     epsilon=1e-8, reduction="mean", name=None):
    """ref: loss.py poisson_nll_loss."""
    def f(a, b):
        if log_input:
            v = jnp.exp(a) - b * a
        else:
            v = a - b * jnp.log(a + epsilon)
        if full:
            stirling = (b * jnp.log(b) - b
                        + 0.5 * jnp.log(2 * jnp.pi * b))
            v = v + jnp.where(b > 1, stirling, 0.0)
        return _reduce(v, reduction)
    return apply_op(f, input, label, op_name="poisson_nll_loss")


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean", name=None):
    """ref: loss.py gaussian_nll_loss."""
    def f(a, b, var):
        var = jnp.maximum(var, epsilon)
        v = 0.5 * (jnp.log(var) + (a - b) ** 2 / var)
        if full:
            v = v + 0.5 * jnp.log(2 * jnp.asarray(jnp.pi))
        return _reduce(v, reduction)
    return apply_op(f, input, label, variance, op_name="gaussian_nll_loss")


# -- round-2 long-tail losses -------------------------------------------------

def square_error_cost(input, label):
    """ref: loss.py square_error_cost — element-wise (input - label)^2."""
    return apply_op(lambda a, b: (a - b) ** 2, input, label,
                    op_name="square_error_cost")


def log_loss(input, label, epsilon=1e-4, name=None):
    """ref: loss.py log_loss."""
    def f(a, b):
        return (-b * jnp.log(a + epsilon)
                - (1.0 - b) * jnp.log(1.0 - a + epsilon))
    return apply_op(f, input, label, op_name="log_loss")


def dice_loss(input, label, epsilon=1e-5, name=None):
    """ref: loss.py dice_loss — 1 - 2|X∩Y|/(|X|+|Y|), mean over batch."""
    def f(a, b):
        lbl = jax.nn.one_hot(jnp.squeeze(b, -1), a.shape[-1], dtype=a.dtype)
        axes = tuple(range(1, a.ndim))
        inse = jnp.sum(a * lbl, axis=axes)
        denom = jnp.sum(a, axis=axes) + jnp.sum(lbl, axis=axes)
        return jnp.mean(1.0 - 2.0 * inse / (denom + epsilon))
    return apply_op(f, input, label, op_name="dice_loss")


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    """ref: loss.py npair_loss (NPairs metric-learning loss)."""
    def f(a, p, l):
        n = l.shape[0]
        lm = (l.reshape(n, 1) == l.reshape(1, n)).astype(a.dtype)
        lm = lm / jnp.sum(lm, axis=1, keepdims=True)
        l2 = (jnp.mean(jnp.sum(a * a, 1)) + jnp.mean(jnp.sum(p * p, 1))) \
            * 0.25 * l2_reg
        sim = a @ p.T
        ce = -jnp.sum(lm * jax.nn.log_softmax(sim, axis=-1), axis=-1)
        celoss = jnp.mean(jnp.sum(lm * ce[:, None], axis=0))
        return l2 + celoss
    return apply_op(f, anchor, positive, labels, op_name="npair_loss")


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    """ref: loss.py sigmoid_focal_loss (RetinaNet focal loss on logits)."""
    def f(x, y, *rest):
        p = jax.nn.sigmoid(x)
        ce = jnp.maximum(x, 0) - x * y + jnp.log1p(jnp.exp(-jnp.abs(x)))
        p_t = p * y + (1 - p) * (1 - y)
        loss = ce * ((1 - p_t) ** gamma)
        if alpha >= 0:
            a_t = alpha * y + (1 - alpha) * (1 - y)
            loss = a_t * loss
        if rest:
            loss = loss / rest[0]
        return _reduce(loss, reduction)
    args = [normalizer] if normalizer is not None else []
    return apply_op(f, logit, label, *args, op_name="sigmoid_focal_loss")


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean",
                                      name=None):
    """ref: loss.py triplet_margin_with_distance_loss."""
    dist = distance_function
    if dist is None:
        def dist(x, y):
            from ...ops import math as _m
            return apply_op(
                lambda a, b: jnp.sqrt(jnp.sum((a - b) ** 2, -1) + 1e-12),
                x, y, op_name="pdist")
    dp = dist(input, positive)
    dn = dist(input, negative)
    if swap:
        dsw = dist(positive, negative)
        dn = apply_op(lambda a, b: jnp.minimum(a, b), dn, dsw,
                      op_name="min")
    return apply_op(
        lambda a, b: _reduce(jnp.maximum(a - b + margin, 0.0), reduction),
        dp, dn, op_name="triplet_margin_with_distance_loss")


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Hierarchical sigmoid loss over a complete binary tree (or a custom
    tree via path_table/path_code).

    ref: python/paddle/nn/functional/loss.py hsigmoid_loss; default-tree
    bit coding per phi/kernels/funcs/matrix_bit_code.h SimpleCode:
    c = label + num_classes; path node j = (c >> (j+1)) - 1,
    bit j = (c >> j) & 1, path length = floor(log2(c)).
    """
    import numpy as _np
    if num_classes < 2:
        raise ValueError(f"Expected num_classes >= 2 (got {num_classes})")
    if (path_table is None) != (path_code is None):
        raise ValueError(
            "path_table and path_code must be given together (custom tree)")

    def f(x, lbl, w, *rest):
        b = rest[0] if bias is not None else None
        if path_table is None:
            # default complete binary tree, host-computed bit tables are
            # data-dependent → compute on device from label
            c = lbl.astype(jnp.int32) + num_classes
            max_len = int(_np.floor(_np.log2(2 * num_classes - 1)))
            js = jnp.arange(max_len)
            # node index and bit per path position
            nodes = (c[:, None] >> (js[None, :] + 1)) - 1
            bits = (c[:, None] >> js[None, :]) & 1
            # valid while (c >> (j+1)) > 0  <=> node >= 0
            valid = nodes >= 0
            nodes = jnp.maximum(nodes, 0)
        else:
            pt, pc = rest[-2], rest[-1]
            nodes = pt.astype(jnp.int32)
            bits = pc.astype(jnp.int32)
            valid = nodes >= 0
            nodes = jnp.maximum(nodes, 0)
        wn = w[nodes]                     # [N, L, D]
        pre = jnp.einsum("nld,nd->nl", wn, x)
        if b is not None:
            pre = pre + jnp.reshape(b, (-1,))[nodes]
        pre = jnp.clip(pre, -40.0, 40.0)
        # binary logistic: log(1+e^pre) - bit*pre, summed over the path
        per = jnp.logaddexp(0.0, pre) - bits.astype(pre.dtype) * pre
        per = jnp.where(valid, per, 0.0)
        return jnp.sum(per, axis=1, keepdims=True)

    args = [a for a in (bias, path_table, path_code) if a is not None]
    return apply_op(f, input, label, weight, *args, op_name="hsigmoid_loss")


def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.001, reduction="mean", name=None):
    """RNN-Transducer loss via a forward-variable DP in pure XLA ops.

    ref: python/paddle/nn/functional/loss.py rnnt_loss (warprnnt kernel,
    phi/kernels/cpu/warprnnt_kernel.cc). input: [B, T, U+1, V] logits
    (log_softmax applied internally, as the kernel does); label [B, U];
    FastEmit (arXiv:2010.11148) applies a (1+lambda) log-weight on label
    emissions.
    """
    def f(acts, lbl, tlen, ulen):
        logp = jax.nn.log_softmax(acts.astype(jnp.float32), axis=-1)
        B, T, U1, V = logp.shape
        U = U1 - 1
        blank_lp = logp[..., blank]                      # [B, T, U+1]
        emit_lp = jnp.take_along_axis(
            logp[:, :, :U, :], lbl[:, None, :, None].astype(jnp.int32),
            axis=-1)[..., 0]                             # [B, T, U]
        if fastemit_lambda:
            emit_lp = emit_lp + jnp.log1p(
                jnp.asarray(fastemit_lambda, jnp.float32))
        NEG = jnp.asarray(-1e30, jnp.float32)

        # alpha[t, u]: log-prob of emitting first u labels in t frames.
        # scan over t; within a row, u-recursion via associative scan
        # (alpha[t,u] = logaddexp(alpha[t-1,u]+blank[t-1,u],
        #                         alpha[t,u-1]+emit[t,u-1]))
        def row_update(carry, t_inp):
            prev_alpha = carry                            # [B, U+1]
            blank_prev, emit_cur = t_inp                  # [B,U+1],[B,U]
            base = prev_alpha + blank_prev                # horizontal step
            # alpha_t[u] = logsumexp over k<=u of
            #   base[k] + sum_{j=k..u-1} emit_cur[j]
            csum = jnp.concatenate(
                [jnp.zeros((B, 1), jnp.float32),
                 jnp.cumsum(emit_cur, axis=1)], axis=1)   # [B, U+1]
            shifted = base - csum
            # exact running logsumexp along u (associative, stable)
            lse = jax.lax.associative_scan(jnp.logaddexp, shifted, axis=1)
            alpha_t = lse + csum
            return alpha_t, alpha_t

        # t = 0 row: alpha[0, u] = sum emit[0, :u]
        emit0 = emit_lp[:, 0, :]
        alpha0 = jnp.concatenate(
            [jnp.zeros((B, 1), jnp.float32),
             jnp.cumsum(emit0, axis=1)], axis=1)
        xs = (jnp.moveaxis(blank_lp[:, :-1, :], 1, 0),
              jnp.moveaxis(emit_lp[:, 1:, :], 1, 0))
        _, rows = jax.lax.scan(row_update, alpha0, xs)
        alphas = jnp.concatenate([alpha0[None], rows], axis=0)  # [T, B, U+1]
        alphas = jnp.moveaxis(alphas, 1, 0)                     # [B, T, U+1]

        t_idx = (tlen.astype(jnp.int32) - 1)
        u_idx = ulen.astype(jnp.int32)
        a_fin = jnp.take_along_axis(
            jnp.take_along_axis(
                alphas, t_idx[:, None, None], axis=1)[:, 0],
            u_idx[:, None], axis=1)[:, 0]
        b_fin = jnp.take_along_axis(
            jnp.take_along_axis(
                blank_lp, t_idx[:, None, None], axis=1)[:, 0],
            u_idx[:, None], axis=1)[:, 0]
        nll = -(a_fin + b_fin)
        if reduction == "mean":
            return jnp.sum(nll) / B
        if reduction == "sum":
            return jnp.sum(nll)
        return nll

    return apply_op(f, input, label, input_lengths, label_lengths,
                    op_name="rnnt_loss")


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean"):
    """ArcFace-family margin softmax CE.

    ref: python/paddle/nn/functional/loss.py:2224 margin_cross_entropy —
    logit of the true class becomes
    cos(m1*theta + m2) - m3, all scaled by s. With a model-parallel group
    (class-sharded logits) the softmax runs over the global class dim via
    the group collectives (ref: c_softmax_with_cross_entropy).
    """
    from ...distributed import collective as coll

    # reference semantics: group=None -> default group (model parallel),
    # group=False -> data parallel (no cross-rank softmax)
    mp = group is not False
    g = coll._get_group(None if group in (None, True) else group) \
        if mp else None
    class_offset = 0
    if mp and g.nranks > 1:
        # class-sharded logits: global class id offset of this rank
        sizes = []
        coll.all_gather_object(sizes, int(logits.shape[-1]), group=g)
        class_offset = sum(sizes[:g.rank])

    def f(lg, lb):
        lb = lb.reshape(lb.shape[0]) if lb.ndim > 1 else lb
        local = lb.astype(jnp.int32) - class_offset
        in_range = (local >= 0) & (local < lg.shape[-1])
        safe = jnp.where(in_range, local, 0)
        onehot = jax.nn.one_hot(safe, lg.shape[-1], dtype=lg.dtype) \
            * in_range[:, None].astype(lg.dtype)
        cos_t = jnp.clip(lg, -1.0, 1.0)
        theta = jnp.arccos(cos_t)
        modified = jnp.cos(margin1 * theta + margin2) - margin3
        out = jnp.where(onehot > 0, modified, cos_t) * scale
        return out, onehot

    out, onehot = apply_op(f, logits, label,
                           op_name="margin_cross_entropy_logits")

    if mp and g is not None and g.nranks > 1:
        from ..functional import softmax as _softmax
        # distributed softmax: subtract global max, divide by global sum
        def g_max(a):
            return jnp.max(a, axis=-1, keepdims=True)
        mx = apply_op(g_max, out, op_name="rowmax")
        coll.all_reduce(mx, coll.ReduceOp.MAX, g)
        exp = apply_op(lambda a, m: jnp.exp(a - m), out, mx, op_name="exp")
        den = apply_op(lambda e: jnp.sum(e, -1, keepdims=True), exp,
                       op_name="rowsum")
        coll.all_reduce(den, coll.ReduceOp.SUM, g)
        sm = apply_op(lambda e, d: e / d, exp, den, op_name="div")
        logden = apply_op(lambda d: jnp.log(d), den, op_name="log")
        tgt = apply_op(lambda o, a, m: jnp.sum(o * (a - m), -1,
                                               keepdims=True),
                       onehot, out, mx, op_name="target_logit")
        coll.all_reduce(tgt, coll.ReduceOp.SUM, g)
        loss = apply_op(lambda ld, t: ld - t, logden, tgt, op_name="nll")
    else:
        def f2(o, oh):
            lsm = jax.nn.log_softmax(o, axis=-1)
            loss = -jnp.sum(oh * lsm, axis=-1, keepdims=True)
            return loss, jnp.exp(lsm)
        loss, sm = apply_op(f2, out, onehot, op_name="margin_ce")

    if reduction == "mean":
        loss = apply_op(lambda v: jnp.mean(v), loss, op_name="mean")
    elif reduction == "sum":
        loss = apply_op(lambda v: jnp.sum(v), loss, op_name="sum")
    if return_softmax:
        return loss, sm
    return loss


def adaptive_log_softmax_with_loss(input, label, head_weight, tail_weights,
                                   cutoffs, head_bias=None, name=None):
    """ref: loss.py adaptive_log_softmax_with_loss (Grave et al. 2017).
    Masked vectorized form (no data-dependent gathers) so it jits clean.
    Returns (per-sample log-prob of the target, mean NLL loss)."""

    def f(x, y, hw, *rest):
        if x.ndim == 1:
            x = x[None]
            y = jnp.reshape(y, (1,))
        hb = rest[0] if head_bias is not None else None
        tails = rest[1:] if head_bias is not None else rest
        # paddle contract: cutoffs excludes num_classes; head covers
        # [0, cutoffs[0]) plus one slot per tail cluster
        shortlist = cutoffs[0]
        head_logits = x @ hw
        if hb is not None:
            head_logits = head_logits + hb
        head_lp = jax.nn.log_softmax(head_logits, axis=-1)
        y = y.astype(jnp.int32)
        out = jnp.take_along_axis(
            head_lp, jnp.minimum(y, shortlist - 1)[:, None], axis=1)[:, 0]
        bounds = [0] + list(cutoffs)
        for i, (w1, w2) in enumerate(tails):
            lo = bounds[i + 1]
            hi = bounds[i + 2] if i + 2 < len(bounds) else lo + w2.shape[-1]
            mask = (y >= lo) & (y < hi)
            rel = jnp.clip(y - lo, 0, w2.shape[-1] - 1)
            tail_lp = jax.nn.log_softmax((x @ w1) @ w2, axis=-1)
            cluster_lp = head_lp[:, shortlist + i] + jnp.take_along_axis(
                tail_lp, rel[:, None], axis=1)[:, 0]
            out = jnp.where(mask, cluster_lp, out)
        loss = -jnp.mean(out)
        return out, loss

    args = [head_weight]
    if head_bias is not None:
        args.append(head_bias)
    args += [w for pair in tail_weights for w in pair]

    def wrapper(x, y, hw, *rest):
        hb = ()
        if head_bias is not None:
            hb, rest = (rest[0],), rest[1:]
        pairs = [(rest[2 * i], rest[2 * i + 1])
                 for i in range(len(rest) // 2)]
        return f(x, y, hw, *hb, *pairs)

    return apply_op(wrapper, input, label, *args,
                    op_name="adaptive_log_softmax_with_loss")
