"""Convolution functionals over lax.conv_general_dilated (MXU path).

ref: python/paddle/nn/functional/conv.py. Weight layout follows the
reference: [out_c, in_c/groups, *kernel]; data_format NCHW (default) or NHWC.
XLA maps these directly onto the MXU via implicit im2col.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.autograd import apply_op


def _tuple(v, n):
    if isinstance(v, (list, tuple)):
        if len(v) == n:
            return tuple(int(x) for x in v)
        if len(v) == 1:
            return tuple(int(v[0]) for _ in range(n))
        return tuple(int(x) for x in v)
    return (int(v),) * n


def _padding(padding, n):
    """paddle padding: int, list of n ints, list of 2n ints, list of n pairs,
    or 'SAME'/'VALID'."""
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n and all(isinstance(p, int) for p in padding):
        return [(p, p) for p in padding]
    if len(padding) == 2 * n:
        return [(padding[2 * i], padding[2 * i + 1]) for i in range(n)]
    return [tuple(p) for p in padding]


def _dims(nd, channel_last):
    if nd == 1:
        return ("NWC", "WIO", "NWC") if channel_last else \
            ("NCW", "OIW", "NCW")
    if nd == 2:
        return ("NHWC", "HWIO", "NHWC") if channel_last else \
            ("NCHW", "OIHW", "NCHW")
    return ("NDHWC", "DHWIO", "NDHWC") if channel_last else \
        ("NCDHW", "OIDHW", "NCDHW")


def _conv(x, weight, bias, stride, padding, dilation, groups, nd,
          data_format, op_name):
    channel_last = data_format in ("NHWC", "NWC", "NLC", "NDHWC")
    strides = _tuple(stride, nd)
    dil = _tuple(dilation, nd)
    pad = _padding(padding, nd)
    dn_in, dn_w, dn_out = _dims(nd, channel_last)

    def f(a, w, *maybe_b):
        # standard jnp promotion (same as the `x @ w` in F.linear):
        # fp32 input x bf16 weight computes in fp32 — lax.conv just needs
        # both sides pre-cast to the common type
        if a.dtype != w.dtype:
            common = jnp.result_type(a, w)
            a = a.astype(common)
            w = w.astype(common)
        # weight arrives paddle-layout [O, I/g, *k]; lax wants per dn_w
        if channel_last:
            # OIHW -> HWIO etc.
            perm = tuple(range(2, 2 + nd)) + (1, 0)
            w = jnp.transpose(w, perm)
        out = jax.lax.conv_general_dilated(
            a, w, window_strides=strides, padding=pad,
            rhs_dilation=dil, dimension_numbers=(dn_in, dn_w, dn_out),
            feature_group_count=groups,
            preferred_element_type=None)
        if maybe_b:
            b = maybe_b[0]
            shape = [1] * out.ndim
            shape[-1 if channel_last else 1] = b.shape[0]
            out = out + b.reshape(shape)
        return out

    if bias is not None:
        return apply_op(f, x, weight, bias, op_name=op_name)
    return apply_op(f, x, weight, op_name=op_name)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    df = "NWC" if data_format == "NLC" else "NCW"
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1, df,
                 "conv1d")


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2,
                 data_format, "conv2d")


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3,
                 data_format, "conv3d")


def _conv_transpose(x, weight, bias, stride, padding, output_padding,
                    dilation, groups, nd, data_format, op_name):
    channel_last = data_format in ("NHWC", "NWC", "NLC", "NDHWC")
    strides = _tuple(stride, nd)
    dil = _tuple(dilation, nd)
    pad = _padding(padding, nd)
    opad = _tuple(output_padding, nd) if output_padding is not None \
        else (0,) * nd
    dn_in, dn_w, dn_out = _dims(nd, channel_last)

    def f(a, w, *maybe_b):
        # paddle transpose-conv weight: [in_c, out_c/groups, *k]
        # grad-of-conv formulation: lhs_dilation = stride
        if isinstance(pad, str):
            padding_cfg = pad
        else:
            # transposed conv padding: effective pad = k - 1 - p (per side)
            k = w.shape[2:2 + nd]
            padding_cfg = [
                (dil[i] * (k[i] - 1) - pad[i][0],
                 dil[i] * (k[i] - 1) - pad[i][1] + opad[i])
                for i in range(nd)]
        if groups > 1:
            ic, ocg = w.shape[0], w.shape[1]
            wg = w.reshape((groups, ic // groups) + w.shape[1:])
            # flip spatial, swap in/out per group
            wg = jnp.flip(wg, axis=tuple(range(3, 3 + nd)))
            wg = jnp.swapaxes(wg, 1, 2)  # [g, ocg, icg, *k]
            w2 = wg.reshape((groups * ocg, ic // groups) + w.shape[2:])
        else:
            w2 = jnp.swapaxes(w, 0, 1)
            w2 = jnp.flip(w2, axis=tuple(range(2, 2 + nd)))
        if channel_last:
            perm = tuple(range(2, 2 + nd)) + (1, 0)
            w2 = jnp.transpose(w2, perm)
        out = jax.lax.conv_general_dilated(
            a, w2, window_strides=(1,) * nd, padding=padding_cfg,
            lhs_dilation=strides, rhs_dilation=dil,
            dimension_numbers=(dn_in, dn_w, dn_out),
            feature_group_count=groups)
        if maybe_b:
            b = maybe_b[0]
            shape = [1] * out.ndim
            shape[-1 if channel_last else 1] = b.shape[0]
            out = out + b.reshape(shape)
        return out

    if bias is not None:
        return apply_op(f, x, weight, bias, op_name=op_name)
    return apply_op(f, x, weight, op_name=op_name)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCL", name=None):
    df = "NWC" if data_format == "NLC" else "NCW"
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 1, df, "conv1d_transpose")


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 2, data_format,
                           "conv2d_transpose")


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCDHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 3, data_format,
                           "conv3d_transpose")
