"""Common functionals: linear, dropout, embedding, interpolate, etc.
ref: python/paddle/nn/functional/common.py, input.py"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core import fusion as _fusion
from ...core import random as random_mod
from ...core.autograd import apply_op, is_grad_enabled
from ...core.dtype import convert_dtype
from ...core.tensor import Tensor


def _rng_key_tensor() -> Tensor:
    """A fresh PRNG key wrapped as a marked Tensor arg: eager ops consume
    the concrete key; static recording turns the marker into an ("rng", i)
    slot that the Executor refills with a fresh key on every run."""
    t = Tensor(random_mod.next_key())
    t._static_rng = True
    return t


def _linear_impl(a, w, b=None):
    # module-level (stable identity): the eager fast path caches one
    # jitted pair per arity, and fusion (`fusable: epilogue`) re-captures
    # the contraction so a following activation/cast runs as the dot's
    # XLA epilogue
    r = a @ w
    return r if b is None else r + b


_fusion.register_param_impl("linear", _linear_impl)


def linear(x, weight, bias=None, name=None):
    """y = x @ W + b with paddle's [in, out] weight layout
    (ref: python/paddle/nn/functional/common.py linear)."""
    if bias is None:
        return apply_op(_linear_impl, x, weight, op_name="linear",
                        fuse_attrs=())
    return apply_op(_linear_impl, x, weight, bias, op_name="linear",
                    fuse_attrs=())


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None):
    if not training or p == 0.0:
        if training or mode == "upscale_in_train" or p == 0.0:
            return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
        # downscale_in_infer: train applies the raw mask, so eval scales
        # by the keep probability (ref: common.py dropout mode semantics)
        return apply_op(lambda a: (a * (1.0 - p)).astype(a.dtype), x,
                        op_name="dropout")
    if p == 1.0:
        return apply_op(lambda a: jnp.zeros_like(a), x, op_name="dropout")
    # the key rides as a marked arg (not a closure capture) so static
    # replay can substitute a fresh key every Executor.run
    key_t = _rng_key_tensor()

    def f(a, key):
        if axis is None and mode == "upscale_in_train" and a.size > 1:
            # cheap-hash mask (murmur3 finalizer over iota ^ seed): pure
            # fusable elementwise XLA — the compiler rematerializes it in
            # the backward instead of storing masks, same as threefry,
            # but ~10x less ALU (threefry here cost ~35% of a BERT-base
            # step). A Pallas PRNG kernel was measured worse: its custom
            # VJP is opaque to remat, so every dropout OUTPUT had to be
            # stored (+2.4GB on the BERT step -> OOM).
            seed = random_mod.derive_seed(key, jnp.uint32)
            idx = jax.lax.iota(jnp.uint32, a.size).reshape(a.shape)
            h = idx * jnp.uint32(0x9E3779B1) + seed
            h = (h ^ (h >> 16)) * jnp.uint32(0x85EBCA6B)
            h = (h ^ (h >> 13)) * jnp.uint32(0xC2B2AE35)
            h = h ^ (h >> 16)
            thresh = jnp.uint32(min(int(p * (2 ** 32)), 2 ** 32 - 1))
            return jnp.where(h >= thresh, a / (1.0 - p),
                             0.0).astype(a.dtype)
        shape = list(a.shape)
        if axis is not None:
            axes = axis if isinstance(axis, (list, tuple)) else [axis]
            shape = [s if i in axes else 1 for i, s in enumerate(shape)]
        keep = jax.random.bernoulli(key, 1.0 - p, tuple(shape))
        if mode == "upscale_in_train":
            return jnp.where(keep, a / (1.0 - p), 0.0).astype(a.dtype)
        return jnp.where(keep, a, 0.0).astype(a.dtype)
    return apply_op(f, x, key_t, op_name="dropout")


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x
    key_t = _rng_key_tensor()
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale

    def f(a, key):
        keep = jax.random.bernoulli(key, 1.0 - p, a.shape)
        q = 1.0 - p
        a_coef = (q + alpha_p ** 2 * q * p) ** -0.5
        b_coef = -a_coef * alpha_p * p
        return (a_coef * jnp.where(keep, a, alpha_p) + b_coef).astype(a.dtype)
    return apply_op(f, x, key_t, op_name="alpha_dropout")


@jax.custom_vjp
def _embedding_lookup(idx, w):
    return jnp.take(w, idx, axis=0)


def _embedding_lookup_fwd(idx, w):
    # residual w is the parameter the caller already holds — no extra
    # memory pinned, and its shape/dtype are needed in bwd
    return jnp.take(w, idx, axis=0), (idx, w)


# table-size threshold (bytes) above which the embedding dgrad switches
# from scatter-add to a one-hot MXU contraction. XLA's scatter degrades
# sharply on big tables (measured 8K tokens on v5e: 14.7 ms into a
# 229 MB [32000, 3584] table but 88 ms into a 515 MB [50304, 5120] one,
# vs ~21 ms for the equivalent matmul); for small tables the scatter
# still wins because the one-hot contraction pays the full T*V*H flops.
_EMBED_MATMUL_DGRAD_BYTES = 256 * 1024 * 1024
# minimum token-chunk size for the chunked one-hot dgrad (module-level
# so tests can force the multi-chunk accumulation path)
_EMBED_CHUNK_FLOOR = 1024


def _embedding_lookup_bwd(res, g):
    """dW = onehot(idx)ᵀ @ g on the MXU (big-table path only — small
    tables keep jnp.take's native scatter VJP, see embedding()). The
    token dim is chunked so the one-hot operand stays bounded (~256 MB)
    regardless of batch size; chunk contributions accumulate in fp32."""
    idx, w = res
    v, h = w.shape
    flat_idx = idx.reshape(-1)
    flat_g = g.reshape(-1, h)
    t = flat_idx.shape[0]
    chunk = max(_EMBED_CHUNK_FLOOR,
                (_EMBED_MATMUL_DGRAD_BYTES
                 // max(v * flat_g.dtype.itemsize, 1)))
    dw = jnp.zeros((v, h), jnp.float32)
    for start in range(0, t, chunk):
        end = min(start + chunk, t)
        oh = jax.nn.one_hot(flat_idx[start:end], v, dtype=flat_g.dtype)
        dw = dw + jax.lax.dot_general(
            oh, flat_g[start:end], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
    import numpy as _np
    return (_np.zeros(idx.shape, dtype=jax.dtypes.float0),
            dw.astype(w.dtype))


_embedding_lookup.defvjp(_embedding_lookup_fwd, _embedding_lookup_bwd)


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    def f(idx, w):
        if w.size * w.dtype.itemsize >= _EMBED_MATMUL_DGRAD_BYTES:
            out = _embedding_lookup(idx, w)
        else:
            out = jnp.take(w, idx, axis=0)  # native scatter VJP
        if padding_idx is not None:
            mask = (idx == padding_idx)[..., None]
            out = jnp.where(mask, 0.0, out).astype(w.dtype)
        return out
    return apply_op(f, x, weight, op_name="embedding")


def one_hot(x, num_classes, name=None):
    def f(idx):
        return jax.nn.one_hot(idx, num_classes, dtype=jnp.float32)
    return apply_op(f, x, op_name="one_hot")


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    def f(a, b):
        dot = jnp.sum(a * b, axis=axis)
        na = jnp.sqrt(jnp.sum(a * a, axis=axis))
        nb = jnp.sqrt(jnp.sum(b * b, axis=axis))
        return dot / jnp.maximum(na * nb, eps)
    return apply_op(f, x1, x2, op_name="cosine_similarity")


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def f(a):
        n = jnp.sum(jnp.abs(a) ** p, axis=axis, keepdims=True) ** (1.0 / p)
        return a / jnp.maximum(n, epsilon)
    return apply_op(f, x, op_name="normalize")


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    pd = prior_dist._data if isinstance(prior_dist, Tensor) else prior_dist

    def f(lbl):
        k = lbl.shape[-1]
        if pd is None:
            return (1 - epsilon) * lbl + epsilon / k
        return (1 - epsilon) * lbl + epsilon * pd
    return apply_op(f, label, op_name="label_smooth")


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW",
                name=None):
    """ref: python/paddle/nn/functional/common.py interpolate. Uses
    jax.image.resize; supports nearest/bilinear/bicubic/trilinear/area."""
    if isinstance(size, Tensor):
        size = [int(s) for s in np.asarray(size._data)]
    elif size is not None and not isinstance(size, (list, tuple)):
        size = [int(size)]

    def f(a):
        channel_last = data_format in ("NHWC", "NDHWC", "NLC")
        nd = a.ndim - 2
        if channel_last:
            spatial = a.shape[1:-1]
        else:
            spatial = a.shape[2:]
        if size is not None:
            out_spatial = tuple(int(s) for s in size)
        else:
            sf = scale_factor
            if isinstance(sf, Tensor):
                sf = [float(v) for v in np.asarray(sf._data)]
            if not isinstance(sf, (list, tuple)):
                sf = [sf] * nd
            out_spatial = tuple(int(s * f_) for s, f_ in zip(spatial, sf))
        if channel_last:
            out_shape = (a.shape[0],) + out_spatial + (a.shape[-1],)
        else:
            out_shape = a.shape[:2] + out_spatial
        method = {"nearest": "nearest", "bilinear": "bilinear",
                  "bicubic": "bicubic", "trilinear": "trilinear",
                  "linear": "linear", "area": "linear"}[mode]
        return jax.image.resize(a, out_shape, method=method).astype(a.dtype)
    return apply_op(f, x, op_name="interpolate")


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, align_mode=0, data_format="NCHW",
             name=None):
    return interpolate(x, size, scale_factor, mode, align_corners,
                       align_mode, data_format)


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor

    def f(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            oc = c // (r * r)
            a = a.reshape(n, oc, r, r, h, w)
            a = a.transpose(0, 1, 4, 2, 5, 3)
            return a.reshape(n, oc, h * r, w * r)
        n, h, w, c = a.shape
        oc = c // (r * r)
        a = a.reshape(n, h, w, r, r, oc)
        a = a.transpose(0, 1, 3, 2, 4, 5)
        return a.reshape(n, h * r, w * r, oc)
    return apply_op(f, x, op_name="pixel_shuffle")


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = downscale_factor

    def f(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            a = a.reshape(n, c, h // r, r, w // r, r)
            a = a.transpose(0, 1, 3, 5, 2, 4)
            return a.reshape(n, c * r * r, h // r, w // r)
        n, h, w, c = a.shape
        a = a.reshape(n, h // r, r, w // r, r, c)
        a = a.transpose(0, 2, 4, 5, 1, 3)
        return a.reshape(n, h // r, w // r, c * r * r)
    return apply_op(f, x, op_name="pixel_unshuffle")


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    def f(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            a = a.reshape(n, groups, c // groups, h, w)
            return a.transpose(0, 2, 1, 3, 4).reshape(n, c, h, w)
        n, h, w, c = a.shape
        a = a.reshape(n, h, w, groups, c // groups)
        return a.transpose(0, 1, 2, 4, 3).reshape(n, h, w, c)
    return apply_op(f, x, op_name="channel_shuffle")


def bilinear(x1, x2, weight, bias=None, name=None):
    def f(a, b, w, *maybe_bias):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if maybe_bias:
            out = out + maybe_bias[0]
        return out
    if bias is not None:
        return apply_op(f, x1, x2, weight, bias, op_name="bilinear")
    return apply_op(f, x1, x2, weight, op_name="bilinear")


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None):
    """col2im, inverse of unfold."""
    os = output_sizes if isinstance(output_sizes, (list, tuple)) \
        else [output_sizes] * 2
    ks = kernel_sizes if isinstance(kernel_sizes, (list, tuple)) \
        else [kernel_sizes] * 2
    st = strides if isinstance(strides, (list, tuple)) else [strides] * 2
    pd = paddings if isinstance(paddings, (list, tuple)) else [paddings] * 2
    if len(pd) == 2:
        pd = [pd[0], pd[1], pd[0], pd[1]]
    dl = dilations if isinstance(dilations, (list, tuple)) else [dilations] * 2

    def f(a):
        n, ckk, L = a.shape
        c = ckk // (ks[0] * ks[1])
        ph, pw = os[0] + pd[0] + pd[2], os[1] + pd[1] + pd[3]
        oh = (ph - (dl[0] * (ks[0] - 1) + 1)) // st[0] + 1
        ow = (pw - (dl[1] * (ks[1] - 1) + 1)) // st[1] + 1
        a = a.reshape(n, c, ks[0], ks[1], oh, ow)
        out = jnp.zeros((n, c, ph, pw), a.dtype)
        for i in range(ks[0]):
            for j in range(ks[1]):
                hi = i * dl[0]
                wj = j * dl[1]
                out = out.at[:, :, hi:hi + oh * st[0]:st[0],
                             wj:wj + ow * st[1]:st[1]].add(a[:, :, i, j])
        return out[:, :, pd[0]:ph - pd[2], pd[1]:pw - pd[3]]
    return apply_op(f, x, op_name="fold")


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    """ref: python/paddle/nn/functional/distance.py pairwise_distance."""
    def f(a, b):
        d = a - b + epsilon
        if p == float("inf"):
            out = jnp.max(jnp.abs(d), axis=-1, keepdims=keepdim)
        elif p == float("-inf"):
            out = jnp.min(jnp.abs(d), axis=-1, keepdims=keepdim)
        else:
            out = jnp.sum(jnp.abs(d) ** p, axis=-1,
                          keepdims=keepdim) ** (1.0 / p)
        return out
    return apply_op(f, x, y, op_name="pairwise_distance")


def feature_alpha_dropout(x, p=0.5, training=True, name=None):
    """ref: common.py feature_alpha_dropout — alpha dropout with the mask
    shared per feature map (channel dim 1)."""
    if not training or p == 0.0:
        return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
    if not 0 <= p < 1:
        raise ValueError(f"p must be in [0, 1), got {p}")
    from ...core import random as random_mod
    key = random_mod.next_key()
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale

    def f(a):
        mask_shape = a.shape[:2] + (1,) * (a.ndim - 2)
        keep = jax.random.bernoulli(key, 1.0 - p, mask_shape)
        q = 1.0 - p
        a_coef = (q + alpha_p ** 2 * q * (1 - q)) ** -0.5
        b_coef = -a_coef * alpha_p * (1 - q)
        return a_coef * (jnp.where(keep, a, alpha_p)) + b_coef
    return apply_op(f, x, op_name="feature_alpha_dropout")


def zeropad2d(x, padding, data_format="NCHW", name=None):
    """ref: common.py zeropad2d — padding [left, right, top, bottom]."""
    if hasattr(padding, "numpy"):
        padding = padding.numpy().tolist()
    l, r, t, b = [int(v) for v in padding]

    def f(a):
        if data_format == "NCHW":
            return jnp.pad(a, ((0, 0), (0, 0), (t, b), (l, r)))
        return jnp.pad(a, ((0, 0), (t, b), (l, r), (0, 0)))
    return apply_op(f, x, op_name="zeropad2d")
