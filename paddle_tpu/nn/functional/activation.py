"""Activation functionals. ref: python/paddle/nn/functional/activation.py"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core import fusion as _fusion
from ...core.autograd import apply_op

# the canonical epilogue activations are chain-fusable (`fusable: true`
# in ops.yaml): relu/relu6/silu gate on their stable jax.nn identity;
# gelu is parametric (its `approximate` flag rides the program key)
_fusion.register_impl("relu", jax.nn.relu)
_fusion.register_impl("relu6", jax.nn.relu6)
_fusion.register_impl("silu", jax.nn.silu)


def _gelu_impl(a, approximate=False):
    return jax.nn.gelu(a, approximate=approximate)


_fusion.register_param_impl("gelu", _gelu_impl)


def relu(x, name=None):
    return apply_op(jax.nn.relu, x, op_name="relu")


def relu6(x, name=None):
    return apply_op(jax.nn.relu6, x, op_name="relu6")


def leaky_relu(x, negative_slope=0.01, name=None):
    return apply_op(lambda a: jax.nn.leaky_relu(a, negative_slope), x,
                    op_name="leaky_relu")


def prelu(x, weight, data_format="NCHW", name=None):
    def f(a, w):
        if w.size == 1:
            return jnp.where(a >= 0, a, w.reshape(()) * a)
        shape = [1] * a.ndim
        ch_axis = 1 if data_format == "NCHW" else a.ndim - 1
        shape[ch_axis] = w.size
        return jnp.where(a >= 0, a, w.reshape(shape) * a)
    return apply_op(f, x, weight, op_name="prelu")


def elu(x, alpha=1.0, name=None):
    return apply_op(lambda a: jax.nn.elu(a, alpha), x, op_name="elu")


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return apply_op(
        lambda a: scale * jnp.where(a > 0, a, alpha * jnp.expm1(a)), x,
        op_name="selu")


def celu(x, alpha=1.0, name=None):
    return apply_op(lambda a: jax.nn.celu(a, alpha), x, op_name="celu")


def gelu(x, approximate=False, name=None):
    ap = bool(approximate)
    return apply_op(lambda a: _gelu_impl(a, approximate=ap), x,
                    op_name="gelu", fuse_attrs=(("approximate", ap),))


def silu(x, name=None):
    return apply_op(jax.nn.silu, x, op_name="silu")


swish = silu


def mish(x, name=None):
    return apply_op(lambda a: a * jnp.tanh(jax.nn.softplus(a)), x,
                    op_name="mish")


def hardswish(x, name=None):
    return apply_op(
        lambda a: a * jnp.clip(a + 3.0, 0.0, 6.0) / 6.0, x,
        op_name="hardswish")


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return apply_op(lambda a: jnp.clip(slope * a + offset, 0.0, 1.0), x,
                    op_name="hardsigmoid")


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return apply_op(lambda a: jnp.clip(a, min, max), x, op_name="hardtanh")


def hardshrink(x, threshold=0.5, name=None):
    return apply_op(
        lambda a: jnp.where(jnp.abs(a) > threshold, a, 0.0).astype(a.dtype),
        x, op_name="hardshrink")


def softshrink(x, threshold=0.5, name=None):
    return apply_op(
        lambda a: jnp.where(a > threshold, a - threshold,
                            jnp.where(a < -threshold, a + threshold,
                                      0.0)).astype(a.dtype),
        x, op_name="softshrink")


def tanhshrink(x, name=None):
    return apply_op(lambda a: a - jnp.tanh(a), x, op_name="tanhshrink")


def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    return apply_op(
        lambda a: jnp.where(a > threshold, a, value).astype(a.dtype), x,
        op_name="thresholded_relu")


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return apply_op(
        lambda a: jnp.where(beta * a > threshold, a,
                            jax.nn.softplus(beta * a) / beta), x,
        op_name="softplus")


def softsign(x, name=None):
    return apply_op(jax.nn.soft_sign, x, op_name="softsign")


def sigmoid(x, name=None):
    return apply_op(jax.nn.sigmoid, x, op_name="sigmoid")


def log_sigmoid(x, name=None):
    return apply_op(jax.nn.log_sigmoid, x, op_name="log_sigmoid")


def tanh(x, name=None):
    return apply_op(jnp.tanh, x, op_name="tanh")


def softmax(x, axis=-1, dtype=None, name=None):
    from ...core.dtype import convert_dtype
    d = convert_dtype(dtype)

    def f(a):
        if d is not None:
            a = a.astype(d)
        return jax.nn.softmax(a, axis=axis)
    return apply_op(f, x, op_name="softmax")


def log_softmax(x, axis=-1, dtype=None, name=None):
    from ...core.dtype import convert_dtype
    d = convert_dtype(dtype)

    def f(a):
        if d is not None:
            a = a.astype(d)
        return jax.nn.log_softmax(a, axis=axis)
    return apply_op(f, x, op_name="log_softmax")


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ...core import random as random_mod
    key = random_mod.next_key()

    def f(a):
        g = jax.random.gumbel(key, a.shape, a.dtype)
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            y_hard = jnp.zeros_like(y)
            y_hard = jnp.put_along_axis(
                y_hard, idx, jnp.ones_like(idx, y.dtype), axis=axis,
                inplace=False)
            # straight-through: hard value forward, soft gradient backward
            y = y_hard - jax.lax.stop_gradient(y) + y
        return y
    return apply_op(f, x, op_name="gumbel_softmax")


def maxout(x, groups, axis=1, name=None):
    def f(a):
        shape = list(a.shape)
        c = shape[axis]
        shape[axis:axis + 1] = [c // groups, groups]
        return jnp.max(a.reshape(shape), axis=axis + 1)
    return apply_op(f, x, op_name="maxout")


def glu(x, axis=-1, name=None):
    return apply_op(lambda a: jax.nn.glu(a, axis=axis), x, op_name="glu")


def rrelu(x, lower=1.0 / 8.0, upper=1.0 / 3.0, training=True, name=None):
    from ...core import random as random_mod
    if not training:
        mid = (lower + upper) / 2.0
        return leaky_relu(x, mid)
    key = random_mod.next_key()

    def f(a):
        slope = jax.random.uniform(key, a.shape, a.dtype, lower, upper)
        return jnp.where(a >= 0, a, slope * a)
    return apply_op(f, x, op_name="rrelu")


# -- inplace variants ---------------------------------------------------------
# ref: the reference generates relu_/tanh_/... siblings writing into the
# input buffer (python/paddle/nn/functional/activation.py). Tensors wrap
# immutable jax.Arrays, so inplace = compute + buffer swap, the same
# user-visible contract as paddle_tpu.ops.inplace.

def _inplace(fn):
    import functools

    @functools.wraps(fn)
    def wrapper(x, *args, **kwargs):
        from ...core import tensor as tensor_mod
        if tensor_mod._mutation_hook is not None:
            tensor_mod._mutation_hook(x)
        out = fn(x, *args, **kwargs)
        x._data = out._data
        x._node = out._node
        x._out_index = out._out_index
        x.stop_gradient = out.stop_gradient
        return x
    wrapper.__name__ = fn.__name__ + "_"
    wrapper.__qualname__ = fn.__qualname__ + "_"
    return wrapper


relu_ = _inplace(relu)
tanh_ = _inplace(tanh)
elu_ = _inplace(elu)
hardtanh_ = _inplace(hardtanh)
leaky_relu_ = _inplace(leaky_relu)
softmax_ = _inplace(softmax)
thresholded_relu_ = _inplace(thresholded_relu)
