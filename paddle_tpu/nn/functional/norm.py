"""Normalization functionals. ref: python/paddle/nn/functional/norm.py.

These are prime XLA fusion targets; layer_norm/rms_norm additionally have
Pallas fused implementations in paddle_tpu.ops.pallas used on TPU for the
hot transformer path.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.autograd import apply_op
from ...core.tensor import Tensor


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-05,
               name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    n_axes = len(normalized_shape)

    def f(a, *wb):
        axes = tuple(range(a.ndim - n_axes, a.ndim))
        mean = jnp.mean(a.astype(jnp.float32), axis=axes, keepdims=True)
        var = jnp.var(a.astype(jnp.float32), axis=axes, keepdims=True)
        out = (a.astype(jnp.float32) - mean) * jax.lax.rsqrt(var + epsilon)
        i = 0
        if weight is not None:
            out = out * wb[i].astype(jnp.float32)
            i += 1
        if bias is not None:
            out = out + wb[i].astype(jnp.float32)
        return out.astype(a.dtype)

    args = [a for a in (weight, bias) if a is not None]
    return apply_op(f, x, *args, op_name="layer_norm")


def rms_norm(x, weight=None, epsilon=1e-6, name=None):
    """RMSNorm (used by Llama-family). Above-parity with the reference's
    fused_rms_norm (ref: paddle/phi/kernels/fusion/gpu/fused_layernorm*)."""
    def f(a, *w):
        var = jnp.mean(jnp.square(a.astype(jnp.float32)), axis=-1,
                       keepdims=True)
        out = a.astype(jnp.float32) * jax.lax.rsqrt(var + epsilon)
        if w:
            out = out * w[0].astype(jnp.float32)
        return out.astype(a.dtype)
    args = [weight] if weight is not None else []
    return apply_op(f, x, *args, op_name="rms_norm")


import functools


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _bn_train(x, w, b, anchor, axes, eps):
    """Training batch-norm core with a hand-written VJP.

    Autodiff through the mean/var/normalize composition emits ~6 passes
    over the activation in the backward (profiled ~34 ms/step of
    reduce/convert kernels on ResNet-50/v5e); the closed-form BN grad
    needs exactly one two-output reduction pass (Σg, Σg·x) and one
    elementwise pass — the same schedule the reference's fused
    batch_norm_grad_kernel uses (ref: paddle/phi/kernels/gpu/
    batch_norm_grad_kernel.cu).

    The FORWARD stats are one pass too — the r5 roofline attack on the
    ~19 ms/step of BN HBM traffic the ResNet xplane profile blames
    (VERDICT r4 #3): var = E[(x-rm)^2] - E[x-rm]^2, anchored on
    RUNNING_MEAN — an independent [C] input whose broadcast subtraction
    fuses INTO the multi-output reduction, so XLA reads the activation
    exactly once. The cancellation scale drops from the naive form's
    |m|^2 to |m-rm|^2 + σ^2, and rm tracks m across steps (momentum
    EMA), so precision self-heals as training runs. For the cold-anchor
    case (first steps, rm far from a pathological mean) ONE lax.cond
    per BN — predicated on jnp.any over the per-channel badness, so a
    single hostile channel switches the whole call for that step —
    recomputes an exact-centered variance over strided batch rows
    (~1/8-of-batch sample, ~12% rel. var error at stride 8; exact for
    batches <= 8 where the stride clamps to 1). Steady state never
    takes the branch and never reads the sample rows.

    Rejected alternates, all measured on ResNet-50/v5e batch 128
    (shipped form: 2649 img/s): two-pass 2538; Pallas stats kernel
    1918 (the custom call is a fusion barrier with pinned layouts);
    per-channel `where` + always-on sampled repair 2137 (the
    m-dependent sample pass serializes against the main reduction);
    slice-derived anchor 2409 (an anchor computed FROM x splits the
    fused reduction even when pre-reduced to [C]); naive unanchored
    one-pass 2714 but catastrophically wrong for |m| >> σ."""
    y, m, v_unb = _bn_train_fwd_math(x, w, b, anchor, axes, eps)
    return y, m, v_unb


def _bn_train_fwd_math(x, w, b, anchor, axes, eps):
    n = 1
    for a in axes:
        n *= x.shape[a]
    ch_ = [i for i in range(x.ndim) if i not in axes][0]
    shape_ = [1] * x.ndim
    shape_[ch_] = x.shape[ch_]
    a32 = jax.lax.stop_gradient(
        anchor.astype(jnp.float32)).reshape(shape_)
    d = x.astype(jnp.float32) - a32
    # ONE fused multi-output reduction pass over the activation
    s1 = jnp.mean(d, axis=axes)
    s2 = jnp.mean(jnp.square(d), axis=axes)
    m = a32.reshape(-1) + s1
    v_fast = jnp.maximum(s2 - s1 * s1, 0.0)

    # cold-anchor repair (see _bn_train docstring): when any channel's
    # anchor sits too far from its mean for f32, ONE lax.cond branch
    # recomputes an exact-centered variance over strided batch rows
    # (exact when the stride clamps to 1 on small batches); steady
    # state never takes the branch and never reads the rows
    def _exact(_):
        stride = max(1, x.shape[0] // 8)
        xs = x[::stride].astype(jnp.float32)
        mb = m
        for ax_ in sorted(axes):
            mb = jnp.expand_dims(mb, ax_)
        return jnp.mean(jnp.square(xs - mb), axis=axes)

    bad = jnp.any(s1 * s1 > 1e4 * v_fast + 1e-6)
    v = jax.lax.cond(bad, _exact, lambda _: v_fast, None)
    inv = jax.lax.rsqrt(v + eps)
    scale = inv * w.astype(jnp.float32)
    shift = b.astype(jnp.float32) - m * scale
    y = (x * scale.astype(x.dtype).reshape(shape_)
         + shift.astype(x.dtype).reshape(shape_))
    v_unb = v * (n / max(n - 1, 1))
    return y, m, v_unb


def _bn_train_vjp_fwd(x, w, b, anchor, axes, eps):
    y, m, v_unb = _bn_train_fwd_math(x, w, b, anchor, axes, eps)
    return (y, m, v_unb), (x, w, m, v_unb)


def _bn_train_vjp_bwd(axes, eps, res, cts):
    g, g_m, g_v = cts
    x, w, m, v_unb = res
    n = 1
    for a in axes:
        n *= x.shape[a]
    nf = float(n)
    v = v_unb * (max(n - 1, 1) / n)
    inv = jax.lax.rsqrt(v + eps)
    g32 = g.astype(jnp.float32)
    # one pass, two channel reductions (both read g; Σg·x reads x too)
    dbeta = jnp.sum(g32, axis=axes)
    sum_gx = jnp.sum(g32 * x.astype(jnp.float32), axis=axes)
    dgamma = inv * (sum_gx - m * dbeta)
    w32 = w.astype(jnp.float32)
    # dx = A·g + B·x + C  (per-channel A/B/C): closed form of the batch-
    # stat backward, plus the (normally zero) cotangents of the emitted
    # m / v_unbiased outputs
    A = w32 * inv
    B = -w32 * inv * inv * dgamma / nf
    C = -A * dbeta / nf - B * m
    if g_m is not None:
        C = C + g_m / nf
    if g_v is not None:
        coef = 2.0 / max(n - 1, 1)
        B = B + g_v * coef
        C = C - g_v * coef * m
    ch = [i for i in range(x.ndim) if i not in axes][0]
    shape = [1] * x.ndim
    shape[ch] = x.shape[ch]
    dx = (g * A.astype(g.dtype).reshape(shape)
          + x * B.astype(x.dtype).reshape(shape)
          + C.astype(x.dtype).reshape(shape))
    # the anchor is a stop_gradient stats shift: zero cotangent
    return (dx, dgamma.astype(w.dtype), dbeta.astype(w.dtype),
            jnp.zeros(x.shape[ch], jnp.float32))


_bn_train.defvjp(_bn_train_vjp_fwd, _bn_train_vjp_bwd)


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-05,
               data_format="NCHW", use_global_stats=None, name=None):
    """Eager batch_norm; updates running stats in-place on the passed
    Tensors when training (ref: nn/functional/norm.py batch_norm)."""
    channel_axis = 1 if data_format.startswith("NC") else -1

    use_batch_stats = training and not use_global_stats

    def _normalize(a, m, v, wb):
        """Shared normalize + affine body for both stat sources. The
        per-channel math folds to ONE scale + shift vector pair in f32
        (tiny, [C]); the big elementwise apply stays in the input dtype
        so on bf16 activations it is a single fused multiply-add with no
        convert kernels — profiled on ResNet-50/v5e the f32-elementwise
        form cost ~40ms/step of standalone subtract/convert fusions."""
        shape = [1] * a.ndim
        shape[channel_axis] = a.shape[channel_axis]
        scale = jax.lax.rsqrt(v.astype(jnp.float32) + epsilon)
        i = 0
        if weight is not None:
            scale = scale * wb[i].astype(jnp.float32)
            i += 1
        shift = -m.astype(jnp.float32) * scale
        if bias is not None:
            shift = shift + wb[i].astype(jnp.float32)
        return (a * scale.astype(a.dtype).reshape(shape)
                + shift.astype(a.dtype).reshape(shape))

    args = [a for a in (weight, bias) if a is not None]

    if use_batch_stats:
        # batch stats are computed INSIDE the differentiated fn — backward
        # must flow through mean/var (the centering terms), else deep BN
        # stacks get exploding gradients. _bn_train's custom VJP computes
        # that closed-form backward in two passes instead of autodiff's
        # six; m/v ride out as extra outputs so the running-stat update
        # below doesn't recompute the reductions.
        def f_train(a, rm_, *wb):
            axes = tuple(i for i in range(a.ndim)
                         if i != (channel_axis % a.ndim))
            nc = a.shape[channel_axis % a.ndim]
            i = 0
            if weight is not None:
                w_ = wb[i]
                i += 1
            else:
                w_ = jnp.ones((nc,), jnp.float32)
            b_ = wb[i] if bias is not None else jnp.zeros((nc,),
                                                          jnp.float32)
            return _bn_train(a, w_, b_, rm_, axes, epsilon)

        # running_mean rides in as the one-pass variance ANCHOR (see
        # _bn_train); a non-Tensor running mean anchors at zero
        rm_in = running_mean if isinstance(running_mean, Tensor) else \
            Tensor(jnp.zeros((x.shape[channel_axis],), jnp.float32))
        out, bm, bv = apply_op(f_train, x, rm_in, *args,
                               op_name="batch_norm")

        def _upd_mean(old, m):
            return momentum * old + (1 - momentum) * m.astype(old.dtype)

        def _upd_var(old, v):
            return momentum * old + (1 - momentum) * v.astype(old.dtype)

        from ...static.program import current_program
        prog = current_program()
        if prog is not None:
            # recording a static program: the eager mutation below would
            # only ever see the record-time placeholder values, so register
            # the update to run after every Executor.run replay instead
            if isinstance(running_mean, Tensor):
                prog.register_buffer_update(running_mean, bm, _upd_mean)
            if isinstance(running_var, Tensor):
                prog.register_buffer_update(running_var, bv, _upd_var)
            return out
        if isinstance(running_mean, Tensor):
            running_mean._data = _upd_mean(running_mean._data, bm._data)
        if isinstance(running_var, Tensor):
            running_var._data = _upd_var(running_var._data, bv._data)
        return out

    def f(a, m, v, *wb):
        return _normalize(a, m, v, wb)

    return apply_op(f, x, running_mean, running_var, *args,
                    op_name="batch_norm")


def group_norm(x, num_groups, weight=None, bias=None, epsilon=1e-05,
               data_format="NCHW", name=None):
    channel_last = not data_format.startswith("NC")

    def f(a, *wb):
        if channel_last:
            a_m = jnp.moveaxis(a, -1, 1)
        else:
            a_m = a
        n, c = a_m.shape[:2]
        spatial = a_m.shape[2:]
        g = a_m.reshape(n, num_groups, c // num_groups, *spatial)
        axes = tuple(range(2, g.ndim))
        mean = jnp.mean(g.astype(jnp.float32), axis=axes, keepdims=True)
        var = jnp.var(g.astype(jnp.float32), axis=axes, keepdims=True)
        out = (g.astype(jnp.float32) - mean) * jax.lax.rsqrt(var + epsilon)
        out = out.reshape(n, c, *spatial)
        shape = [1, c] + [1] * len(spatial)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        out = out.astype(a.dtype)
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
        return out

    args = [a for a in (weight, bias) if a is not None]
    return apply_op(f, x, *args, op_name="group_norm")


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9, eps=1e-05,
                  data_format="NCHW", name=None):
    def f(a, *wb):
        axes = tuple(range(2, a.ndim))
        mean = jnp.mean(a.astype(jnp.float32), axis=axes, keepdims=True)
        var = jnp.var(a.astype(jnp.float32), axis=axes, keepdims=True)
        out = (a.astype(jnp.float32) - mean) * jax.lax.rsqrt(var + eps)
        shape = [1, a.shape[1]] + [1] * (a.ndim - 2)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        return out.astype(a.dtype)

    args = [a for a in (weight, bias) if a is not None]
    return apply_op(f, x, *args, op_name="instance_norm")


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    def f(a):
        ch_axis = 1 if data_format.startswith("NC") else a.ndim - 1
        sq = jnp.square(a.astype(jnp.float32))
        moved = jnp.moveaxis(sq, ch_axis, -1)
        pad = [(0, 0)] * (moved.ndim - 1) + [(size // 2, (size - 1) // 2)]
        padded = jnp.pad(moved, pad)
        c = moved.shape[-1]
        acc = jnp.stack([padded[..., i:i + c] for i in range(size)],
                        axis=0).sum(0)
        acc = jnp.moveaxis(acc, -1, ch_axis)
        # reference semantics: the window is AVERAGED (its impl is an
        # avg_pool over squares, python/paddle/nn/functional/norm.py
        # local_response_norm), so alpha scales sum/size — not the raw
        # sum (caught by the r5 OpTest batch against the NumPy oracle)
        return (a / jnp.power(k + alpha * acc / size, beta)).astype(
            a.dtype)
    return apply_op(f, x, op_name="local_response_norm")
