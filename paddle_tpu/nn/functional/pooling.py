"""Pooling functionals via lax.reduce_window.
ref: python/paddle/nn/functional/pooling.py"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.autograd import apply_op


def _tuple(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in (v if len(v) == n else list(v) * n))[:n]
    return (int(v),) * n


def _pad_cfg(padding, n):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n and all(isinstance(p, int) for p in padding):
        return [(p, p) for p in padding]
    if len(padding) == 2 * n:
        return [(padding[2 * i], padding[2 * i + 1]) for i in range(n)]
    return [tuple(p) for p in padding]


def _window_tables(spatial, k, s, pads):
    """Static gather tables for strided windows over channel-first input:
    gidx [P, K] flat input index per (output position, window offset),
    valid [P, K] in-bounds mask, out_sp output spatial dims."""
    nd = len(spatial)
    out_sp = [(spatial[i] + pads[i][0] + pads[i][1] - k[i]) // s[i] + 1
              for i in range(nd)]
    coord = np.meshgrid(*[np.arange(out_sp[i]) * s[i] - pads[i][0]
                          for i in range(nd)], indexing="ij")
    offs = np.meshgrid(*[np.arange(k[i]) for i in range(nd)],
                       indexing="ij")
    flat_strides = [int(np.prod(spatial[i + 1:])) for i in range(nd)]
    gidx = np.zeros((int(np.prod(out_sp)), int(np.prod(k))), np.int64)
    valid = np.ones_like(gidx, bool)
    for i in range(nd):
        ci = coord[i].reshape(-1, 1) + offs[i].reshape(1, -1)
        valid &= (ci >= 0) & (ci < spatial[i])
        gidx += np.clip(ci, 0, spatial[i] - 1) * flat_strides[i]
    return np.where(valid, gidx, 0), valid, out_sp


import functools as _functools


@_functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def _maxpool_cvjp(a, k, s, pads):
    """Channel-first max pool with a slice/pad backward.

    XLA differentiates reduce_window(max) into SelectAndScatter, which
    runs on the TPU scalar core — measured 300x slower than the forward
    (14.5s vs 48ms on ResNet-50's stem pool at batch 128 in NCHW). In
    NHWC the situation inverts: SelectAndScatter is lane-parallel there
    (~0.8 ms/step on ResNet-50) while this slice/pad backward measured
    1671 vs 2421 img/s end-to-end — so only the channel-first layout
    routes here (see the dispatch in _pool).
    """
    window = (1, 1) + k
    strides = (1, 1) + s
    neg = (-jnp.inf if jnp.issubdtype(a.dtype, jnp.floating)
           else jnp.iinfo(a.dtype).min)
    return jax.lax.reduce_window(
        a, neg, jax.lax.max, window, strides,
        [(0, 0), (0, 0)] + [tuple(p) for p in pads])


def _maxpool_cvjp_fwd(a, k, s, pads):
    return _maxpool_cvjp(a, k, s, pads), a


def _maxpool_cvjp_bwd(k, s, pads, a, g):
    """Backward from shifted strided slices + dilated pads only — no
    gather, no scatter (both serialize on TPU at these shapes, like the
    SelectAndScatter this replaces). For each window offset: compare the
    offset's strided input slice against the pooled max (first-match
    tie-breaking, the torch/paddle contract), place the matched cotangent
    back at that offset with an interior-dilated lax.pad, accumulate."""
    nd = len(k)
    neg = (-jnp.inf if jnp.issubdtype(a.dtype, jnp.floating)
           else jnp.iinfo(a.dtype).min)
    full_pad = [(0, 0), (0, 0)] + [tuple(p) for p in pads]
    ap = jnp.pad(a, full_pad, constant_values=neg)
    out = _maxpool_cvjp(a, k, s, pads)
    out_sp = out.shape[2:]
    taken = jnp.zeros(out.shape, bool)
    dxp = jnp.zeros(ap.shape, jnp.float32)
    g32 = g.astype(jnp.float32)
    for koff in np.ndindex(*k):
        sl = tuple(
            slice(koff[d], koff[d] + (out_sp[d] - 1) * s[d] + 1, s[d])
            for d in range(nd))
        x_sl = ap[(slice(None), slice(None)) + sl]
        match = (x_sl == out) & (~taken)
        taken = taken | match
        contrib = jnp.where(match, g32, 0.0)
        pad_cfg = [(0, 0, 0), (0, 0, 0)] + [
            (koff[d],
             ap.shape[2 + d] - koff[d] - ((out_sp[d] - 1) * s[d] + 1),
             s[d] - 1)
            for d in range(nd)]
        dxp = dxp + jax.lax.pad(contrib, jnp.float32(0), pad_cfg)
    inner = tuple(slice(pads[d][0], pads[d][0] + a.shape[2 + d])
                  for d in range(nd))
    dx = dxp[(slice(None), slice(None)) + inner]
    return (dx.astype(g.dtype),)


_maxpool_cvjp.defvjp(_maxpool_cvjp_fwd, _maxpool_cvjp_bwd)


def _pool(x, kernel, stride, padding, nd, data_format, reducer, init,
          op_name, ceil_mode=False, exclusive=True):
    channel_last = data_format in ("NHWC", "NWC", "NLC", "NDHWC")
    k = _tuple(kernel, nd)
    s = _tuple(stride if stride is not None else kernel, nd)
    pad = _pad_cfg(padding, nd)

    def f(a):
        if channel_last:
            window = (1,) + k + (1,)
            strides = (1,) + s + (1,)
            spatial_off = 1
        else:
            window = (1, 1) + k
            strides = (1, 1) + s
            spatial_off = 2
        if isinstance(pad, str):
            pad_cfg = pad
        else:
            full = [(0, 0)] * a.ndim
            for i in range(nd):
                full[spatial_off + i] = pad[i]
            if ceil_mode:
                # extend right pad so the last partial window is included
                for i in range(nd):
                    size = a.shape[spatial_off + i]
                    lo, hi = full[spatial_off + i]
                    total = size + lo + hi - k[i]
                    rem = total % s[i]
                    if rem != 0:
                        full[spatial_off + i] = (lo, hi + (s[i] - rem))
            pad_cfg = full
        if reducer == "max":
            # custom-VJP path only for channel-first: NCHW
            # SelectAndScatter grad is catastrophic on the scalar core
            # (14.5 s vs 48 ms at ResNet stem shapes) while the slice/pad
            # backward is fast. In NHWC the situation inverts — XLA's
            # SelectAndScatter is lane-parallel there (~0.8 ms/step on
            # ResNet-50) and the 9-offset slice/pad backward measured
            # 1671 vs 2421 img/s end-to-end, so NHWC keeps the native
            # gradient.
            if not channel_last and not isinstance(pad_cfg, str):
                sp_pads = tuple(
                    tuple(p) for p in
                    pad_cfg[spatial_off:spatial_off + nd])
                out = _maxpool_cvjp(a, k, s, sp_pads)
            else:
                out = jax.lax.reduce_window(
                    a, -jnp.inf if jnp.issubdtype(a.dtype, jnp.floating)
                    else jnp.iinfo(a.dtype).min,
                    jax.lax.max, window, strides,
                    pad_cfg if not isinstance(pad_cfg, str) else pad_cfg)
        else:  # mean
            summed = jax.lax.reduce_window(
                a.astype(jnp.float32), 0.0, jax.lax.add, window, strides,
                pad_cfg)
            if exclusive and not isinstance(pad_cfg, str):
                counts = jax.lax.reduce_window(
                    jnp.ones_like(a, jnp.float32), 0.0, jax.lax.add,
                    window, strides, pad_cfg)
                out = (summed / counts).astype(a.dtype)
            else:
                out = (summed / float(np.prod(k))).astype(a.dtype)
        return out
    return apply_op(f, x, op_name=op_name)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    if return_mask:
        return _max_pool_with_mask(x, kernel_size, stride, padding, 1,
                                   "max_pool1d", ceil_mode,
                                   channel_last=data_format == "NLC")
    df = "NWC" if data_format == "NLC" else "NCW"
    return _pool(x, kernel_size, stride, padding, 1, df, "max", None,
                 "max_pool1d", ceil_mode)


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    if return_mask:
        return _max_pool_with_mask(x, kernel_size, stride, padding, 2,
                                   "max_pool2d", ceil_mode,
                                   channel_last=data_format == "NHWC")
    return _pool(x, kernel_size, stride, padding, 2, data_format, "max",
                 None, "max_pool2d", ceil_mode)


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    if return_mask:
        return _max_pool_with_mask(x, kernel_size, stride, padding, 3,
                                   "max_pool3d", ceil_mode,
                                   channel_last=data_format == "NDHWC")
    return _pool(x, kernel_size, stride, padding, 3, data_format, "max",
                 None, "max_pool3d", ceil_mode)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None):
    df = "NWC" if data_format == "NLC" else "NCW"
    return _pool(x, kernel_size, stride, padding, 1, df, "mean", None,
                 "avg_pool1d", ceil_mode, exclusive)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    return _pool(x, kernel_size, stride, padding, 2, data_format, "mean",
                 None, "avg_pool2d", ceil_mode, exclusive)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    return _pool(x, kernel_size, stride, padding, 3, data_format, "mean",
                 None, "avg_pool3d", ceil_mode, exclusive)


def _adaptive_pool(x, output_size, nd, data_format, mode, op_name):
    channel_last = data_format in ("NHWC", "NWC", "NLC", "NDHWC")
    os = _tuple(output_size, nd)

    def f(a):
        spatial_off = 1 if channel_last else 2
        in_sizes = [a.shape[spatial_off + i] for i in range(nd)]
        out = a
        # adaptive pooling = per-dim variable windows; implement via mean/max
        # over index buckets (equal splits when divisible, else gather)
        for d in range(nd):
            axis = spatial_off + d
            n_in, n_out = in_sizes[d], os[d]
            if n_out is None:
                continue
            if n_in % n_out == 0:
                k = n_in // n_out
                new_shape = (out.shape[:axis] + (n_out, k) +
                             out.shape[axis + 1:])
                r = out.reshape(new_shape)
                out = (jnp.max(r, axis=axis + 1) if mode == "max"
                       else jnp.mean(r.astype(jnp.float32),
                                     axis=axis + 1).astype(a.dtype))
            else:
                starts = [int(np.floor(i * n_in / n_out))
                          for i in range(n_out)]
                ends = [int(np.ceil((i + 1) * n_in / n_out))
                        for i in range(n_out)]
                pieces = []
                for st, en in zip(starts, ends):
                    sl = [slice(None)] * out.ndim
                    sl[axis] = slice(st, en)
                    seg = out[tuple(sl)]
                    pieces.append(
                        jnp.max(seg, axis=axis, keepdims=True)
                        if mode == "max" else
                        jnp.mean(seg.astype(jnp.float32), axis=axis,
                                 keepdims=True).astype(a.dtype))
                out = jnp.concatenate(pieces, axis=axis)
        return out
    return apply_op(f, x, op_name=op_name)


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool(x, output_size, 1, "NCW", "avg",
                          "adaptive_avg_pool1d")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool(x, output_size, 2, data_format, "avg",
                          "adaptive_avg_pool2d")


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool(x, output_size, 3, data_format, "avg",
                          "adaptive_avg_pool3d")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 1, "NCW", "max",
                          "adaptive_max_pool1d")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 2, "NCHW", "max",
                          "adaptive_max_pool2d")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 3, "NCDHW", "max",
                          "adaptive_max_pool3d")


# -- mask-returning max pooling + unpooling ----------------------------------
# ref: python/paddle/nn/functional/pooling.py max_pool2d(return_mask=True) /
# max_unpool2d. The mask holds flat spatial indices into the input (per
# N, C), the contract the reference's unpool kernels consume
# (phi/kernels/impl/unpool_kernel_impl.h).

def _max_pool_with_mask(x, kernel, stride, padding, nd, op_name,
                        ceil_mode=False, channel_last=False):
    """NCX layouts only — the reference likewise rejects channel-last
    when return_mask=True."""
    if channel_last:
        raise ValueError(
            f"{op_name}(return_mask=True) only supports channel-first "
            f"layouts (NCL/NCHW/NCDHW), matching the reference unpool "
            f"contract")
    k = _tuple(kernel, nd)
    s = _tuple(stride if stride is not None else kernel, nd)
    pad = _pad_cfg(padding, nd)
    if isinstance(pad, str):
        raise ValueError(f"{op_name}(return_mask=True) needs numeric padding")

    def f(a):
        spatial = a.shape[2:]
        # window geometry is shape-static: host-side index tables
        # (_window_tables) keep values in their native dtype and indices
        # exact — no float round-trips
        pads = [tuple(p) for p in pad]
        if ceil_mode:
            for i in range(nd):
                lo, hi = pads[i]
                rem = (spatial[i] + lo + hi - k[i]) % s[i]
                if rem != 0:
                    pads[i] = (lo, hi + (s[i] - rem))
        gidx, valid, out_sp = _window_tables(spatial, k, s, pads)
        n, c = a.shape[:2]
        flat = a.reshape(n, c, -1)
        wins = flat[:, :, jnp.asarray(gidx)]          # [N, C, P, K] native
        neg = (-jnp.inf if jnp.issubdtype(a.dtype, jnp.floating)
               else jnp.iinfo(a.dtype).min)
        wins = jnp.where(jnp.asarray(valid)[None, None], wins, neg)
        arg = jnp.argmax(wins, axis=-1)               # [N, C, P]
        vals = jnp.take_along_axis(wins, arg[..., None], -1)[..., 0]
        mask = jnp.asarray(gidx.astype(np.int32))[
            jnp.arange(gidx.shape[0])[None, None], arg]
        return (vals.reshape(n, c, *out_sp).astype(a.dtype),
                mask.reshape(n, c, *out_sp))

    return apply_op(f, x, op_name=op_name)


def _max_unpool(x, indices, kernel, stride, padding, output_size, nd,
                op_name):
    k = _tuple(kernel, nd)
    s = _tuple(stride if stride is not None else kernel, nd)
    p = _tuple(padding, nd)

    def f(a, idx):
        n, c, *in_sp = a.shape
        if output_size is not None:
            out_sp = list(_tuple(output_size, nd))
        else:
            out_sp = [(in_sp[i] - 1) * s[i] - 2 * p[i] + k[i]
                      for i in range(nd)]
        flat = jnp.zeros((n, c, int(np.prod(out_sp))), a.dtype)
        ii = idx.reshape(n, c, -1).astype(jnp.int32)
        vv = a.reshape(n, c, -1)
        flat = jax.vmap(jax.vmap(
            lambda z, i, v: z.at[i].set(v)))(flat, ii, vv)
        return flat.reshape(n, c, *out_sp)

    return apply_op(f, x, indices, op_name=op_name)


def _trim_output_size(output_size, nd):
    """Accept both the spatial form [*spatial] and the full form
    [N, C, *spatial] the reference allows."""
    if output_size is not None and len(output_size) == nd + 2:
        return list(output_size)[2:]
    return output_size


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    """ref: pooling.py max_unpool1d."""
    return _max_unpool(x, indices, kernel_size, stride, padding,
                       _trim_output_size(output_size, 1), 1, "max_unpool1d")


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    """ref: pooling.py max_unpool2d."""
    return _max_unpool(x, indices, kernel_size, stride, padding,
                       _trim_output_size(output_size, 2), 2, "max_unpool2d")


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    """ref: pooling.py max_unpool3d."""
    return _max_unpool(x, indices, kernel_size, stride, padding,
                       _trim_output_size(output_size, 3), 3, "max_unpool3d")


def lp_pool1d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCL", name=None):
    """Power-average pooling: (sum x^p)^(1/p). ref: pooling.py lp_pool1d."""
    return _lp_pool(x, norm_type, kernel_size, stride, padding, 1,
                    "NWC" if data_format == "NLC" else "NCW", ceil_mode,
                    "lp_pool1d")


def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCHW", name=None):
    """ref: pooling.py lp_pool2d."""
    return _lp_pool(x, norm_type, kernel_size, stride, padding, 2,
                    data_format, ceil_mode, "lp_pool2d")


def _lp_pool(x, p, kernel, stride, padding, nd, data_format, ceil_mode,
             op_name):
    p = float(p)
    if p == float("inf"):
        return _pool(x, kernel, stride, padding, nd, data_format, "max",
                     None, op_name, ceil_mode)
    k = _tuple(kernel, nd)
    # (sum_w x^p)^(1/p) = (mean * count)^(1/p); reuse the sum path
    xp = apply_op(lambda a: jnp.power(a, p), x, op_name=f"{op_name}_pow")
    pooled = _pool(xp, kernel, stride, padding, nd, data_format, "mean",
                   None, op_name, ceil_mode, exclusive=False)
    return apply_op(
        lambda a: jnp.power(a * float(np.prod(k)), 1.0 / p),
        pooled, op_name=f"{op_name}_root")


def _fractional_starts(n_in, n_out, u):
    alpha = n_in / n_out
    starts = np.ceil(alpha * (np.arange(n_out) + u)).astype(np.int64) - 1
    ends = np.ceil(alpha * (np.arange(n_out) + 1 + u)).astype(np.int64) - 1
    starts = np.clip(starts, 0, n_in - 1)
    ends = np.clip(ends, 1, n_in)
    return starts, ends


def _fractional_max_pool(x, output_size, kernel_size, random_u, return_mask,
                         nd, op_name):
    """ref: pooling.py fractional_max_pool2d/3d (Graham 2015):
    start=ceil(alpha*(i+u))-1, end=ceil(alpha*(i+1+u))-1 per dim;
    kernel_size overrides the window length when given."""
    if random_u is None:
        # framework-seeded RNG (paddle.seed reproducibility), like every
        # other stochastic op
        from ...core import random as random_mod
        u = float(np.clip(np.asarray(
            jax.random.uniform(random_mod.next_key(), ())),
            1e-6, 1.0 - 1e-6))
    else:
        u = float(random_u)
        if not 0.0 < u < 1.0:
            raise ValueError(f"random_u must be in (0, 1), got {u}")
    os = _tuple(output_size, nd)
    ks = _tuple(kernel_size, nd) if kernel_size is not None else None

    def f(a):
        spatial = a.shape[2:]
        # per-dim gather of variable windows; windows are data-independent
        # (host-computed index tables), so this stays jit-friendly
        tables = []
        for d in range(nd):
            n_in, n_out = spatial[d], os[d] if os[d] else spatial[d]
            st, en = _fractional_starts(n_in, n_out, u)
            if ks is not None:
                en = np.minimum(st + ks[d], n_in)
            tables.append((st, en))
        # reduce one dim at a time via segment max over gathered slices
        cur = a
        for d in range(nd):
            axis = 2 + d
            st, en = tables[d]
            maxw = int((en - st).max())
            # gather windows: for each output index, take maxw elements
            # starting at st (clamped), mask beyond en
            gidx = np.minimum(st[:, None] + np.arange(maxw)[None, :],
                              cur.shape[axis] - 1)
            valid = (st[:, None] + np.arange(maxw)[None, :]) < en[:, None]
            g = jnp.take(cur, jnp.asarray(gidx.reshape(-1)), axis=axis)
            new_shape = (cur.shape[:axis] + (len(st), maxw) +
                         cur.shape[axis + 1:])
            g = g.reshape(new_shape)
            neg = (-jnp.inf if jnp.issubdtype(a.dtype, jnp.floating)
                   else jnp.iinfo(a.dtype).min)
            vshape = [1] * g.ndim
            vshape[axis], vshape[axis + 1] = valid.shape
            g = jnp.where(jnp.asarray(valid).reshape(vshape), g, neg)
            cur = jnp.max(g, axis=axis + 1)
        if not return_mask:
            return cur
        # mask: recompute flat argmax indices by comparing to input values
        # window-by-window (correctness path; mask consumers are unpool-ish)
        # recompute with flat input indices carried through the same
        # per-dim argmax chain
        cur2 = a
        idxs = jnp.broadcast_to(
            jnp.arange(int(np.prod(spatial))).reshape(spatial), a.shape)
        curi = idxs
        for d in range(nd):
            axis = 2 + d
            st, en = tables[d]
            maxw = int((en - st).max())
            gidx = np.minimum(st[:, None] + np.arange(maxw)[None, :],
                              cur2.shape[axis] - 1)
            valid = (st[:, None] + np.arange(maxw)[None, :]) < en[:, None]
            gv = jnp.take(cur2, jnp.asarray(gidx.reshape(-1)), axis=axis)
            gi = jnp.take(curi, jnp.asarray(gidx.reshape(-1)), axis=axis)
            new_shape = (cur2.shape[:axis] + (len(st), maxw) +
                         cur2.shape[axis + 1:])
            gv = gv.reshape(new_shape)
            gi = gi.reshape(new_shape)
            neg = (-jnp.inf if jnp.issubdtype(a.dtype, jnp.floating)
                   else jnp.iinfo(a.dtype).min)
            vshape = [1] * gv.ndim
            vshape[axis], vshape[axis + 1] = valid.shape
            gv = jnp.where(jnp.asarray(valid).reshape(vshape), gv, neg)
            arg = jnp.argmax(gv, axis=axis + 1, keepdims=True)
            cur2 = jnp.take_along_axis(gv, arg, axis=axis + 1).squeeze(
                axis + 1)
            curi = jnp.take_along_axis(gi, arg, axis=axis + 1).squeeze(
                axis + 1)
        return cur, curi.astype(jnp.int32)

    return apply_op(f, x, op_name=op_name)


def fractional_max_pool2d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    """ref: pooling.py fractional_max_pool2d."""
    return _fractional_max_pool(x, output_size, kernel_size, random_u,
                                return_mask, 2, "fractional_max_pool2d")


def fractional_max_pool3d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    """ref: pooling.py fractional_max_pool3d."""
    return _fractional_max_pool(x, output_size, kernel_size, random_u,
                                return_mask, 3, "fractional_max_pool3d")
