"""Pooling functionals via lax.reduce_window.
ref: python/paddle/nn/functional/pooling.py"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.autograd import apply_op


def _tuple(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in (v if len(v) == n else list(v) * n))[:n]
    return (int(v),) * n


def _pad_cfg(padding, n):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n and all(isinstance(p, int) for p in padding):
        return [(p, p) for p in padding]
    if len(padding) == 2 * n:
        return [(padding[2 * i], padding[2 * i + 1]) for i in range(n)]
    return [tuple(p) for p in padding]


def _pool(x, kernel, stride, padding, nd, data_format, reducer, init,
          op_name, ceil_mode=False, exclusive=True):
    channel_last = data_format in ("NHWC", "NWC", "NLC", "NDHWC")
    k = _tuple(kernel, nd)
    s = _tuple(stride if stride is not None else kernel, nd)
    pad = _pad_cfg(padding, nd)

    def f(a):
        if channel_last:
            window = (1,) + k + (1,)
            strides = (1,) + s + (1,)
            spatial_off = 1
        else:
            window = (1, 1) + k
            strides = (1, 1) + s
            spatial_off = 2
        if isinstance(pad, str):
            pad_cfg = pad
        else:
            full = [(0, 0)] * a.ndim
            for i in range(nd):
                full[spatial_off + i] = pad[i]
            if ceil_mode:
                # extend right pad so the last partial window is included
                for i in range(nd):
                    size = a.shape[spatial_off + i]
                    lo, hi = full[spatial_off + i]
                    total = size + lo + hi - k[i]
                    rem = total % s[i]
                    if rem != 0:
                        full[spatial_off + i] = (lo, hi + (s[i] - rem))
            pad_cfg = full
        if reducer == "max":
            out = jax.lax.reduce_window(
                a, -jnp.inf if jnp.issubdtype(a.dtype, jnp.floating)
                else jnp.iinfo(a.dtype).min,
                jax.lax.max, window, strides,
                pad_cfg if not isinstance(pad_cfg, str) else pad_cfg)
        else:  # mean
            summed = jax.lax.reduce_window(
                a.astype(jnp.float32), 0.0, jax.lax.add, window, strides,
                pad_cfg)
            if exclusive and not isinstance(pad_cfg, str):
                counts = jax.lax.reduce_window(
                    jnp.ones_like(a, jnp.float32), 0.0, jax.lax.add,
                    window, strides, pad_cfg)
                out = (summed / counts).astype(a.dtype)
            else:
                out = (summed / float(np.prod(k))).astype(a.dtype)
        return out
    return apply_op(f, x, op_name=op_name)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    df = "NWC" if data_format == "NLC" else "NCW"
    return _pool(x, kernel_size, stride, padding, 1, df, "max", None,
                 "max_pool1d", ceil_mode)


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    return _pool(x, kernel_size, stride, padding, 2, data_format, "max",
                 None, "max_pool2d", ceil_mode)


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    return _pool(x, kernel_size, stride, padding, 3, data_format, "max",
                 None, "max_pool3d", ceil_mode)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None):
    df = "NWC" if data_format == "NLC" else "NCW"
    return _pool(x, kernel_size, stride, padding, 1, df, "mean", None,
                 "avg_pool1d", ceil_mode, exclusive)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    return _pool(x, kernel_size, stride, padding, 2, data_format, "mean",
                 None, "avg_pool2d", ceil_mode, exclusive)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    return _pool(x, kernel_size, stride, padding, 3, data_format, "mean",
                 None, "avg_pool3d", ceil_mode, exclusive)


def _adaptive_pool(x, output_size, nd, data_format, mode, op_name):
    channel_last = data_format in ("NHWC", "NWC", "NLC", "NDHWC")
    os = _tuple(output_size, nd)

    def f(a):
        spatial_off = 1 if channel_last else 2
        in_sizes = [a.shape[spatial_off + i] for i in range(nd)]
        out = a
        # adaptive pooling = per-dim variable windows; implement via mean/max
        # over index buckets (equal splits when divisible, else gather)
        for d in range(nd):
            axis = spatial_off + d
            n_in, n_out = in_sizes[d], os[d]
            if n_out is None:
                continue
            if n_in % n_out == 0:
                k = n_in // n_out
                new_shape = (out.shape[:axis] + (n_out, k) +
                             out.shape[axis + 1:])
                r = out.reshape(new_shape)
                out = (jnp.max(r, axis=axis + 1) if mode == "max"
                       else jnp.mean(r.astype(jnp.float32),
                                     axis=axis + 1).astype(a.dtype))
            else:
                starts = [int(np.floor(i * n_in / n_out))
                          for i in range(n_out)]
                ends = [int(np.ceil((i + 1) * n_in / n_out))
                        for i in range(n_out)]
                pieces = []
                for st, en in zip(starts, ends):
                    sl = [slice(None)] * out.ndim
                    sl[axis] = slice(st, en)
                    seg = out[tuple(sl)]
                    pieces.append(
                        jnp.max(seg, axis=axis, keepdims=True)
                        if mode == "max" else
                        jnp.mean(seg.astype(jnp.float32), axis=axis,
                                 keepdims=True).astype(a.dtype))
                out = jnp.concatenate(pieces, axis=axis)
        return out
    return apply_op(f, x, op_name=op_name)


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool(x, output_size, 1, "NCW", "avg",
                          "adaptive_avg_pool1d")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool(x, output_size, 2, data_format, "avg",
                          "adaptive_avg_pool2d")


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool(x, output_size, 3, data_format, "avg",
                          "adaptive_avg_pool3d")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 1, "NCW", "max",
                          "adaptive_max_pool1d")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 2, "NCHW", "max",
                          "adaptive_max_pool2d")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 3, "NCDHW", "max",
                          "adaptive_max_pool3d")
