"""Extension functionals: sequence_mask, gather_tree, sparse_attention,
class_center_sample.

ref: python/paddle/nn/functional/extension.py:56 (sequence_mask), :149
(gather_tree); common.py:2372 (class_center_sample);
input.py (sparse_attention in the reference op zoo).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.autograd import apply_op
from ...core.tensor import Tensor

__all__ = ["sequence_mask", "gather_tree", "sparse_attention",
           "class_center_sample"]


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """ref: extension.py:56 — mask[i, ..., j] = j < x[i, ...]."""
    from ...core.dtype import convert_dtype
    if maxlen is None:
        data = x._data if isinstance(x, Tensor) else np.asarray(x)
        maxlen = int(np.asarray(data).max())
    jd = convert_dtype(dtype)

    def f(lens):
        ar = jnp.arange(maxlen)
        return (ar < lens[..., None]).astype(jd)

    return apply_op(f, x, op_name="sequence_mask")


def gather_tree(ids, parents):
    """ref: extension.py:149 gather_tree — backtrace beam-search ancestry.
    ids/parents: [max_time, batch, beam]."""
    def f(idv, par):
        t_max = idv.shape[0]
        beam = idv.shape[2]

        def step(carry, t_inp):
            beams = carry                      # [batch, beam] parent ptrs
            ids_t, par_t = t_inp
            out_t = jnp.take_along_axis(ids_t, beams, axis=1)
            beams = jnp.take_along_axis(par_t, beams, axis=1)
            return beams, out_t

        init = jnp.broadcast_to(jnp.arange(beam, dtype=par.dtype),
                                idv.shape[1:])
        # walk from the last step backwards
        rev_ids = idv[::-1]
        rev_par = par[::-1]
        _, outs = jax.lax.scan(step, init, (rev_ids, rev_par))
        return outs[::-1]

    return apply_op(f, ids, parents, op_name="gather_tree")


def sparse_attention(query, key, value, sparse_csr_offset,
                     sparse_csr_columns, key_padding_mask=None,
                     attn_mask=None, name=None):
    """Block-sparse attention given a per-(batch, head) CSR pattern.

    ref: the reference's sparse_attention op (phi sparse attention kernel).
    TPU-native fallback: materialize the CSR pattern as a dense mask and
    let XLA fuse the masked softmax — correct for any pattern; a Pallas
    tile-skipping kernel is the perf path for real block-sparse layouts.
    q/k/v: [B, H, M, D]; offset: [B, H, M+1]; columns: [B, H, nnz].
    """
    def f(q, k, v, off, cols, *rest):
        b, h, m, d = q.shape
        nnz = cols.shape[-1]
        # row id of each nnz entry: searchsorted over the offset vector
        def row_of(off_1d):
            return jnp.searchsorted(off_1d, jnp.arange(nnz), side="right") - 1
        rows = jax.vmap(jax.vmap(row_of))(off)        # [B, H, nnz]
        mask = jnp.zeros((b, h, m, m), jnp.bool_)
        bidx = jnp.arange(b)[:, None, None]
        hidx = jnp.arange(h)[None, :, None]
        mask = mask.at[bidx, hidx, rows, cols].set(True)
        scores = jnp.einsum("bhmd,bhnd->bhmn", q, k) / jnp.sqrt(
            jnp.asarray(d, q.dtype))
        neg = jnp.asarray(-1e30, scores.dtype)
        scores = jnp.where(mask, scores, neg)
        i = 0
        if key_padding_mask is not None:
            kpm = rest[i]; i += 1
            scores = jnp.where(kpm[:, None, None, :] != 0, scores, neg)
        if attn_mask is not None:
            am = rest[i]; i += 1
            scores = jnp.where(am != 0, scores, neg)
        probs = jax.nn.softmax(scores, axis=-1)
        # fully-masked rows produce uniform softmax over -1e30 → zero out
        any_valid = jnp.any(mask, axis=-1, keepdims=True)
        probs = jnp.where(any_valid, probs, 0.0)
        return jnp.einsum("bhmn,bhnd->bhmd", probs, v)

    extra = [t for t in (key_padding_mask, attn_mask) if t is not None]
    return apply_op(f, query, key, value, sparse_csr_offset,
                    sparse_csr_columns, *extra, op_name="sparse_attention")


def class_center_sample(label, num_classes, num_samples, group=None):
    """PartialFC class-center sampling (arXiv:2010.05222).

    ref: common.py:2372 class_center_sample. Keeps all positive class
    centers, pads with uniformly sampled negatives to num_samples, and
    remaps labels into the sampled index space. Under a model-parallel
    group each rank samples within its own class shard after pooling the
    positives across ranks (all_gather_object). Host-side (data-dependent
    output size), eager-only — as in the reference, this feeds the data
    pipeline of margin_cross_entropy.
    """
    from ...distributed import collective as coll
    from ...core import random as random_mod

    lab = np.asarray(label._data if isinstance(label, Tensor) else label)
    mp = group is not False
    g = coll._get_group(group if group is not True else None) if mp else None

    if g is not None and g.nranks > 1:
        pooled = []
        coll.all_gather_object(pooled, lab.tolist(), group=g)
        all_pos = np.unique(np.concatenate([np.asarray(p) for p in pooled]))
        # this rank's class shard: [offset, offset + num_classes)
        sizes = []
        coll.all_gather_object(sizes, int(num_classes), group=g)
        offset = sum(sizes[:g.rank])
    else:
        all_pos = np.unique(lab)
        offset = 0

    local_pos = all_pos[(all_pos >= offset) & (all_pos < offset + num_classes)]
    local_pos = local_pos - offset
    n_pos = len(local_pos)
    seed = int(np.asarray(
        jax.random.randint(random_mod.next_key(), (), 0, 2 ** 31 - 1)))
    rng = np.random.default_rng(seed)
    if n_pos >= num_samples:
        sampled = np.sort(local_pos)
    else:
        neg_pool = np.setdiff1d(np.arange(num_classes), local_pos,
                                assume_unique=False)
        extra = rng.choice(neg_pool, size=num_samples - n_pos, replace=False)
        sampled = np.sort(np.concatenate([local_pos, extra]))
    # remap: global label -> position in the (global) sampled order
    if g is not None and g.nranks > 1:
        all_sampled = []
        coll.all_gather_object(all_sampled, (sampled + offset).tolist(),
                               group=g)
        flat = np.concatenate([np.asarray(s) for s in all_sampled])
    else:
        flat = sampled
    lut = {int(c): i for i, c in enumerate(flat)}
    remapped = np.asarray([lut.get(int(v), -1) for v in lab.reshape(-1)],
                          dtype=lab.dtype).reshape(lab.shape)
    return (Tensor(jnp.asarray(remapped)),
            Tensor(jnp.asarray(sampled.astype(lab.dtype))))
