"""Seq2seq decoding: BeamSearchDecoder + dynamic_decode.

ref: python/paddle/nn/decode.py:161 (BeamSearchDecoder), :1090
(dynamic_decode). Host-driven decode loop (the reference's dynamic
while_op path collapses to a Python loop under eager); each step's math is
jnp so the per-step programs jit-cache. Final sequences are reconstructed
with nn.functional.gather_tree.
"""
from __future__ import annotations

import collections

import jax
import jax.numpy as jnp
import numpy as np

from ..core.autograd import apply_op
from ..core.tensor import Tensor
from .layer import Layer

__all__ = ["Decoder", "BeamSearchDecoder", "dynamic_decode"]


def _map_structure(fn, *structs):
    s0 = structs[0]
    if isinstance(s0, (list, tuple)):
        return type(s0)(_map_structure(fn, *xs) for xs in zip(*structs))
    return fn(*structs)


def _data(t):
    return t._data if isinstance(t, Tensor) else jnp.asarray(t)


class Decoder:
    """Abstract decode-step protocol (ref: decode.py Decoder)."""

    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, inputs, states, **kwargs):
        raise NotImplementedError

    def finalize(self, outputs, final_states, sequence_lengths):
        raise NotImplementedError

    @property
    def tracks_own_finished(self):
        return False


class BeamSearchDecoder(Decoder):
    """ref: decode.py:161 BeamSearchDecoder.

    cell: an RNNCell-like Layer returning (output, next_state);
    embedding_fn maps token ids -> embeddings; output_fn (e.g. the
    projection to vocab logits) is applied to the cell output.
    """

    OutputWrapper = collections.namedtuple(
        "OutputWrapper", ("scores", "predicted_ids", "parent_ids"))
    StateWrapper = collections.namedtuple(
        "StateWrapper", ("cell_states", "log_probs", "finished", "lengths"))

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn
        self.kinf = 1e9

    # -- beam helpers (ref: decode.py tile_beam_merge_with_batch etc.) ----
    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        """[batch, ...] -> [batch*beam, ...] (repeat each row beam times)."""
        def f(a):
            return jnp.repeat(a, beam_size, axis=0)
        return _map_structure(
            lambda t: Tensor(f(_data(t))) if isinstance(t, Tensor)
            else f(t), x)

    def _expand_to_beam_size(self, x):
        a = _data(x)
        tiled = jnp.repeat(a[:, None], self.beam_size, axis=1)
        return tiled  # [batch, beam, ...]

    def _merge_batch_beams(self, x):
        a = _data(x)
        return a.reshape((-1,) + a.shape[2:])

    def _split_batch_beams(self, x):
        a = _data(x)
        return a.reshape((-1, self.beam_size) + a.shape[1:])

    # -- protocol ----------------------------------------------------------
    def initialize(self, initial_cell_states):
        cell_states = _map_structure(
            lambda s: self._merge_batch_beams(self._expand_to_beam_size(s)),
            initial_cell_states)
        first = initial_cell_states
        while isinstance(first, (list, tuple)):
            first = first[0]
        batch = _data(first).shape[0]
        self.batch_size = batch
        log_probs = jnp.tile(
            jnp.asarray([[0.0] + [-self.kinf] * (self.beam_size - 1)],
                        jnp.float32), (batch, 1))
        finished = jnp.zeros((batch, self.beam_size), bool)
        lengths = jnp.zeros((batch, self.beam_size), jnp.int32)
        init_inputs = jnp.full((batch * self.beam_size,), self.start_token,
                               jnp.int32)
        if self.embedding_fn is not None:
            init_inputs = self.embedding_fn(Tensor(init_inputs))
            init_inputs = _data(init_inputs)
        state = self.StateWrapper(cell_states, log_probs, finished, lengths)
        return init_inputs, state, finished

    def step(self, time, inputs, states, **kwargs):
        cell_out, next_cell_states = self.cell(
            Tensor(inputs) if not isinstance(inputs, Tensor) else inputs,
            _map_structure(lambda s: Tensor(s) if not isinstance(s, Tensor)
                           else s, states.cell_states), **kwargs)
        if self.output_fn is not None:
            cell_out = self.output_fn(cell_out)
        logits = _data(cell_out)                       # [batch*beam, vocab]
        vocab = logits.shape[-1]
        step_lp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
        step_lp = step_lp.reshape(self.batch_size, self.beam_size, vocab)

        # finished beams only extend with end_token at no cost
        noend = jnp.full((vocab,), -self.kinf, jnp.float32
                         ).at[self.end_token].set(0.0)
        step_lp = jnp.where(states.finished[:, :, None],
                            noend[None, None, :], step_lp)

        total = states.log_probs[:, :, None] + step_lp
        flat = total.reshape(self.batch_size, -1)
        top_scores, top_idx = jax.lax.top_k(flat, self.beam_size)
        parent = (top_idx // vocab).astype(jnp.int32)  # [batch, beam]
        token = (top_idx % vocab).astype(jnp.int32)

        next_finished = jnp.take_along_axis(states.finished, parent, 1) | \
            (token == self.end_token)
        next_lengths = jnp.take_along_axis(states.lengths, parent, 1) + \
            (~jnp.take_along_axis(states.finished, parent, 1)).astype(
                jnp.int32)

        # gather cell states along the parent beams
        flat_parent = (parent + jnp.arange(self.batch_size)[:, None] *
                       self.beam_size).reshape(-1)

        def gather_state(s):
            return _data(s)[flat_parent]
        next_cell = _map_structure(
            lambda s: gather_state(s), next_cell_states)

        next_state = self.StateWrapper(next_cell, top_scores, next_finished,
                                       next_lengths)
        out = self.OutputWrapper(top_scores, token, parent)
        next_inputs = token.reshape(-1)
        if self.embedding_fn is not None:
            next_inputs = _data(self.embedding_fn(Tensor(next_inputs)))
        return out, next_state, next_inputs, next_finished

    def finalize(self, outputs, final_states, sequence_lengths):
        from .functional.extension import gather_tree
        preds = gather_tree(Tensor(outputs.predicted_ids),
                            Tensor(outputs.parent_ids))
        return preds, final_states

    @property
    def tracks_own_finished(self):
        return True


def dynamic_decode(decoder, inits=None, max_step_num=None,
                   output_time_major=False, impute_finished=False,
                   is_test=False, return_length=False, **kwargs):
    """ref: decode.py:1090 dynamic_decode — run decoder.step until all
    beams finish or max_step_num."""
    inputs, states, finished = decoder.initialize(inits)
    outputs_t = []
    step = 0
    limit = max_step_num if max_step_num is not None else 10 ** 9
    seq_lens = None
    while not bool(np.asarray(finished).all()) and step <= limit:
        out, states, inputs, finished = decoder.step(step, inputs, states,
                                                     **kwargs)
        outputs_t.append(out)
        step += 1
    if hasattr(states, "lengths"):
        seq_lens = states.lengths
    if isinstance(outputs_t[0], tuple) and hasattr(outputs_t[0], "_fields"):
        stacked = type(outputs_t[0])(*[
            jnp.stack([_data(getattr(o, f)) for o in outputs_t])
            for f in outputs_t[0]._fields])
    else:
        stacked = _map_structure(
            lambda *xs: jnp.stack([_data(x) for x in xs]), *outputs_t)
    final_outputs, final_states = decoder.finalize(stacked, states, seq_lens)

    def to_batch_major(t):
        a = _data(t)
        perm = (1, 0) + tuple(range(2, a.ndim))
        return Tensor(jnp.transpose(a, perm))

    if not output_time_major:
        final_outputs = _map_structure(
            lambda t: to_batch_major(t), final_outputs)
    if return_length:
        return final_outputs, final_states, Tensor(seq_lens)
    return final_outputs, final_states
