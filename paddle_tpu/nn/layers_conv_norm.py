"""Conv / Norm / Pooling layers.
ref: python/paddle/nn/layer/{conv,norm,pooling}.py"""
from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Parameter, Tensor
from . import functional as F
from . import initializer as I
from .layer import Layer


def _ntuple(v, n):
    return tuple(v) if isinstance(v, (list, tuple)) else (v,) * n


class _ConvNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride,
                 padding, dilation, groups, nd, transpose=False,
                 output_padding=0, weight_attr=None, bias_attr=None,
                 data_format="NCHW"):
        super().__init__()
        self.in_channels = in_channels
        self.out_channels = out_channels
        self.kernel_size = _ntuple(kernel_size, nd)
        self.stride = stride
        self.padding = padding
        self.dilation = dilation
        self.groups = groups
        self.nd = nd
        self.output_padding = output_padding
        self.data_format = data_format
        self._transpose = transpose
        if transpose:
            w_shape = [in_channels, out_channels // groups,
                       *self.kernel_size]
        else:
            w_shape = [out_channels, in_channels // groups,
                       *self.kernel_size]
        fan_in = (in_channels // groups) * int(np.prod(self.kernel_size))
        bound = 1.0 / math.sqrt(fan_in)
        self.weight = self.create_parameter(
            w_shape, attr=weight_attr,
            default_initializer=I.KaimingUniform(fan_in=fan_in))
        self.bias = self.create_parameter(
            [out_channels], attr=bias_attr, is_bias=True,
            default_initializer=I.Uniform(-bound, bound))

    def extra_repr(self):
        return (f"{self.in_channels}, {self.out_channels}, "
                f"kernel_size={self.kernel_size}, stride={self.stride}")


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, 1,
                         weight_attr=weight_attr, bias_attr=bias_attr,
                         data_format=data_format)

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, self.stride,
                        self.padding, self.dilation, self.groups,
                        self.data_format)


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, 2,
                         weight_attr=weight_attr, bias_attr=bias_attr,
                         data_format=data_format)

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, self.stride,
                        self.padding, self.dilation, self.groups,
                        self.data_format)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, 3,
                         weight_attr=weight_attr, bias_attr=bias_attr,
                         data_format=data_format)

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self.stride,
                        self.padding, self.dilation, self.groups,
                        self.data_format)


class Conv1DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, 1, transpose=True,
                         output_padding=output_padding,
                         weight_attr=weight_attr, bias_attr=bias_attr,
                         data_format=data_format)

    def forward(self, x, output_size=None):
        return F.conv1d_transpose(x, self.weight, self.bias, self.stride,
                                  self.padding, self.output_padding,
                                  self.groups, self.dilation, output_size,
                                  self.data_format)


class Conv2DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, 2, transpose=True,
                         output_padding=output_padding,
                         weight_attr=weight_attr, bias_attr=bias_attr,
                         data_format=data_format)

    def forward(self, x, output_size=None):
        return F.conv2d_transpose(x, self.weight, self.bias, self.stride,
                                  self.padding, self.output_padding,
                                  self.groups, self.dilation, output_size,
                                  self.data_format)


class Conv3DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, 3, transpose=True,
                         output_padding=output_padding,
                         weight_attr=weight_attr, bias_attr=bias_attr,
                         data_format=data_format)

    def forward(self, x, output_size=None):
        return F.conv3d_transpose(x, self.weight, self.bias, self.stride,
                                  self.padding, self.output_padding,
                                  self.groups, self.dilation, output_size,
                                  self.data_format)


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self.num_features = num_features
        self.momentum = momentum
        self.epsilon = epsilon
        self.data_format = data_format
        self.use_global_stats = use_global_stats
        self.weight = self.create_parameter(
            [num_features], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter([num_features], attr=bias_attr,
                                          is_bias=True)
        self.register_buffer("_mean", Tensor(jnp.zeros(num_features,
                                                       jnp.float32)))
        self.register_buffer("_variance", Tensor(jnp.ones(num_features,
                                                          jnp.float32)))

    def forward(self, x):
        return F.batch_norm(x, self._mean, self._variance, self.weight,
                            self.bias, training=self.training,
                            momentum=self.momentum, epsilon=self.epsilon,
                            data_format=self.data_format,
                            use_global_stats=self.use_global_stats)

    def extra_repr(self):
        return f"num_features={self.num_features}, momentum={self.momentum}"


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, "NCHW" if data_format == "NCDHW"
                         else data_format, use_global_stats)


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica BN. Under pjit/shard_map the mean/var reduction is a
    psum over the data-parallel mesh axis inserted by XLA; eager single-chip
    behavior equals BatchNorm (ref: python/paddle/nn/layer/norm.py
    SyncBatchNorm)."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        out = layer
        if isinstance(layer, _BatchNormBase) and not isinstance(
                layer, SyncBatchNorm):
            out = SyncBatchNorm(layer.num_features, layer.momentum,
                                layer.epsilon,
                                data_format=layer.data_format)
            out.weight, out.bias = layer.weight, layer.bias
            out._buffers = layer._buffers
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return out


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self.normalized_shape = list(normalized_shape)
        self.epsilon = epsilon
        self.weight = self.create_parameter(
            self.normalized_shape, attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter(self.normalized_shape,
                                          attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self.normalized_shape, self.weight,
                            self.bias, self.epsilon)

    def extra_repr(self):
        return f"normalized_shape={self.normalized_shape}"


class RMSNorm(Layer):
    """Above-parity layer used by Llama-family models."""

    def __init__(self, normalized_shape, epsilon=1e-6, weight_attr=None,
                 name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self.epsilon = epsilon
        self.weight = self.create_parameter(
            list(normalized_shape), attr=weight_attr,
            default_initializer=I.Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self.epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self.num_groups = num_groups
        self.epsilon = epsilon
        self.data_format = data_format
        self.weight = self.create_parameter(
            [num_channels], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter([num_channels], attr=bias_attr,
                                          is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self.num_groups, self.weight, self.bias,
                            self.epsilon, self.data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self.epsilon = epsilon
        if weight_attr is False or bias_attr is False:
            self.weight = None
            self.bias = None
        else:
            self.weight = self.create_parameter(
                [num_features], attr=weight_attr,
                default_initializer=I.Constant(1.0))
            self.bias = self.create_parameter(
                [num_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               eps=self.epsilon)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class SpectralNorm(Layer):
    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 name=None):
        super().__init__()
        self.dim = dim
        self.power_iters = power_iters
        self.eps = eps
        h = weight_shape[dim]
        w = int(np.prod(weight_shape)) // h
        self.weight_u = self.create_parameter(
            [h], default_initializer=I.Normal(0, 1))
        self.weight_v = self.create_parameter(
            [w], default_initializer=I.Normal(0, 1))

    def forward(self, weight):
        from ..core.autograd import apply_op
        dim, iters, eps = self.dim, self.power_iters, self.eps

        def f(w, u, v):
            wm = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
            for _ in range(iters):
                v = wm.T @ u
                v = v / (jnp.linalg.norm(v) + eps)
                u = wm @ v
                u = u / (jnp.linalg.norm(u) + eps)
            sigma = u @ wm @ v
            return w / sigma
        return apply_op(f, weight, self.weight_u, self.weight_v,
                        op_name="spectral_norm")


class _PoolNd(Layer):
    def __init__(self, fn, kernel_size, stride, padding, **kw):
        super().__init__()
        self._fn = fn
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self._kw = kw

    def forward(self, x):
        return self._fn(x, self.kernel_size, self.stride, self.padding,
                        **self._kw)


class MaxPool1D(_PoolNd):
    def __init__(self, kernel_size, stride=None, padding=0,
                 return_mask=False, ceil_mode=False, name=None):
        super().__init__(F.max_pool1d, kernel_size, stride, padding,
                         ceil_mode=ceil_mode)


class MaxPool2D(_PoolNd):
    def __init__(self, kernel_size, stride=None, padding=0,
                 return_mask=False, ceil_mode=False, data_format="NCHW",
                 name=None):
        super().__init__(F.max_pool2d, kernel_size, stride, padding,
                         ceil_mode=ceil_mode, data_format=data_format)


class MaxPool3D(_PoolNd):
    def __init__(self, kernel_size, stride=None, padding=0,
                 return_mask=False, ceil_mode=False, data_format="NCDHW",
                 name=None):
        super().__init__(F.max_pool3d, kernel_size, stride, padding,
                         ceil_mode=ceil_mode, data_format=data_format)


class AvgPool1D(_PoolNd):
    def __init__(self, kernel_size, stride=None, padding=0, exclusive=True,
                 ceil_mode=False, name=None):
        super().__init__(F.avg_pool1d, kernel_size, stride, padding,
                         exclusive=exclusive, ceil_mode=ceil_mode)


class AvgPool2D(_PoolNd):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCHW",
                 name=None):
        super().__init__(F.avg_pool2d, kernel_size, stride, padding,
                         ceil_mode=ceil_mode, exclusive=exclusive,
                         data_format=data_format)


class AvgPool3D(_PoolNd):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCDHW",
                 name=None):
        super().__init__(F.avg_pool3d, kernel_size, stride, padding,
                         ceil_mode=ceil_mode, exclusive=exclusive,
                         data_format=data_format)


class AdaptiveAvgPool1D(Layer):
    def __init__(self, output_size, name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool1d(x, self.output_size)


class AdaptiveAvgPool2D(Layer):
    def __init__(self, output_size, data_format="NCHW", name=None):
        super().__init__()
        self.output_size = output_size
        self.data_format = data_format

    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self.output_size, self.data_format)


class AdaptiveAvgPool3D(Layer):
    def __init__(self, output_size, data_format="NCDHW", name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self.output_size)


class AdaptiveMaxPool1D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_max_pool1d(x, self.output_size)


class AdaptiveMaxPool2D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_max_pool2d(x, self.output_size)


class AdaptiveMaxPool3D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_max_pool3d(x, self.output_size)


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.args = (size, alpha, beta, k, data_format)

    def forward(self, x):
        return F.local_response_norm(x, *self.args)
