"""Round-2 long-tail layers.

ref: python/paddle/nn/layer/{common,distance,pooling,loss,activation}.py —
thin Layer wrappers over nn.functional, same contract as the reference's
layer zoo.
"""
from __future__ import annotations

import numpy as np

from .layer import Layer
from . import functional as F

__all__ = [
    "PairwiseDistance", "Softmax2D", "Unflatten", "FeatureAlphaDropout",
    "ZeroPad1D", "ZeroPad3D", "MaxUnPool1D", "MaxUnPool2D", "MaxUnPool3D",
    "LPPool1D", "LPPool2D", "FractionalMaxPool2D", "FractionalMaxPool3D",
    "RNNTLoss", "HSigmoidLoss", "TripletMarginWithDistanceLoss",
    "AdaptiveLogSoftmaxWithLoss",
]


class PairwiseDistance(Layer):
    """ref: nn/layer/distance.py PairwiseDistance."""

    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p, self.epsilon, self.keepdim = p, epsilon, keepdim

    def forward(self, x, y):
        return F.pairwise_distance(x, y, self.p, self.epsilon, self.keepdim)


class Softmax2D(Layer):
    """ref: nn/layer/activation.py Softmax2D — softmax over the channel
    dim of NCHW input."""

    def forward(self, x):
        if len(x.shape) not in (3, 4):
            raise ValueError(
                f"Softmax2D expects 3D/4D input, got {len(x.shape)}D")
        return F.softmax(x, axis=-3)


class Unflatten(Layer):
    """ref: nn/layer/common.py Unflatten."""

    def __init__(self, axis, shape, name=None):
        super().__init__()
        self.axis, self.shape = axis, list(shape)

    def forward(self, x):
        from ..ops.manipulation import reshape
        s = list(x.shape)
        ax = self.axis if self.axis >= 0 else self.axis + len(s)
        new = s[:ax] + self.shape + s[ax + 1:]
        return reshape(x, new)


class FeatureAlphaDropout(Layer):
    """ref: nn/layer/common.py FeatureAlphaDropout."""

    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.feature_alpha_dropout(x, self.p, self.training)


class ZeroPad1D(Layer):
    """ref: nn/layer/common.py ZeroPad1D — pad [left, right] on NCL."""

    def __init__(self, padding, data_format="NCL", name=None):
        super().__init__()
        self.padding = [padding, padding] if isinstance(padding, int) \
            else list(padding)
        self.data_format = data_format

    def forward(self, x):
        from ..ops.manipulation import pad
        return pad(x, self.padding, mode="constant", value=0.0,
                   data_format=self.data_format)


class ZeroPad3D(Layer):
    """ref: nn/layer/common.py ZeroPad3D — [l, r, t, b, front, back]."""

    def __init__(self, padding, data_format="NCDHW", name=None):
        super().__init__()
        self.padding = [padding] * 6 if isinstance(padding, int) \
            else list(padding)
        self.data_format = data_format

    def forward(self, x):
        from ..ops.manipulation import pad
        return pad(x, self.padding, mode="constant", value=0.0,
                   data_format=self.data_format)


class _UnpoolNd(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format=None, output_size=None, name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.data_format = data_format
        self.output_size = output_size


class MaxUnPool1D(_UnpoolNd):
    """ref: nn/layer/pooling.py MaxUnPool1D."""

    def forward(self, x, indices):
        return F.max_unpool1d(x, indices, self.kernel_size, self.stride,
                              self.padding, self.data_format or "NCL",
                              self.output_size)


class MaxUnPool2D(_UnpoolNd):
    """ref: nn/layer/pooling.py MaxUnPool2D."""

    def forward(self, x, indices):
        return F.max_unpool2d(x, indices, self.kernel_size, self.stride,
                              self.padding, self.data_format or "NCHW",
                              self.output_size)


class MaxUnPool3D(_UnpoolNd):
    """ref: nn/layer/pooling.py MaxUnPool3D."""

    def forward(self, x, indices):
        return F.max_unpool3d(x, indices, self.kernel_size, self.stride,
                              self.padding, self.data_format or "NCDHW",
                              self.output_size)


class LPPool1D(Layer):
    """ref: nn/layer/pooling.py LPPool1D."""

    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format="NCL", name=None):
        super().__init__()
        self.norm_type = norm_type
        self.kernel_size, self.stride = kernel_size, stride
        self.padding, self.ceil_mode = padding, ceil_mode
        self.data_format = data_format

    def forward(self, x):
        return F.lp_pool1d(x, self.norm_type, self.kernel_size, self.stride,
                           self.padding, self.ceil_mode, self.data_format)


class LPPool2D(Layer):
    """ref: nn/layer/pooling.py LPPool2D."""

    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format="NCHW", name=None):
        super().__init__()
        self.norm_type = norm_type
        self.kernel_size, self.stride = kernel_size, stride
        self.padding, self.ceil_mode = padding, ceil_mode
        self.data_format = data_format

    def forward(self, x):
        return F.lp_pool2d(x, self.norm_type, self.kernel_size, self.stride,
                           self.padding, self.ceil_mode, self.data_format)


class FractionalMaxPool2D(Layer):
    """ref: nn/layer/pooling.py FractionalMaxPool2D."""

    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self.output_size, self.kernel_size = output_size, kernel_size
        self.random_u, self.return_mask = random_u, return_mask

    def forward(self, x):
        return F.fractional_max_pool2d(x, self.output_size,
                                       self.kernel_size, self.random_u,
                                       self.return_mask)


class FractionalMaxPool3D(Layer):
    """ref: nn/layer/pooling.py FractionalMaxPool3D."""

    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self.output_size, self.kernel_size = output_size, kernel_size
        self.random_u, self.return_mask = random_u, return_mask

    def forward(self, x):
        return F.fractional_max_pool3d(x, self.output_size,
                                       self.kernel_size, self.random_u,
                                       self.return_mask)


class RNNTLoss(Layer):
    """ref: nn/layer/loss.py RNNTLoss."""

    def __init__(self, blank=0, fastemit_lambda=0.001, reduction="mean",
                 name=None):
        super().__init__()
        self.blank = blank
        self.fastemit_lambda = fastemit_lambda
        self.reduction = reduction

    def forward(self, input, label, input_lengths, label_lengths):
        return F.rnnt_loss(input, label, input_lengths, label_lengths,
                           self.blank, self.fastemit_lambda, self.reduction)


class HSigmoidLoss(Layer):
    """ref: nn/layer/loss.py HSigmoidLoss — holds the internal-node
    weight table [num_classes-1, feature_size] (+bias)."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False,
                 name=None):
        super().__init__()
        if (num_classes < 2) and (not is_custom):
            raise ValueError(
                "num_classes must not be less than 2 with default tree")
        self.num_classes = num_classes
        self.is_custom = is_custom
        n_nodes = num_classes if is_custom else num_classes - 1
        import math
        from .initializer import Uniform
        std = math.sqrt(1.0 / (feature_size + 1))
        self.weight = self.create_parameter(
            [n_nodes, feature_size], attr=weight_attr,
            default_initializer=Uniform(-std, std))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                [n_nodes, 1], attr=bias_attr, is_bias=True,
                default_initializer=Uniform(-std, std))

    def forward(self, input, label, path_table=None, path_code=None):
        return F.hsigmoid_loss(input, label, self.num_classes, self.weight,
                               self.bias, path_table, path_code)


class TripletMarginWithDistanceLoss(Layer):
    """ref: nn/layer/loss.py TripletMarginWithDistanceLoss."""

    def __init__(self, distance_function=None, margin=1.0, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.distance_function = distance_function
        self.margin, self.swap, self.reduction = margin, swap, reduction

    def forward(self, input, positive, negative):
        return F.triplet_margin_with_distance_loss(
            input, positive, negative, self.distance_function, self.margin,
            self.swap, self.reduction)


class AdaptiveLogSoftmaxWithLoss(Layer):
    """ref: nn/layer/loss.py AdaptiveLogSoftmaxWithLoss (Grave et al.).
    Owns head + per-cluster tail projections (div_value decay)."""

    def __init__(self, in_features, n_classes, cutoffs, div_value=4.0,
                 head_bias=False, weight_attr=None, bias_attr=None,
                 name=None):
        super().__init__()
        cutoffs = list(cutoffs)
        if any(int(c) <= 0 for c in cutoffs) or \
                sorted(set(cutoffs)) != sorted(cutoffs) or \
                max(cutoffs) > n_classes - 1:
            raise ValueError(
                "cutoffs must be unique, positive, increasing ints "
                "below n_classes")
        self.in_features = in_features
        self.n_classes = n_classes
        self.cutoffs = cutoffs + [n_classes]
        self.div_value = div_value
        self.shortlist_size = cutoffs[0]
        self.n_clusters = len(cutoffs)
        self.head_size = self.shortlist_size + self.n_clusters
        self.head_weight = self.create_parameter(
            [in_features, self.head_size], attr=weight_attr)
        self.head_bias = (self.create_parameter(
            [self.head_size], attr=bias_attr, is_bias=True)
            if head_bias else None)
        from .container import ParameterList
        self.tail_weights = []
        for i in range(self.n_clusters):
            hsz = max(1, int(in_features // (div_value ** (i + 1))))
            osz = self.cutoffs[i + 1] - self.cutoffs[i]
            w1 = self.create_parameter([in_features, hsz],
                                       attr=weight_attr)
            w2 = self.create_parameter([hsz, osz], attr=weight_attr)
            setattr(self, f"_tail_{i}_0", w1)
            setattr(self, f"_tail_{i}_1", w2)
            self.tail_weights.append([w1, w2])

    def forward(self, input, label):
        return F.adaptive_log_softmax_with_loss(
            input, label, self.head_weight, self.tail_weights,
            self.cutoffs[:-1], self.head_bias)

    def log_prob(self, input):
        """Full [N, n_classes] log-probabilities."""
        import jax.numpy as jnp
        from ..core.autograd import apply_op

        def f(x, hw, *rest):
            hb = rest[0] if self.head_bias is not None else None
            tails = rest[1:] if self.head_bias is not None else rest
            head_logits = x @ hw
            if hb is not None:
                head_logits = head_logits + hb
            head_lp = jnp.log(jnp.clip(
                jnp.exp(head_logits - head_logits.max(-1, keepdims=True)) /
                jnp.sum(jnp.exp(head_logits -
                                head_logits.max(-1, keepdims=True)),
                        -1, keepdims=True), 1e-38))
            outs = [head_lp[:, :self.shortlist_size]]
            for i in range(self.n_clusters):
                w1, w2 = tails[2 * i], tails[2 * i + 1]
                t = (x @ w1) @ w2
                t = t - t.max(-1, keepdims=True)
                t_lp = t - jnp.log(jnp.sum(jnp.exp(t), -1, keepdims=True))
                outs.append(head_lp[:, self.shortlist_size + i:
                                    self.shortlist_size + i + 1] + t_lp)
            return jnp.concatenate(outs, axis=-1)

        args = [self.head_weight]
        if self.head_bias is not None:
            args.append(self.head_bias)
        for w1, w2 in self.tail_weights:
            args += [w1, w2]
        return apply_op(f, input, *args, op_name="adaptive_log_prob")

    def predict(self, input):
        from ..ops.math import argmax
        return argmax(self.log_prob(input), axis=-1)
