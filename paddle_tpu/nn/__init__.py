"""paddle.nn equivalent. ref: python/paddle/nn/__init__.py"""
from .layer import Layer, ParamAttr  # noqa: F401
from .container import (  # noqa: F401
    Sequential, LayerList, ParameterList, LayerDict, ParameterDict,
)
from .layers_common import (  # noqa: F401
    Linear, Embedding, Dropout, Dropout2D, Dropout3D, AlphaDropout,
    Flatten, Identity, Upsample, UpsamplingBilinear2D, UpsamplingNearest2D,
    Bilinear, PixelShuffle, PixelUnshuffle, ChannelShuffle,
    Pad1D, Pad2D, Pad3D, ZeroPad2D, CosineSimilarity, Unfold, Fold,
)
from .layers_conv_norm import (  # noqa: F401
    Conv1D, Conv2D, Conv3D, Conv1DTranspose, Conv2DTranspose,
    Conv3DTranspose,
    BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D, SyncBatchNorm,
    LayerNorm, RMSNorm, GroupNorm, InstanceNorm1D, InstanceNorm2D,
    InstanceNorm3D, SpectralNorm, LocalResponseNorm,
    MaxPool1D, MaxPool2D, MaxPool3D, AvgPool1D, AvgPool2D, AvgPool3D,
    AdaptiveAvgPool1D, AdaptiveAvgPool2D, AdaptiveAvgPool3D,
    AdaptiveMaxPool1D, AdaptiveMaxPool2D, AdaptiveMaxPool3D,
)
from .layers_activation import (  # noqa: F401
    ReLU, ReLU6, LeakyReLU, PReLU, GELU, Sigmoid, Tanh, Softmax,
    LogSoftmax, ELU, SELU, CELU, Silu, Swish, Mish, Hardswish, Hardsigmoid,
    Hardtanh, Hardshrink, Softshrink, Tanhshrink, ThresholdedReLU,
    Softplus, Softsign, LogSigmoid, Maxout, GLU, RReLU,
)
from .layers_loss import (  # noqa: F401
    CrossEntropyLoss, MSELoss, L1Loss, SmoothL1Loss, NLLLoss, BCELoss,
    BCEWithLogitsLoss, KLDivLoss, MarginRankingLoss, CosineEmbeddingLoss,
    TripletMarginLoss, HingeEmbeddingLoss, CTCLoss, SoftMarginLoss,
    MultiLabelSoftMarginLoss, MultiMarginLoss, PoissonNLLLoss,
    GaussianNLLLoss,
)
from .transformer import (  # noqa: F401
    MultiHeadAttention, Transformer, TransformerEncoder,
    TransformerEncoderLayer, TransformerDecoder, TransformerDecoderLayer,
)
from .rnn import (  # noqa: F401
    SimpleRNN, LSTM, GRU, SimpleRNNCell, LSTMCell, GRUCell, RNN, BiRNN,
    RNNCellBase,
)
from .layers_extra import (  # noqa: F401
    PairwiseDistance, Softmax2D, Unflatten, FeatureAlphaDropout,
    ZeroPad1D, ZeroPad3D, MaxUnPool1D, MaxUnPool2D, MaxUnPool3D,
    LPPool1D, LPPool2D, FractionalMaxPool2D, FractionalMaxPool3D,
    RNNTLoss, HSigmoidLoss, TripletMarginWithDistanceLoss,
    AdaptiveLogSoftmaxWithLoss,
)
from .decode import Decoder, BeamSearchDecoder, dynamic_decode  # noqa: F401
from . import functional  # noqa: F401
from . import initializer  # noqa: F401

from ..utils.clip_grad import (  # noqa: F401
    ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue,
)
