"""Weight initializers. ref: python/paddle/nn/initializer/*"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core import random as random_mod
from ..core.dtype import convert_dtype


class Initializer:
    def __call__(self, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype):
        return jnp.full(shape, self.value, convert_dtype(dtype))


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        d = convert_dtype(dtype)
        return (jax.random.normal(random_mod.next_key(), shape, jnp.float32)
                * self.std + self.mean).astype(d)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, shape, dtype):
        d = convert_dtype(dtype)
        z = jax.random.truncated_normal(random_mod.next_key(), self.a, self.b,
                                        shape, jnp.float32)
        return (z * self.std + self.mean).astype(d)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype):
        d = convert_dtype(dtype)
        return jax.random.uniform(random_mod.next_key(), shape, jnp.float32,
                                  self.low, self.high).astype(d)


def _fan_in_out(shape):
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        # paddle Linear weight is [in, out]
        return shape[0], shape[1]
    # conv weight [out_c, in_c, *k]
    rf = int(np.prod(shape[2:]))
    return shape[1] * rf, shape[0] * rf


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return Normal(0.0, std)(shape, dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return Uniform(-limit, limit)(shape, dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        return Normal(0.0, gain / math.sqrt(fi))(shape, dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / fi)
        return Uniform(-limit, limit)(shape, dtype)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype):
        from ..core.tensor import Tensor
        v = self.value
        if isinstance(v, Tensor):
            v = v._data
        arr = jnp.asarray(np.asarray(v), convert_dtype(dtype))
        return arr.reshape(shape)


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype):
        d = convert_dtype(dtype)
        rows = shape[0]
        cols = int(np.prod(shape[1:]))
        n = max(rows, cols)
        a = jax.random.normal(random_mod.next_key(), (n, n), jnp.float32)
        q, r = jnp.linalg.qr(a)
        q = q * jnp.sign(jnp.diagonal(r))
        return (self.gain * q[:rows, :cols]).reshape(shape).astype(d)


def calculate_gain(nonlinearity, param=None):
    if nonlinearity in ("sigmoid", "linear", "conv1d", "conv2d", "conv3d"):
        return 1.0
    if nonlinearity == "tanh":
        return 5.0 / 3.0
    if nonlinearity == "relu":
        return math.sqrt(2.0)
    if nonlinearity == "leaky_relu":
        a = 0.01 if param is None else param
        return math.sqrt(2.0 / (1 + a * a))
    if nonlinearity == "selu":
        return 3.0 / 4.0
    return 1.0
