"""Layer: the module base class.

ref: python/paddle/nn/layer/layers.py:354 (Layer) — parameters/buffers/
sublayers registries, hooks, state_dict, train/eval. The TPU-native twist:
parameters are leaf Tensors whose ._data can be swapped for tracers, so the
same Layer object serves eager execution and jit functionalization
(see paddle_tpu.jit).
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Callable, Dict, Iterator, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..core.dtype import convert_dtype, get_default_dtype
from ..core.tensor import Parameter, Tensor
from . import initializer as I


class HookRemoveHelper:
    def __init__(self, hooks: dict, hook_id: int):
        self._hooks = hooks
        self._hook_id = hook_id

    def remove(self):
        self._hooks.pop(self._hook_id, None)


class Layer:
    def __init__(self, name_scope=None, dtype=None):
        # use object.__setattr__ to dodge our own __setattr__
        object.__setattr__(self, "_parameters", OrderedDict())
        object.__setattr__(self, "_buffers", OrderedDict())
        object.__setattr__(self, "_sub_layers", OrderedDict())
        self._non_persistable_buffer_names = set()
        self.training = True
        self._dtype = convert_dtype(dtype) or get_default_dtype()
        self._forward_pre_hooks: Dict[int, Callable] = {}
        self._forward_post_hooks: Dict[int, Callable] = {}
        self._hook_id = 0
        self._name_scope = name_scope or self.__class__.__name__.lower()

    # -- registration --------------------------------------------------------
    def __setattr__(self, name, value):
        if isinstance(value, Parameter):
            self._parameters[name] = value
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            self._sub_layers[name] = value
            self.__dict__.pop(name, None)
        elif isinstance(value, Tensor) and not name.startswith("_"):
            # plain Tensor attr → non-persistable buffer (ref: layers.py
            # __setattr__ registers Tensor values as buffers)
            self._buffers[name] = value
            self._non_persistable_buffer_names.add(name)
            self.__dict__.pop(name, None)
        else:
            # plain attribute; drop any stale registry entry with same name
            if name in getattr(self, "_parameters", {}):
                if value is None:
                    self._parameters[name] = None
                    return
                del self._parameters[name]
            if name in getattr(self, "_sub_layers", {}):
                del self._sub_layers[name]
            if name in getattr(self, "_buffers", {}):
                del self._buffers[name]
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        # only called when normal lookup fails
        for registry in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(registry)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for registry in (self._parameters, self._buffers, self._sub_layers):
            if name in registry:
                del registry[name]
                return
        object.__delattr__(self, name)

    def add_parameter(self, name: str, parameter: Optional[Parameter]):
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name: str, sublayer: "Layer"):
        self._sub_layers[name] = sublayer
        return sublayer

    def register_buffer(self, name: str, tensor: Optional[Tensor],
                        persistable: bool = True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        """ref: layers.py create_parameter; attr may be a ParamAttr, an
        Initializer, False (no parameter), or None (default init)."""
        if attr is False:
            return None
        d = convert_dtype(dtype) or self._dtype
        init = default_initializer
        trainable = True
        if attr is not None:
            if isinstance(attr, I.Initializer):
                init = attr
            elif isinstance(attr, ParamAttr):
                if attr.initializer is not None:
                    init = attr.initializer
                trainable = attr.trainable
        if init is None:
            init = I.Constant(0.0) if is_bias else I.XavierNormal()
        data = init(tuple(shape), d)
        p = Parameter(data, stop_gradient=not trainable)
        return p

    # -- iteration -----------------------------------------------------------
    def named_parameters(self, prefix="", include_sublayers=True
                         ) -> Iterator[Tuple[str, Parameter]]:
        yield from self._named_parameters(prefix, include_sublayers,
                                          set())

    def _named_parameters(self, prefix, include_sublayers, seen):
        # `seen` threads through the WHOLE walk: a tied Parameter
        # reachable via two submodules (tied embedding/lm-head) must
        # yield once — a per-level memo made optimizers built from
        # parameters() apply the update twice to the shared tensor
        # (ref: Layer.parameters dedup semantics, nn/layer/layers.py)
        for name, p in self._parameters.items():
            if p is not None and id(p) not in seen:
                seen.add(id(p))
                yield (prefix + name if not prefix else
                       f"{prefix}.{name}"), p
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                if layer is None:
                    continue
                sub_prefix = f"{prefix}.{lname}" if prefix else lname
                yield from layer._named_parameters(sub_prefix, True,
                                                   seen)

    def parameters(self, include_sublayers=True) -> List[Parameter]:
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def clear_gradients(self):
        """ref: nn/layer/layers.py Layer.clear_gradients."""
        for p in self.parameters():
            p.clear_grad()

    def named_buffers(self, prefix="", include_sublayers=True):
        for name, b in self._buffers.items():
            if b is not None:
                yield (f"{prefix}.{name}" if prefix else name), b
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                if layer is None:
                    continue
                sub_prefix = f"{prefix}.{lname}" if prefix else lname
                for item in layer.named_buffers(sub_prefix):
                    yield item

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(
            include_sublayers=include_sublayers)]

    def named_sublayers(self, prefix="", include_self=False):
        if include_self:
            yield prefix, self
        for name, layer in self._sub_layers.items():
            if layer is None:
                continue
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield sub_prefix, layer
            for item in layer.named_sublayers(sub_prefix):
                yield item

    def sublayers(self, include_self=False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def children(self):
        return iter(l for l in self._sub_layers.values() if l is not None)

    def named_children(self):
        return iter((n, l) for n, l in self._sub_layers.items()
                    if l is not None)

    def apply(self, fn):
        for layer in self.sublayers(include_self=True):
            fn(layer)
        return self

    # -- mode ----------------------------------------------------------------
    def train(self):
        for layer in self.sublayers(include_self=True):
            layer.training = True
        return self

    def eval(self):
        for layer in self.sublayers(include_self=True):
            layer.training = False
        return self

    # -- hooks ---------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook):
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id)

    # -- call ----------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in list(self._forward_pre_hooks.values()):
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        out = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            result = hook(self, inputs, out)
            if result is not None:
                out = result
        return out

    # -- state ---------------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True):
        dest = destination if destination is not None else OrderedDict()
        for lname, layer in self.named_sublayers(
                prefix=structured_name_prefix, include_self=True):
            for pname, p in layer._parameters.items():
                if p is not None:
                    dest[f"{lname}.{pname}" if lname else pname] = p
            for bname, b in layer._buffers.items():
                if b is None or bname in layer._non_persistable_buffer_names:
                    continue
                dest[f"{lname}.{bname}" if lname else bname] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True,
                       cast_dtype=True):
        """Returns (missing_keys, unexpected_keys) like the reference.
        cast_dtype=False installs checkpoint values in THEIR dtype (a
        bf16-saved model stays bf16) instead of the model's init dtype."""
        own = self.state_dict()
        missing, unexpected = [], []
        for k, v in state_dict.items():
            if k in own:
                data = v._data if isinstance(v, Tensor) else jnp.asarray(
                    np.asarray(v))
                target = own[k]
                if tuple(data.shape) != tuple(target._data.shape):
                    raise ValueError(
                        f"shape mismatch for {k}: checkpoint "
                        f"{tuple(data.shape)} vs model "
                        f"{tuple(target._data.shape)}")
                target._data = data.astype(target._data.dtype) \
                    if cast_dtype else data
            else:
                unexpected.append(k)
        for k in own:
            if k not in state_dict:
                missing.append(k)
        return missing, unexpected

    load_dict = set_state_dict

    # -- dtype / device ------------------------------------------------------
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            d = convert_dtype(dtype)
            for p in self.parameters():
                p._data = p._data.astype(d)
            for b in self.buffers():
                if jnp.issubdtype(b._data.dtype, jnp.floating):
                    b._data = b._data.astype(d)
            for layer in self.sublayers(include_self=True):
                layer._dtype = d
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    def half(self):
        return self.to(dtype="float16")

    def full_name(self):
        return self._name_scope

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, layer in self._sub_layers.items():
            rep = repr(layer).split("\n")
            rep = [rep[0]] + ["  " + r for r in rep[1:]]
            lines.append(f"  ({name}): " + "\n".join(rep))
        main = f"{type(self).__name__}({extra}"
        if lines:
            return main + "\n" + "\n".join(lines) + "\n)"
        return main + ")"


class ParamAttr:
    """ref: python/paddle/base/param_attr.py ParamAttr"""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=True,
                 need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip
