"""Recurrent layers: SimpleRNN / LSTM / GRU (+ cells).

ref: python/paddle/nn/layer/rnn.py. TPU-native: the time loop is a
``lax.scan`` inside one apply_op, so it traces to a single XLA while-op
(compiler-friendly control flow, no Python-per-step dispatch) and is
differentiable through the scan.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..core.autograd import apply_op
from ..core.tensor import Tensor
from . import initializer as I
from .layer import Layer


def _uniform_init(hidden_size):
    k = 1.0 / math.sqrt(hidden_size)
    return I.Uniform(-k, k)


class RNNCellBase(Layer):
    """Base class for RNN cells (ref: python/paddle/nn/layer/rnn.py
    RNNCellBase) — provides get_initial_states over possibly-nested
    state shapes."""

    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        batch = batch_ref.shape[batch_dim_idx]
        if shape is None:
            shape = self.state_shape
        if dtype is None:
            dtype = jnp.float32

        def build(s):
            if isinstance(s, (list, tuple)) and s and \
                    isinstance(s[0], (list, tuple)):
                return type(s)(build(x) for x in s)
            dims = [batch] + [int(d) for d in s]
            return Tensor(jnp.full(dims, init_value, dtype))

        return build(shape)

    @property
    def state_shape(self):
        if hasattr(self, "hidden_size"):
            return [self.hidden_size]
        raise NotImplementedError(
            "cells must define state_shape or hidden_size")


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        init = _uniform_init(hidden_size)
        self.hidden_size = hidden_size
        self.activation = activation
        self.weight_ih = self.create_parameter(
            [hidden_size, input_size], attr=weight_ih_attr,
            default_initializer=init)
        self.weight_hh = self.create_parameter(
            [hidden_size, hidden_size], attr=weight_hh_attr,
            default_initializer=init)
        self.bias_ih = self.create_parameter(
            [hidden_size], attr=bias_ih_attr, is_bias=True,
            default_initializer=init)
        self.bias_hh = self.create_parameter(
            [hidden_size], attr=bias_hh_attr, is_bias=True,
            default_initializer=init)

    def forward(self, inputs, states=None):
        if states is None:
            states = Tensor(jnp.zeros(
                (inputs.shape[0], self.hidden_size), inputs._data.dtype))
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu

        def f(x, h, wi, wh, bi, bh):
            return act(x @ wi.T + bi + h @ wh.T + bh)
        h = apply_op(f, inputs, states, self.weight_ih, self.weight_hh,
                     self.bias_ih, self.bias_hh, op_name="rnn_cell")
        return h, h


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 proj_size=None, name=None):
        super().__init__()
        init = _uniform_init(hidden_size)
        self.hidden_size = hidden_size
        self.weight_ih = self.create_parameter(
            [4 * hidden_size, input_size], attr=weight_ih_attr,
            default_initializer=init)
        self.weight_hh = self.create_parameter(
            [4 * hidden_size, hidden_size], attr=weight_hh_attr,
            default_initializer=init)
        self.bias_ih = self.create_parameter(
            [4 * hidden_size], attr=bias_ih_attr, is_bias=True,
            default_initializer=init)
        self.bias_hh = self.create_parameter(
            [4 * hidden_size], attr=bias_hh_attr, is_bias=True,
            default_initializer=init)

    @property
    def state_shape(self):
        return ([self.hidden_size], [self.hidden_size])

    def forward(self, inputs, states=None):
        if states is None:
            z = jnp.zeros((inputs.shape[0], self.hidden_size),
                          inputs._data.dtype)
            states = (Tensor(z), Tensor(z))
        h0, c0 = states

        def f(x, h, c, wi, wh, bi, bh):
            gates = x @ wi.T + bi + h @ wh.T + bh
            i, fgt, g, o = jnp.split(gates, 4, axis=-1)
            i, fgt, o = (jax.nn.sigmoid(i), jax.nn.sigmoid(fgt),
                         jax.nn.sigmoid(o))
            g = jnp.tanh(g)
            c_new = fgt * c + i * g
            h_new = o * jnp.tanh(c_new)
            return h_new, c_new
        h, c = apply_op(f, inputs, h0, c0, self.weight_ih, self.weight_hh,
                        self.bias_ih, self.bias_hh, op_name="lstm_cell")
        return h, (h, c)


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        init = _uniform_init(hidden_size)
        self.hidden_size = hidden_size
        self.weight_ih = self.create_parameter(
            [3 * hidden_size, input_size], attr=weight_ih_attr,
            default_initializer=init)
        self.weight_hh = self.create_parameter(
            [3 * hidden_size, hidden_size], attr=weight_hh_attr,
            default_initializer=init)
        self.bias_ih = self.create_parameter(
            [3 * hidden_size], attr=bias_ih_attr, is_bias=True,
            default_initializer=init)
        self.bias_hh = self.create_parameter(
            [3 * hidden_size], attr=bias_hh_attr, is_bias=True,
            default_initializer=init)

    def forward(self, inputs, states=None):
        if states is None:
            states = Tensor(jnp.zeros(
                (inputs.shape[0], self.hidden_size), inputs._data.dtype))

        def f(x, h, wi, wh, bi, bh):
            gi = x @ wi.T + bi
            gh = h @ wh.T + bh
            ir, iz, ic = jnp.split(gi, 3, axis=-1)
            hr, hz, hc = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(ir + hr)
            z = jax.nn.sigmoid(iz + hz)
            c = jnp.tanh(ic + r * hc)
            return (1 - z) * c + z * h
        h = apply_op(f, inputs, states, self.weight_ih, self.weight_hh,
                     self.bias_ih, self.bias_hh, op_name="gru_cell")
        return h, h


class _RNNBase(Layer):
    MODE = "RNN_TANH"
    GATES = 1

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.activation = activation
        self.bidirect = direction in ("bidirect", "bidirectional")
        ndir = 2 if self.bidirect else 1
        self.num_directions = ndir
        init = _uniform_init(hidden_size)
        g = self.GATES
        for l in range(num_layers):
            for d in range(ndir):
                in_sz = input_size if l == 0 else hidden_size * ndir
                suffix = f"_l{l}" + ("_reverse" if d == 1 else "")
                self.add_parameter(
                    f"weight_ih{suffix}", self.create_parameter(
                        [g * hidden_size, in_sz], default_initializer=init))
                self.add_parameter(
                    f"weight_hh{suffix}", self.create_parameter(
                        [g * hidden_size, hidden_size],
                        default_initializer=init))
                self.add_parameter(
                    f"bias_ih{suffix}", self.create_parameter(
                        [g * hidden_size], is_bias=True,
                        default_initializer=init))
                self.add_parameter(
                    f"bias_hh{suffix}", self.create_parameter(
                        [g * hidden_size], is_bias=True,
                        default_initializer=init))

    def _cell_fn(self):
        raise NotImplementedError

    def _init_state(self, batch, dtype):
        raise NotImplementedError

    def forward(self, inputs, initial_states=None, sequence_length=None):
        cell = self._cell_fn()
        tm = self.time_major
        nl, nd, hs = self.num_layers, self.num_directions, self.hidden_size
        params = []
        for l in range(nl):
            for d in range(nd):
                sfx = f"_l{l}" + ("_reverse" if d == 1 else "")
                params += [self._parameters[f"weight_ih{sfx}"],
                           self._parameters[f"weight_hh{sfx}"],
                           self._parameters[f"bias_ih{sfx}"],
                           self._parameters[f"bias_hh{sfx}"]]

        has_cell_state = self.MODE == "LSTM"
        init_given = initial_states is not None
        init_tensors = []
        if init_given:
            if has_cell_state:
                init_tensors = [initial_states[0], initial_states[1]]
            else:
                init_tensors = [initial_states]

        def f(x, *flat):
            if init_given:
                if has_cell_state:
                    h0_all, c0_all, *ps = flat
                else:
                    h0_all, *ps = flat
                    c0_all = None
            else:
                ps = list(flat)
                h0_all = c0_all = None
            if not tm:
                x = jnp.swapaxes(x, 0, 1)  # [T, B, F]
            batch = x.shape[1]
            if h0_all is None:
                h0_all = jnp.zeros((nl * nd, batch, hs), x.dtype)
                if has_cell_state:
                    c0_all = jnp.zeros((nl * nd, batch, hs), x.dtype)
            out = x
            last_h, last_c = [], []
            for l in range(nl):
                dir_outs = []
                for d in range(nd):
                    idx = (l * nd + d) * 4
                    wi, wh, bi, bh = ps[idx:idx + 4]
                    seq = out if d == 0 else jnp.flip(out, axis=0)
                    h0 = h0_all[l * nd + d]
                    carry0 = ((h0, c0_all[l * nd + d]) if has_cell_state
                              else h0)

                    def step(carry, x_t):
                        new = cell(x_t, carry, wi, wh, bi, bh)
                        h_out = new[0] if has_cell_state else new
                        return new, h_out

                    carry, hs_seq = jax.lax.scan(step, carry0, seq)
                    if d == 1:
                        hs_seq = jnp.flip(hs_seq, axis=0)
                    dir_outs.append(hs_seq)
                    if has_cell_state:
                        last_h.append(carry[0])
                        last_c.append(carry[1])
                    else:
                        last_h.append(carry)
                out = (jnp.concatenate(dir_outs, axis=-1) if nd == 2
                       else dir_outs[0])
            outputs = out if tm else jnp.swapaxes(out, 0, 1)
            h_stack = jnp.stack(last_h, axis=0)
            if has_cell_state:
                return outputs, h_stack, jnp.stack(last_c, axis=0)
            return outputs, h_stack

        res = apply_op(f, inputs, *init_tensors, *params,
                       op_name=self.MODE.lower())
        if has_cell_state:
            outputs, h, c = res
            return outputs, (h, c)
        outputs, h = res
        return outputs, h


class SimpleRNN(_RNNBase):
    MODE = "RNN_TANH"
    GATES = 1

    def _cell_fn(self):
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu

        def cell(x, h, wi, wh, bi, bh):
            return act(x @ wi.T + bi + h @ wh.T + bh)
        return cell


class LSTM(_RNNBase):
    MODE = "LSTM"
    GATES = 4

    def __init__(self, *args, **kwargs):
        kwargs.pop("activation", None)
        super().__init__(*args, **kwargs)

    def _cell_fn(self):
        def cell(x, carry, wi, wh, bi, bh):
            h, c = carry
            gates = x @ wi.T + bi + h @ wh.T + bh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i, f, o = (jax.nn.sigmoid(i), jax.nn.sigmoid(f),
                       jax.nn.sigmoid(o))
            g = jnp.tanh(g)
            c_new = f * c + i * g
            return (o * jnp.tanh(c_new), c_new)
        return cell


class GRU(_RNNBase):
    MODE = "GRU"
    GATES = 3

    def __init__(self, *args, **kwargs):
        kwargs.pop("activation", None)
        super().__init__(*args, **kwargs)

    def _cell_fn(self):
        def cell(x, h, wi, wh, bi, bh):
            gi = x @ wi.T + bi
            gh = h @ wh.T + bh
            ir, iz, ic = jnp.split(gi, 3, axis=-1)
            hr, hz, hc = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(ir + hr)
            z = jax.nn.sigmoid(iz + hz)
            c = jnp.tanh(ic + r * hc)
            return (1 - z) * c + z * h
        return cell


class RNN(Layer):
    """Wraps a cell into a scan over time. ref: nn/layer/rnn.py RNN"""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        tm = self.time_major
        steps_axis = 0 if tm else 1
        n = inputs.shape[steps_axis]
        outs = []
        states = initial_states
        idxs = range(n - 1, -1, -1) if self.is_reverse else range(n)
        for t in idxs:
            x_t = inputs[t] if tm else inputs[:, t]
            o, states = self.cell(x_t, states)
            outs.append(o)
        if self.is_reverse:
            outs = outs[::-1]
        from ..ops.manipulation import stack
        return stack(outs, axis=steps_axis), states


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, False, time_major)
        self.rnn_bw = RNN(cell_bw, True, time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ..ops.manipulation import concat
        sf = initial_states[0] if initial_states else None
        sb = initial_states[1] if initial_states else None
        out_f, st_f = self.rnn_fw(inputs, sf)
        out_b, st_b = self.rnn_bw(inputs, sb)
        return concat([out_f, out_b], axis=-1), (st_f, st_b)

