"""Activation layers. ref: python/paddle/nn/layer/activation.py"""
from __future__ import annotations

from ..core.tensor import Parameter
from . import functional as F
from . import initializer as I
from .layer import Layer


def _make(fn_name, **fixed):
    class _Act(Layer):
        def __init__(self, *args, name=None, **kwargs):
            super().__init__()
            self._args = args
            self._kwargs = {**fixed, **kwargs}

        def forward(self, x):
            return getattr(F, fn_name)(x, *self._args, **self._kwargs)
    _Act.__name__ = fn_name
    return _Act


class ReLU(Layer):
    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        return F.relu(x)


class ReLU6(Layer):
    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        return F.relu6(x)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self.negative_slope = negative_slope

    def forward(self, x):
        return F.leaky_relu(x, self.negative_slope)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self.data_format = data_format
        self.weight = self.create_parameter(
            [num_parameters], attr=weight_attr,
            default_initializer=I.Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, self.data_format)


class GELU(Layer):
    def __init__(self, approximate=False, name=None):
        super().__init__()
        self.approximate = approximate

    def forward(self, x):
        return F.gelu(x, self.approximate)


class Sigmoid(Layer):
    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        return F.sigmoid(x)


class Tanh(Layer):
    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        return F.tanh(x)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.softmax(x, self.axis)


class LogSoftmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.log_softmax(x, self.axis)


ELU = _make("elu")
SELU = _make("selu")
CELU = _make("celu")
Silu = _make("silu")
Swish = _make("swish")
Mish = _make("mish")
Hardswish = _make("hardswish")
Hardsigmoid = _make("hardsigmoid")
Hardtanh = _make("hardtanh")
Hardshrink = _make("hardshrink")
Softshrink = _make("softshrink")
Tanhshrink = _make("tanhshrink")
ThresholdedReLU = _make("thresholded_relu")
Softplus = _make("softplus")
Softsign = _make("softsign")
LogSigmoid = _make("log_sigmoid")
Maxout = _make("maxout")
GLU = _make("glu")
RReLU = _make("rrelu")
