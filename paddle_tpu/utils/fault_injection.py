"""Site-based fault injection for robustness tests.

Production code threads named *sites* through its failure-prone
operations (``fire("checkpoint.write")`` before a file write,
``fire("store.add")`` inside the TCPStore retry loop, ...). Tests arm a
site with :func:`inject` (or the :func:`injected` context manager) and
the next ``times`` passages through it raise the armed exception,
truncate the write, or simulate a process kill. Unarmed sites cost one
dict lookup on a module-level table — nothing in the hot path imports,
locks, or allocates.

Kill-points raise :class:`KillPoint`, a BaseException subclass, so
``except Exception`` recovery code cannot accidentally "survive" a
simulated preemption — only the test harness catches it.
"""
from __future__ import annotations

import os
import signal
import threading
import time
from contextlib import contextmanager
from typing import Dict, Optional

__all__ = ["KillPoint", "InjectedFault", "inject", "clear", "fire",
           "write_bytes", "injected", "stats", "armed",
           "inject_transport", "FlakyTransport", "kill_pid"]


class KillPoint(BaseException):
    """Simulated process death (SIGKILL / preemption) at a named site.

    BaseException on purpose: recovery paths that swallow ``Exception``
    must not treat a kill as a survivable I/O error.
    """


class InjectedFault(OSError):
    """Default exception raised by an armed site."""


class _Fault:
    __slots__ = ("exc", "times", "truncate_at", "kill", "skip", "fired")

    def __init__(self, exc, times, truncate_at, kill, skip):
        self.exc = exc
        self.times = times
        self.truncate_at = truncate_at
        self.kill = kill
        self.skip = skip
        self.fired = 0


class _TransportFault:
    __slots__ = ("drop", "duplicate", "delay", "times", "skip", "fired")

    def __init__(self, drop, duplicate, delay, times, skip):
        self.drop = drop
        self.duplicate = duplicate
        self.delay = delay
        self.times = times
        self.skip = skip
        self.fired = 0


_lock = threading.Lock()
_sites: Dict[str, _Fault] = {}
_transport_sites: Dict[str, _TransportFault] = {}
_fired_total: Dict[str, int] = {}


def _register_telemetry() -> None:
    """Expose the injected-fault tally in the process metrics registry
    (``observability.snapshot()['faults']['injected_total']``) as a
    snapshot-time view — the unarmed-site fast path stays one dict
    lookup. ``stats()`` below remains the legacy surface."""
    from ..observability import metrics as _om

    def collect():
        with _lock:
            tally = dict(_fired_total)
        return {"faults.injected_total": tally} if tally else {}

    _om.register_collector("fault_injection", collect)


_register_telemetry()


def inject(site: str, exc: Optional[BaseException] = None, times: int = 1,
           truncate_at: Optional[int] = None, kill: bool = False,
           skip: int = 0) -> None:
    """Arm ``site`` to fail its next ``times`` passages (after ``skip``
    clean ones).

    exc:         exception instance to raise (default InjectedFault).
    truncate_at: for write sites — persist only the first N bytes
                 (combine with ``kill=True`` for a mid-write preemption).
    kill:        raise KillPoint instead of ``exc``.
    skip:        let this many passages through unharmed first (fail the
                 Nth save, not the first).
    """
    with _lock:
        _sites[site] = _Fault(exc, int(times), truncate_at, kill, int(skip))


def clear(site: Optional[str] = None) -> None:
    """Disarm one site, or every site when called with no argument
    (transport perturbations included)."""
    with _lock:
        if site is None:
            _sites.clear()
            _transport_sites.clear()
        else:
            _sites.pop(site, None)
            _transport_sites.pop(site, None)


def armed(site: str) -> bool:
    return site in _sites


def stats() -> Dict[str, int]:
    """site -> total faults fired (survives clear(); for test asserts)."""
    with _lock:
        return dict(_fired_total)


def _consume(site: str) -> Optional[_Fault]:
    """Take one shot from an armed site, or None for a clean passage."""
    with _lock:
        f = _sites.get(site)
        if f is None:
            return None
        if f.skip > 0:
            f.skip -= 1
            return None
        if f.times <= 0:
            return None
        f.times -= 1
        f.fired += 1
        _fired_total[site] = _fired_total.get(site, 0) + 1
        if f.times <= 0:
            del _sites[site]
        return f


def fire(site: str) -> None:
    """Checkpoint a failure-prone operation: raises if ``site`` is armed
    with an exception or kill-point; no-op otherwise (truncation-only
    arms are left for :func:`write_bytes` to consume)."""
    if site not in _sites:  # unlocked fast path; arming is test-side
        return
    f = _consume(site)
    if f is None:
        return
    if f.kill and f.truncate_at is None:
        raise KillPoint(site)
    if f.truncate_at is not None:
        # a truncation arm belongs to write_bytes; re-arm the shot
        with _lock:
            f.times += 1
            f.fired -= 1
            _fired_total[site] -= 1
            _sites[site] = f
        return
    raise f.exc if f.exc is not None else InjectedFault(
        f"injected fault at {site!r}")


def write_bytes(site: str, fileobj, blob: bytes) -> int:
    """Write ``blob`` through an injectable site. An armed truncation
    writes only ``truncate_at`` bytes then raises (KillPoint when
    ``kill=True``, else the armed/default exception) — the on-disk state
    a real preemption mid-write leaves behind."""
    f = _consume(site) if site in _sites else None
    if f is None:
        fileobj.write(blob)
        return len(blob)
    if f.truncate_at is None:
        if f.kill:
            raise KillPoint(site)
        raise f.exc if f.exc is not None else InjectedFault(
            f"injected fault at {site!r}")
    n = max(0, min(int(f.truncate_at), len(blob)))
    fileobj.write(blob[:n])
    fileobj.flush()
    if f.kill:
        raise KillPoint(site)
    raise f.exc if f.exc is not None else InjectedFault(
        f"injected truncation at {site!r} after {n} bytes")


@contextmanager
def injected(site: str, **kwargs):
    """``with injected("store.add", times=2): ...`` — arm for the block,
    disarm on exit even if the block dies."""
    inject(site, **kwargs)
    try:
        yield
    finally:
        clear(site)


class FlakyStore:
    """Store wrapper failing the first ``fail_times`` calls of each
    wrapped op with ConnectionResetError — a transport-level flake for
    components (elastic membership) tested against a pure-python store
    double, where the in-store injection sites don't exist."""

    _OPS = ("set", "get", "get_nowait", "add", "take", "delete", "wait")

    def __init__(self, store, fail_times: int = 1, ops=None):
        self._store = store
        self._remaining = {op: int(fail_times)
                           for op in (ops or self._OPS)}
        self.faults_fired = 0

    def __getattr__(self, name):
        target = getattr(self._store, name)
        if name not in self._remaining or not callable(target):
            return target

        def flaky(*a, **kw):
            if self._remaining[name] > 0:
                self._remaining[name] -= 1
                self.faults_fired += 1
                raise ConnectionResetError(
                    f"injected flaky store op {name!r}")
            return target(*a, **kw)

        return flaky


# ---------------------------------------------------------------------------
# transport-level perturbation (fleet RPC chaos)
# ---------------------------------------------------------------------------
def inject_transport(site: str, drop: bool = False, duplicate: bool = False,
                     delay: float = 0.0, times: int = 1,
                     skip: int = 0) -> None:
    """Arm ``site`` to perturb its next ``times`` frames (after ``skip``
    clean ones) as they pass through a :class:`FlakyTransport`.

    drop:      the frame vanishes — a send is never written, a received
               frame is discarded and the NEXT one delivered instead.
    duplicate: the frame arrives twice (at-least-once delivery the
               receiver's dedup path must absorb).
    delay:     sleep this many seconds before the frame moves (reorder /
               heartbeat-stall pressure without wall-clock test sleeps
               elsewhere).
    """
    with _lock:
        _transport_sites[site] = _TransportFault(
            bool(drop), bool(duplicate), float(delay), int(times),
            int(skip))


def _consume_transport(site: str) -> Optional[_TransportFault]:
    with _lock:
        f = _transport_sites.get(site)
        if f is None:
            return None
        if f.skip > 0:
            f.skip -= 1
            return None
        if f.times <= 0:
            return None
        f.times -= 1
        f.fired += 1
        _fired_total[site] = _fired_total.get(site, 0) + 1
        if f.times <= 0:
            del _transport_sites[site]
        return f


class FlakyTransport:
    """Wraps a frame transport — any object with ``send(obj)`` and
    ``recv()`` (the fleet RPC connection) — and perturbs whole frames at
    armed transport sites. Sends consult ``<site>.send``, receives
    ``<site>.recv``; arm them with :func:`inject_transport`. Unarmed
    frames cost one dict lookup; everything else (close, fileno, ...)
    passes straight through, so production code can thread every
    connection through this wrapper unconditionally.
    """

    def __init__(self, transport, site: str):
        self._t = transport
        self.site = site
        self._replay = []  # frames queued by a recv-side duplicate

    def send(self, obj):
        f = (_consume_transport(self.site + ".send")
             if _transport_sites else None)
        if f is not None:
            if f.delay > 0:
                time.sleep(f.delay)
            if f.drop:
                return None  # the peer never sees this frame
            if f.duplicate:
                self._t.send(obj)
        return self._t.send(obj)

    def recv(self):
        if self._replay:
            return self._replay.pop(0)
        f = (_consume_transport(self.site + ".recv")
             if _transport_sites else None)
        if f is not None and f.delay > 0:
            time.sleep(f.delay)
        obj = self._t.recv()
        if f is not None:
            if f.drop:
                return self._t.recv()  # discard; deliver the next frame
            if f.duplicate:
                self._replay.append(obj)
        return obj

    def __getattr__(self, name):
        return getattr(self._t, name)


def kill_pid(site: str, pid: int) -> bool:
    """SIGKILL ``pid`` when ``site`` is armed; no-op (False) otherwise.

    The deterministic chaos trigger for fleet tests: production code
    calls this at a well-defined point (the router just applied the
    k-th streamed token, a replica just acked admission) and an armed
    test turns exactly that point into a real child-process death — no
    sleep-and-hope timing. The unarmed fast path is one dict lookup.
    Refuses to signal the calling process itself.
    """
    if site not in _sites:
        return False
    f = _consume(site)
    if f is None:
        return False
    pid = int(pid)
    if pid == os.getpid() or pid <= 0:
        return False
    os.kill(pid, signal.SIGKILL)
    return True
