from . import clip_grad  # noqa: F401
