from . import backoff  # noqa: F401
from . import clip_grad  # noqa: F401
from . import custom_op  # noqa: F401
from . import download  # noqa: F401
from . import fault_injection  # noqa: F401
from .custom_op import register_op  # noqa: F401
from .helpers import (  # noqa: F401
    deprecated, require_version, run_check, try_import)

__all__ = ["deprecated", "run_check", "require_version", "try_import"]
