from . import clip_grad  # noqa: F401
from . import custom_op  # noqa: F401
from . import download  # noqa: F401
from .custom_op import register_op  # noqa: F401
