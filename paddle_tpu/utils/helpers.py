"""General helpers: deprecation decorator, env check, version gates.

ref: python/paddle/utils/__init__.py __all__ = ['deprecated',
'run_check', 'require_version', 'try_import'] (impls in
utils/deprecated.py, utils/install_check.py, utils/lazy_import.py).
"""
from __future__ import annotations

import functools
import warnings
from types import ModuleType
from typing import Callable, Optional

__all__ = ["deprecated", "run_check", "require_version", "try_import"]


def deprecated(update_to: str = "", since: str = "", reason: str = "",
               level: int = 0) -> Callable:
    """Mark an API deprecated (ref: utils/deprecated.py): appends a
    deprecation notice to the docstring and warns on call. level 0 =
    note only, 1 = also warn at call time, 2 = raise (API removed)."""

    def decorator(fn):
        note = "\n\n.. warning:: Deprecated"
        if since:
            note += f" since {since}"
        note += "."
        if update_to:
            note += f" Use :ref:`{update_to}` instead."
        if reason:
            note += f" Reason: {reason}"
        fn.__doc__ = (fn.__doc__ or "") + note

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if level == 2:
                raise RuntimeError(
                    f"API {fn.__name__} has been deprecated"
                    + (f"; use {update_to} instead" if update_to else ""))
            if level >= 1:
                warnings.warn(
                    f"API {fn.__name__} is deprecated"
                    + (f" since {since}" if since else "")
                    + (f"; use {update_to} instead" if update_to else ""),
                    DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)

        return wrapper

    return decorator


def run_check() -> None:
    """Sanity-check the installation on the available device: one tiny
    matmul + grad must execute (ref: utils/install_check.py run_check —
    same contract, prints the verdict)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    x = jnp.asarray(np.ones((4, 4), np.float32))
    y = jax.grad(lambda a: jnp.sum(a @ a))(x)
    assert y.shape == (4, 4)
    backend = jax.default_backend()
    n = len(jax.devices())
    print(f"paddle_tpu is installed successfully! backend={backend}, "
          f"{n} device(s) visible.")


def require_version(min_version: str,
                    max_version: Optional[str] = None) -> None:
    """Raise unless the installed version is within [min, max]
    (ref: utils/__init__ require_version)."""
    from .. import __version__

    def key(v: str):
        parts = []
        for p in str(v).split("."):
            digits = "".join(ch for ch in p if ch.isdigit())
            parts.append(int(digits) if digits else 0)
        return tuple(parts + [0] * (4 - len(parts)))

    if not isinstance(min_version, str) or (
            max_version is not None and not isinstance(max_version, str)):
        raise TypeError("version arguments must be strings")
    cur = key(__version__)
    if cur < key(min_version):
        raise Exception(
            f"installed version {__version__} < required minimum "
            f"{min_version}")
    if max_version is not None and cur > key(max_version):
        raise Exception(
            f"installed version {__version__} > required maximum "
            f"{max_version}")


def try_import(module_name: str,
               err_msg: Optional[str] = None) -> ModuleType:
    """Import a module, raising a friendlier install hint on failure
    (ref: utils/lazy_import.py)."""
    import importlib
    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        raise ImportError(
            err_msg or f"module {module_name!r} is required but not "
            f"installed; pip install {module_name.split('.')[0]}") from e
