"""Pretrained-weight distribution: URL fetch + cache + md5 check.

ref: python/paddle/utils/download.py (get_weights_path_from_url,
WEIGHTS_HOME, _md5check). Weights cache under
~/.cache/paddle_tpu/weights (override: PADDLE_TPU_WEIGHTS_HOME). For
air-gapped machines the documented local override is
PADDLE_TPU_PRETRAINED_DIR: a directory searched FIRST by file name —
drop reference-format .pdparams files there and pretrained=True works
with no network. Offline with no local file fails loudly, naming both
the URL and the override.
"""
from __future__ import annotations

import hashlib
import os
import os.path as osp
import shutil

__all__ = ["get_weights_path_from_url", "get_path_from_url",
           "WEIGHTS_HOME"]

WEIGHTS_HOME = os.environ.get(
    "PADDLE_TPU_WEIGHTS_HOME",
    osp.expanduser("~/.cache/paddle_tpu/weights"))

# probed once at import (single-threaded): os.umask is process-wide, so
# toggling it per-download would race any other thread creating files
_UMASK = os.umask(0)
os.umask(_UMASK)


def _md5check(fullname: str, md5sum: str | None = None) -> bool:
    """ref: download.py _md5check — streaming md5 of the file."""
    if md5sum is None:
        return True
    md5 = hashlib.md5()
    with open(fullname, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            md5.update(chunk)
    return md5.hexdigest() == md5sum


def _local_override(fname: str, md5sum: str | None):
    d = os.environ.get("PADDLE_TPU_PRETRAINED_DIR")
    if not d:
        return None
    cand = osp.join(d, fname)
    if osp.isfile(cand):
        if not _md5check(cand, md5sum):
            raise ValueError(
                f"{cand} (from PADDLE_TPU_PRETRAINED_DIR) fails its md5 "
                f"check — expected {md5sum}; re-download the weights")
        return cand
    return None


def get_path_from_url(url: str, root_dir: str, md5sum: str | None = None,
                      check_exist: bool = True) -> str:
    """ref: download.py get_path_from_url — cached download of ``url``
    into ``root_dir`` with an md5 gate (archives are not auto-extracted;
    weight files are single .pdparams blobs)."""
    fname = osp.basename(url)
    local = _local_override(fname, md5sum)
    if local is not None:
        return local
    fullname = osp.join(root_dir, fname)
    if check_exist and osp.isfile(fullname) and _md5check(fullname, md5sum):
        return fullname
    os.makedirs(root_dir, exist_ok=True)
    # unique temp per caller: concurrent ranks downloading the same
    # weights must not interleave into one .part file
    import tempfile
    fd, tmp = tempfile.mkstemp(dir=root_dir, prefix=fname + ".part.")
    os.close(fd)
    # mkstemp creates 0600 regardless of umask; restore the
    # umask-governed mode so a shared cache stays readable (and a
    # restrictive umask stays respected)
    os.chmod(tmp, 0o666 & ~_UMASK)
    try:
        import urllib.request
        with urllib.request.urlopen(url, timeout=60) as r, \
                open(tmp, "wb") as f:
            shutil.copyfileobj(r, f)
    except Exception as e:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise RuntimeError(
            f"could not download pretrained weights from {url} ({e}). "
            f"On an offline machine, place the file at "
            f"{fullname}, or point PADDLE_TPU_PRETRAINED_DIR at a "
            f"directory containing {fname}") from e
    if not _md5check(tmp, md5sum):
        os.unlink(tmp)
        raise RuntimeError(
            f"downloaded {url} but its md5 does not match {md5sum} "
            f"(corrupted transfer or changed artifact)")
    os.replace(tmp, fullname)
    return fullname


def get_weights_path_from_url(url: str, md5sum: str | None = None) -> str:
    """ref: download.py get_weights_path_from_url — fetch into the
    weights cache (or resolve via PADDLE_TPU_PRETRAINED_DIR)."""
    return get_path_from_url(url, WEIGHTS_HOME, md5sum)
