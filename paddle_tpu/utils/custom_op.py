"""Custom-op registration: user kernels joined to the framework op surface.

ref: paddle/phi/api/ext/op_meta_info.h PD_BUILD_OP +
fluid/framework/custom_operator.cc + python/paddle/utils/cpp_extension/
(JIT-built C++ ops). The TPU equivalent of "bring your own kernel" is a
Pallas kernel (or any pure JAX function): register it with an optional
custom VJP and it becomes `paddle_tpu.ops.<name>`, differentiable through
the eager tape and traceable under jit — the same contract the
reference's custom ops get from the eager engine.
"""
from __future__ import annotations

from typing import Callable, Dict, Optional

import jax

from ..core.autograd import apply_op

__all__ = ["CustomOp", "register_op", "get_op"]

_REGISTRY: Dict[str, "CustomOp"] = {}


class CustomOp:
    def __init__(self, name: str, fn: Callable,
                 vjp: Optional[Callable] = None):
        self.name = name
        self._has_vjp = vjp is not None
        if vjp is not None:
            raw = jax.custom_vjp(fn)
            raw.defvjp(lambda *args: (fn(*args), args),
                       lambda res, g: vjp(res, g))
            self._fn = raw
        else:
            self._fn = fn

    def __call__(self, *tensors, **kwargs):
        if self._has_vjp and kwargs:
            # jax.custom_vjp folds kwargs into the primal tuple, breaking
            # the "one gradient per positional input" contract
            raise ValueError(
                f"custom op {self.name!r} has a custom vjp and must be "
                "called with positional arguments only")
        return apply_op(self._fn, *tensors, op_name=self.name, **kwargs)


def register_op(name: str, fn: Callable = None, *,
                vjp: Optional[Callable] = None,
                override: bool = False):
    """Register `fn` (pure JAX, arrays in/out) as op `name`.

    vjp(saved_inputs, cotangent) -> tuple of input gradients; omit it to
    let JAX differentiate through fn. Usable as a decorator:

        @register_op("my_norm")
        def my_norm(x): ...

    The op lands on paddle_tpu.ops.<name> (ref: custom ops appearing under
    paddle._C_ops after PD_BUILD_OP registration).
    """
    def _do(f):
        from .. import ops as ops_module
        if not override and (name in _REGISTRY
                             or hasattr(ops_module, name)):
            raise ValueError(
                f"op {name!r} already exists (pass override=True to "
                "replace it deliberately)")
        op = CustomOp(name, f, vjp)
        _REGISTRY[name] = op
        setattr(ops_module, name, op)
        return op

    if fn is not None:
        return _do(fn)
    return _do


def get_op(name: str) -> CustomOp:
    return _REGISTRY[name]
