"""Full-jitter for bounded-exponential backoffs.

Every retry loop in the repo (ServingSupervisor restarts, TCPStore
client ops, the fleet router's replica resurrection) backs off as
``base * 2^(attempt-1)`` capped at a bound. Without jitter, a shared
failure — the coordinator restarting, one replica dying under N
routers — synchronizes every retrier onto the same schedule and they
stampede the recovering component in waves. Full jitter (the AWS
architecture-blog result): sleep ``uniform(0, bound)`` instead of
``bound`` — the expected extra latency is half a bound, the herd is
spread across the whole window, and the worst case never exceeds the
un-jittered sleep.

``FLAGS_backoff_full_jitter=0`` is the kill switch (restores the
deterministic schedule — what the pre-jitter tests pinned), and
:func:`seed` makes the draw reproducible for tests that assert on the
jittered path itself.
"""
from __future__ import annotations

import random
import threading

from ..core.flags import define_flag, flag_value

__all__ = ["full_jitter", "seed"]

define_flag(
    "backoff_full_jitter", True,
    "Full jitter on every bounded-exponential backoff (supervisor "
    "restarts, TCPStore retries, fleet replica resurrection): sleep "
    "uniform(0, bound) instead of the deterministic bound, so "
    "correlated failures do not synchronize retriers into a stampede. "
    "0 restores the deterministic schedule; utils.backoff.seed(n) "
    "makes the jittered draws reproducible for tests")

_lock = threading.Lock()
_rng = random.Random()


def seed(n: int) -> None:
    """Re-seed the jitter RNG (tests pinning the jittered schedule)."""
    with _lock:
        _rng.seed(n)


def full_jitter(bound: float) -> float:
    """The sleep for one backoff step whose un-jittered value is
    ``bound``: ``uniform(0, bound)`` under the flag (default), else
    ``bound`` unchanged. Never negative."""
    bound = max(float(bound), 0.0)
    if bound == 0.0 or not flag_value("backoff_full_jitter"):
        return bound
    with _lock:
        return _rng.uniform(0.0, bound)
