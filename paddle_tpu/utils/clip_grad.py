"""Gradient clipping strategies.

ref: python/paddle/nn/clip.py (ClipGradByGlobalNorm etc.). Operate on
(param, grad) lists; the distributed variant that allreduces the norm
across mesh axes lives in distributed.fleet (hybrid_parallel_optimizer).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor


class ClipGradBase:
    def __call__(self, params_grads):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = max
        self.min = -max if min is None else min

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            gd = g._data if isinstance(g, Tensor) else g
            out.append((p, Tensor(jnp.clip(gd, self.min, self.max))))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            gd = g._data if isinstance(g, Tensor) else g
            norm = jnp.sqrt(jnp.sum(jnp.square(gd.astype(jnp.float32))))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12),
                                1.0)
            out.append((p, Tensor((gd * scale).astype(gd.dtype))))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group",
                 auto_skip_clip=False):
        self.clip_norm = clip_norm

    def __call__(self, params_grads):
        sq = []
        for _, g in params_grads:
            if g is None:
                continue
            gd = g._data if isinstance(g, Tensor) else g
            sq.append(jnp.sum(jnp.square(gd.astype(jnp.float32))))
        if not sq:
            return params_grads
        global_norm = jnp.sqrt(sum(sq))
        scale = self.clip_norm / jnp.maximum(global_norm, self.clip_norm)
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            gd = g._data if isinstance(g, Tensor) else g
            out.append((p, Tensor((gd * scale).astype(gd.dtype))))
        return out


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    """torch-style helper also exposed by paddle.nn.utils."""
    params = [p for p in parameters if p.grad is not None]
    if not params:
        return Tensor(jnp.zeros(()))
    if norm_type == float("inf"):
        norms = [jnp.max(jnp.abs(p.grad._data)) for p in params]
        total = jnp.max(jnp.stack(norms))
    else:
        total = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(p.grad._data.astype(jnp.float32)) ** norm_type)
             for p in params])) ** (1.0 / norm_type)
    scale = max_norm / jnp.maximum(total, 1e-6)
    scale = jnp.minimum(scale, 1.0)
    for p in params:
        p.grad._data = (p.grad._data * scale).astype(p.grad._data.dtype)
    return Tensor(total)
