"""Gradient clipping strategies.

ref: python/paddle/nn/clip.py (ClipGradByGlobalNorm etc.). Operate on
(param, grad) lists; the distributed variant that allreduces the norm
across mesh axes lives in distributed.fleet (hybrid_parallel_optimizer).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor


class ClipGradBase:
    def __call__(self, params_grads):
        raise NotImplementedError


# -- pure functional core ---------------------------------------------------
# ONE numeric definition of each clip strategy over raw jnp grads, shared
# by the eager classes below, the compiled train steps (jit.api TrainStep)
# and the fused optimizer step (optimizer.fused_step): a clip is described
# by a static, hashable *spec* so it can ride a program cache key.

def clip_spec(clip, exact=True):
    """Static description of a known clip strategy: ``()`` for None,
    a hashable tuple for the three in-tree strategies, ``None`` for an
    unrecognized clip object (callers fall back to calling it).

    ``exact=True`` (the fused optimizer's gate) matches only the exact
    in-tree classes — a subclass may override ``__call__`` and must go
    through it. ``exact=False`` (the classes' own ``__call__`` plumbing
    and TrainStep's in-trace clip) matches subclasses too, preserving
    the inherited behavior."""
    if clip is None:
        return ()
    match = ((lambda c: type(clip) is c) if exact
             else (lambda c: isinstance(clip, c)))
    if match(ClipGradByGlobalNorm):
        return ("global_norm", float(clip.clip_norm))
    if match(ClipGradByNorm):
        return ("norm", float(clip.clip_norm))
    if match(ClipGradByValue):
        return ("value", float(clip.min), float(clip.max))
    return None


def global_norm_scale(grads, clip_norm):
    """Pure: the ClipGradByGlobalNorm scale factor over raw jnp grads."""
    sq = [jnp.sum(jnp.square(g.astype(jnp.float32))) for g in grads]
    global_norm = jnp.sqrt(sum(sq))
    return clip_norm / jnp.maximum(global_norm, clip_norm)


def clip_by_spec(spec, grads):
    """Apply a ``clip_spec`` to a list of raw jnp grads (pure, jittable)."""
    if not spec or not grads:
        return grads
    kind = spec[0]
    if kind == "value":
        _, lo, hi = spec
        return [jnp.clip(g, lo, hi) for g in grads]
    if kind == "norm":
        _, cn = spec
        out = []
        for g in grads:
            norm = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
            s = jnp.minimum(cn / jnp.maximum(norm, 1e-12), 1.0)
            out.append((g * s).astype(g.dtype))
        return out
    _, cn = spec  # global_norm
    s = global_norm_scale(grads, cn)
    return [(g * s).astype(g.dtype) for g in grads]


def _apply_class_clip(clip, params_grads):
    """Eager class -> pure core plumbing, preserving None-grad slots."""
    spec = clip_spec(clip, exact=False)
    idx = [i for i, (_, g) in enumerate(params_grads) if g is not None]
    grads = [params_grads[i][1] for i in idx]
    raw = [g._data if isinstance(g, Tensor) else g for g in grads]
    clipped = clip_by_spec(spec, raw)
    out = list(params_grads)
    for i, c in zip(idx, clipped):
        out[i] = (params_grads[i][0], Tensor(c))
    return out


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = max
        self.min = -max if min is None else min

    def __call__(self, params_grads):
        return _apply_class_clip(self, params_grads)


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def __call__(self, params_grads):
        return _apply_class_clip(self, params_grads)


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group",
                 auto_skip_clip=False):
        self.clip_norm = clip_norm

    def __call__(self, params_grads):
        if all(g is None for _, g in params_grads):
            return params_grads
        return _apply_class_clip(self, params_grads)


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    """torch-style helper also exposed by paddle.nn.utils."""
    params = [p for p in parameters if p.grad is not None]
    if not params:
        return Tensor(jnp.zeros(()))
    if norm_type == float("inf"):
        norms = [jnp.max(jnp.abs(p.grad._data)) for p in params]
        total = jnp.max(jnp.stack(norms))
    else:
        total = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(p.grad._data.astype(jnp.float32)) ** norm_type)
             for p in params])) ** (1.0 / norm_type)
    scale = max_norm / jnp.maximum(total, 1e-6)
    scale = jnp.minimum(scale, 1.0)
    for p in params:
        p.grad._data = (p.grad._data * scale).astype(p.grad._data.dtype)
    return Tensor(total)
