"""Pipeline-parallel Llama: decoder stack on the compiled GPipe schedule.

ref: the reference expresses this as PipelineLayer segmentation + the
fleet PP runtime (fleet/meta_parallel/pp_layers.py:257 LayerDesc
segmentation, pipeline_parallel.py 1F1B) — embedding on the first stage,
head on the last. TPU-native: embedding and head run data-parallel
outside the pipelined region (they are one matmul each); the decoder
stack runs inside parallel.spmd_pipeline with its stacked params sharded
on the 'pp' mesh axis, and jax.grad reverses the whole schedule. One jit
covers embed -> pipeline -> head -> loss -> backward -> optimizer.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..jit.api import functionalize
from ..parallel import spmd_pipeline, stack_layer_params
from .llama import LlamaConfig, LlamaForCausalLM

__all__ = ["LlamaForCausalLMPipe"]


class LlamaForCausalLMPipe:
    """Owns a LlamaForCausalLM's parameters but runs the decoder layers
    through the compiled pipeline. train_step is one jitted program.

    batch_axes: mesh axes sharding the micro-batch dim (dp composition,
    ref: hybrid pp+dp in HybridCommunicateGroup).
    """

    def __init__(self, config: LlamaConfig, mesh, pp_axis: str = "pp",
                 batch_axes=(), num_microbatches: int = 4):
        if config.num_hidden_layers % _axis_size(mesh, pp_axis) != 0:
            raise ValueError(
                f"num_hidden_layers={config.num_hidden_layers} must divide "
                f"over the '{pp_axis}' axis")
        self.config = config
        self.mesh = mesh
        self.pp_axis = pp_axis
        self.batch_axes = tuple(batch_axes)
        self.num_microbatches = num_microbatches
        self._model = LlamaForCausalLM(config)

        # functionalize one decoder layer as the stage program; stack all
        # layers' params into [L, ...] pytrees for the pipeline
        layer0 = self._model.llama.layers[0]
        self._stage_apply, _, _ = functionalize(layer0)
        per_layer = []
        for layer in self._model.llama.layers:
            per_layer.append({k: t._data
                              for k, t in dict(
                                  layer.named_parameters()).items()})
        self.stacked = stack_layer_params(per_layer)
        # the stacks are now the single authoritative copy of the decoder
        # weights: drop the serial model's per-layer buffers (halves param
        # memory) and rematerialize them lazily via the `model` property
        for layer in self._model.llama.layers:
            for t in dict(layer.named_parameters()).values():
                t._data = None
        self._serial_stale = True
        self._embed = self._model.llama.embed_tokens.weight
        self._norm_w = self._model.llama.norm.weight
        self._head = (None if config.tie_word_embeddings
                      else self._model.lm_head.weight)
        self._jitted = None

    def _stage_fn(self, p, h):
        out, _ = self._stage_apply(p, {}, Tensor(h))
        return out._data if isinstance(out, Tensor) else out

    def _forward(self, stacked, embed_w, norm_w, head_w, ids):
        """ids: [B, L] -> logits [B, L, V]; pipeline over micro-batches."""
        m = self.num_microbatches
        b = ids.shape[0]
        if b % m != 0:
            raise ValueError(
                f"batch size {b} must be divisible by "
                f"num_microbatches={m}")
        h = jnp.take(embed_w, ids, axis=0)       # embed (outside pipe)
        mb = h.reshape(m, b // m, *h.shape[1:])
        out = spmd_pipeline(self._stage_fn, stacked, mb, self.mesh,
                            self.pp_axis, self.batch_axes)
        h = out.reshape(b, *h.shape[1:])
        # final RMSNorm + head (outside pipe)
        from ..nn.functional.norm import rms_norm
        h = rms_norm(Tensor(h), Tensor(norm_w),
                     self.config.rms_norm_eps)._data
        w = embed_w.T if head_w is None else head_w
        return h @ w

    def forward_logits(self, ids):
        """Eager-facing forward through the pipeline (for parity tests)."""
        params = self._param_tree()
        return self._forward(params["stacked"], params["embed"],
                             params["norm"], params.get("head"),
                             jnp.asarray(ids))

    def _param_tree(self):
        params = {"stacked": self.stacked, "embed": self._embed._data,
                  "norm": self._norm_w._data}
        if self._head is not None:
            params["head"] = self._head._data
        return params

    def train_step(self, learning_rate: float = 1e-3):
        """Returns step(ids, labels) -> loss; pipeline fwd + bwd + SGD
        update compiled into one program."""
        from ..ops.fused_ce import fused_softmax_ce_mean

        def step_fn(params, ids, labels, lr):
            def loss_of(ps):
                logits = self._forward(
                    ps["stacked"], ps["embed"], ps["norm"],
                    ps.get("head"), ids)
                return fused_softmax_ce_mean(logits[:, :-1, :],
                                             labels[:, 1:])
            loss, grads = jax.value_and_grad(loss_of)(params)
            new_params = jax.tree.map(lambda p, g: p - lr * g, params,
                                      grads)
            return loss, new_params

        jitted = jax.jit(step_fn)

        def step(ids, labels):
            loss, new_params = jitted(self._param_tree(),
                                      jnp.asarray(ids),
                                      jnp.asarray(labels),
                                      jnp.float32(learning_rate))
            self._install(new_params)
            return loss

        return step

    @property
    def model(self):
        """The owned serial LlamaForCausalLM. The decoder weights live in
        the pp-sharded stacks between steps; reading this property slices
        them back onto the serial layers first, so state_dict()/save always
        see current weights."""
        self.sync_serial_model()
        return self._model

    def _install(self, params):
        """Write updated params back onto the object, so forward_logits / a
        new train_step resume from them. The per-layer writeback onto the
        owned serial model slices the pp-sharded stacks (cross-device
        gathers), so it is deferred to the `model` property rather than run
        every step."""
        self.stacked = params["stacked"]
        self._embed._data = params["embed"]
        self._norm_w._data = params["norm"]
        if self._head is not None:
            self._head._data = params["head"]
        self._serial_stale = True

    def sync_serial_model(self):
        """Slice the stacked pipeline params back onto the serial layers
        (runs automatically when `self.model` is read)."""
        if not self._serial_stale:
            return
        for i, layer in enumerate(self._model.llama.layers):
            for k, t in dict(layer.named_parameters()).items():
                t._data = self.stacked[k][i]
        self._serial_stale = False


def _axis_size(mesh, axis: str) -> int:
    jmesh = mesh.to_jax_mesh() if hasattr(mesh, "to_jax_mesh") else mesh
    sizes = dict(zip(jmesh.axis_names, jmesh.devices.shape))
    if axis not in sizes:
        raise ValueError(
            f"mesh has no '{axis}' axis (axes: {list(sizes)}); pass the "
            f"pipeline axis name via pp_axis")
    return sizes[axis]
