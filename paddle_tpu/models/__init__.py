"""Language-model zoo: the workload families from BASELINE.md.

ref: test/auto_parallel/hybrid_strategy/semi_auto_parallel_llama_model.py
(Llama), python/paddle/nn/layer/transformer.py (BERT building blocks),
incubate/distributed/models/moe/moe_layer.py (ERNIE-MoE). TPU-native:
every model is a plain nn.Layer whose parameters can carry NamedShardings
(tp/fsdp/sp placements), so one jit of the train step compiles the full
hybrid-parallel program.
"""
from .llama import (  # noqa: F401
    LlamaConfig, LlamaForCausalLM, LlamaModel, LlamaPretrainingCriterion,
    shard_llama,
)
from .gpt import GPTConfig, GPTForCausalLM, shard_gpt  # noqa: F401
from .bert import BertConfig, BertForMaskedLM, BertModel  # noqa: F401
from .ernie_moe import ErnieMoEConfig, ErnieMoEForCausalLM  # noqa: F401
from .llama_pipe import LlamaForCausalLMPipe  # noqa: F401
