"""ERNIE-MoE: transformer encoder-LM with MoE FFNs — BASELINE.md workload 5.

ref: the reference builds this from incubate/distributed/models/moe/
MoELayer dropped into an ERNIE (post-LN encoder) stack; expert parallel
dispatch/combine ran through global_scatter/global_gather alltoalls.
Here alternate layers use paddle_tpu.incubate.moe.MoELayer, whose expert
dim shards over the 'ep' mesh axis.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from ..core.tensor import Tensor
from ..incubate.moe import MoELayer
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.container import LayerList
from ..nn.layer import Layer
from ..nn.layers_common import Embedding, Linear
from ..nn.layers_conv_norm import LayerNorm
from .gpt import GPTAttention, GPTConfig

__all__ = ["ErnieMoEConfig", "ErnieMoEForCausalLM"]


@dataclass
class ErnieMoEConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    intermediate_size: int = 3072
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    max_position_embeddings: int = 2048
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    moe_every: int = 2          # every Nth layer is MoE
    aux_loss_weight: float = 0.01
    layer_norm_eps: float = 1e-5
    use_flash_attention: bool = True

    @staticmethod
    def tiny(**kw):
        base = dict(vocab_size=128, hidden_size=64, intermediate_size=128,
                    num_hidden_layers=2, num_attention_heads=4,
                    max_position_embeddings=128, num_experts=4)
        base.update(kw)
        return ErnieMoEConfig(**base)

    def _attn_cfg(self):
        return GPTConfig(
            vocab_size=self.vocab_size, hidden_size=self.hidden_size,
            num_hidden_layers=self.num_hidden_layers,
            num_attention_heads=self.num_attention_heads,
            max_position_embeddings=self.max_position_embeddings,
            use_flash_attention=self.use_flash_attention)


class ErnieMoEBlock(Layer):
    def __init__(self, config: ErnieMoEConfig, use_moe: bool):
        super().__init__()
        self.ln_1 = LayerNorm(config.hidden_size, config.layer_norm_eps)
        self.attn = GPTAttention(config._attn_cfg())
        self.ln_2 = LayerNorm(config.hidden_size, config.layer_norm_eps)
        self.use_moe = use_moe
        if use_moe:
            self.moe = MoELayer(config.hidden_size,
                                config.intermediate_size,
                                config.num_experts, gate="gshard",
                                top_k=config.top_k,
                                capacity_factor=config.capacity_factor)
        else:
            self.fc_in = Linear(config.hidden_size,
                                config.intermediate_size)
            self.fc_out = Linear(config.intermediate_size,
                                 config.hidden_size)

    def forward(self, h):
        h = h + self.attn(self.ln_1(h))
        if self.use_moe:
            h = h + self.moe(self.ln_2(h))
        else:
            h = h + self.fc_out(F.gelu(self.fc_in(self.ln_2(h))))
        return h


class ErnieMoEForCausalLM(Layer):
    def __init__(self, config: ErnieMoEConfig):
        super().__init__()
        self.config = config
        self.wte = Embedding(config.vocab_size, config.hidden_size,
                             weight_attr=I.Normal(0.0, 0.02))
        self.wpe = Embedding(config.max_position_embeddings,
                             config.hidden_size,
                             weight_attr=I.Normal(0.0, 0.02))
        self.blocks = LayerList([
            ErnieMoEBlock(config, use_moe=(i % config.moe_every ==
                                           config.moe_every - 1))
            for i in range(config.num_hidden_layers)])
        self.ln_f = LayerNorm(config.hidden_size, config.layer_norm_eps)
        self.lm_head = Linear(config.hidden_size, config.vocab_size,
                              bias_attr=False)

    def forward(self, input_ids):
        l = input_ids.shape[1]
        pos = Tensor(jnp.arange(l, dtype=jnp.int32)[None, :])
        h = self.wte(input_ids) + self.wpe(pos)
        for blk in self.blocks:
            h = blk(h)
        return self.lm_head(self.ln_f(h))

    def total_aux_loss(self):
        """Sum of gate load-balancing losses, weighted; add to the LM loss."""
        total = None
        for blk in self.blocks:
            if blk.use_moe and blk.moe.aux_loss is not None:
                total = blk.moe.aux_loss if total is None else \
                    total + blk.moe.aux_loss
        if total is None:
            return None
        return total * self.config.aux_loss_weight

    def shard_experts(self, mesh, ep_axis: str = "ep"):
        from ..distributed.api import shard_parameter
        # all params must live on the mesh for one jit: non-expert weights
        # replicate; expert stacks go straight to Shard(0) on ep (never
        # materialize the full [E, ...] stack per chip)
        expert_params = {id(blk.moe.w_in) for blk in self.blocks
                         if blk.use_moe} | \
                        {id(blk.moe.w_out) for blk in self.blocks
                         if blk.use_moe}
        for _, p in self.named_parameters():
            if p is not None and id(p) not in expert_params:
                shard_parameter(p, mesh)
        for blk in self.blocks:
            if blk.use_moe:
                blk.moe.shard_experts(mesh, ep_axis)
        return self
