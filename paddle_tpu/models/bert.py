"""BERT family (encoder + MLM head) — BASELINE.md workload 2.

ref: the reference's BERT path is paddle.nn.TransformerEncoder assembled by
user code (docs + test/book); here the encoder reuses
paddle_tpu.nn.TransformerEncoder so the benchmark exercises the same layer
stack a reference user would. Whole-model jit gives the "static graph +
fusion" execution the reference gets from to_static + CINN.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from ..core.tensor import Tensor
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer import Layer
from ..nn.layers_common import Dropout, Embedding, Linear
from ..nn.layers_conv_norm import LayerNorm
from ..nn.transformer import TransformerEncoder, TransformerEncoderLayer

__all__ = ["BertConfig", "BertModel", "BertForMaskedLM"]


@dataclass
class BertConfig:
    """Defaults = BERT-base."""
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    dropout: float = 0.1

    @staticmethod
    def tiny(**kw):
        base = dict(vocab_size=128, hidden_size=64, num_hidden_layers=2,
                    num_attention_heads=4, intermediate_size=128,
                    max_position_embeddings=128)
        base.update(kw)
        return BertConfig(**base)


class BertEmbeddings(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.word_embeddings = Embedding(config.vocab_size,
                                         config.hidden_size,
                                         weight_attr=I.Normal(0.0, 0.02))
        self.position_embeddings = Embedding(config.max_position_embeddings,
                                             config.hidden_size,
                                             weight_attr=I.Normal(0.0, 0.02))
        self.token_type_embeddings = Embedding(config.type_vocab_size,
                                               config.hidden_size,
                                               weight_attr=I.Normal(0.0, 0.02))
        self.layer_norm = LayerNorm(config.hidden_size, config.layer_norm_eps)
        self.dropout = Dropout(config.dropout)

    def forward(self, input_ids, token_type_ids=None):
        l = input_ids.shape[1]
        pos = Tensor(jnp.arange(l, dtype=jnp.int32)[None, :])
        h = self.word_embeddings(input_ids) + self.position_embeddings(pos)
        if token_type_ids is not None:
            h = h + self.token_type_embeddings(token_type_ids)
        return self.dropout(self.layer_norm(h))


class BertModel(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        self.embeddings = BertEmbeddings(config)
        enc_layer = TransformerEncoderLayer(
            config.hidden_size, config.num_attention_heads,
            config.intermediate_size, dropout=config.dropout,
            activation="gelu", layer_norm_eps=config.layer_norm_eps)
        self.encoder = TransformerEncoder(enc_layer,
                                          config.num_hidden_layers)
        self.pooler = Linear(config.hidden_size, config.hidden_size)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        h = self.embeddings(input_ids, token_type_ids)
        h = self.encoder(h, attention_mask)
        pooled = F.tanh(self.pooler(h[:, 0]))
        return h, pooled


class BertForMaskedLM(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        self.bert = BertModel(config)
        self.transform = Linear(config.hidden_size, config.hidden_size)
        self.transform_norm = LayerNorm(config.hidden_size,
                                        config.layer_norm_eps)
        self.decoder = Linear(config.hidden_size, config.vocab_size)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        h, _ = self.bert(input_ids, token_type_ids, attention_mask)
        h = self.transform_norm(F.gelu(self.transform(h)))
        return self.decoder(h)
