"""GPT family (GPT-2/3-style decoder) — BASELINE.md workload 4.

ref: the reference ships GPT through its fleet hybrid examples
(test/collective/fleet/hybrid_parallel_*), architecture = pre-LN causal
transformer with learned positions. Shares the placement-rule design of
llama.shard_llama for hybrid TP x FSDP meshes.
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

from ..core.autograd import apply_op
from ..core.tensor import Tensor
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.container import LayerList
from ..nn.layer import Layer
from ..nn.layers_common import Dropout, Embedding, Linear
from ..nn.layers_conv_norm import LayerNorm

__all__ = ["GPTConfig", "GPTForCausalLM", "shard_gpt"]


@dataclass
class GPTConfig:
    """Defaults approximate GPT-3 13B per-layer geometry scaled down; use
    `GPTConfig(hidden_size=5120, num_hidden_layers=40, num_attention_heads=40)`
    for the 13B benchmark config."""
    vocab_size: int = 50304
    hidden_size: int = 768
    intermediate_size: Optional[int] = None    # default 4*hidden
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    max_position_embeddings: int = 2048
    layer_norm_eps: float = 1e-5
    dropout: float = 0.0
    use_flash_attention: bool = True

    def __post_init__(self):
        if self.intermediate_size is None:
            self.intermediate_size = 4 * self.hidden_size

    @staticmethod
    def tiny(**kw):
        base = dict(vocab_size=128, hidden_size=64, num_hidden_layers=2,
                    num_attention_heads=4, max_position_embeddings=128)
        base.update(kw)
        return GPTConfig(**base)


class GPTAttention(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.num_heads = config.num_attention_heads
        self.head_dim = config.hidden_size // config.num_attention_heads
        self.hidden_size = config.hidden_size
        self.use_flash = config.use_flash_attention
        self.qkv_proj = Linear(config.hidden_size, 3 * config.hidden_size)
        self.out_proj = Linear(config.hidden_size, config.hidden_size)

    def forward(self, h):
        b, l, _ = h.shape
        qkv = self.qkv_proj(h)

        def attn(qkv_arr):
            q, k, v = jnp.split(qkv_arr, 3, axis=-1)
            q = q.reshape(b, l, self.num_heads, self.head_dim)
            k = k.reshape(b, l, self.num_heads, self.head_dim)
            v = v.reshape(b, l, self.num_heads, self.head_dim)
            from ..ops.pallas.flash_attention import (_sdpa_xla,
                                                      flash_attention)
            if self.use_flash:
                out = flash_attention(q, k, v, True, None)
            else:
                out = _sdpa_xla(q, k, v, causal=True)
            return out.reshape(b, l, self.hidden_size)

        return self.out_proj(apply_op(attn, qkv, op_name="gpt_attention"))


class GPTBlock(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.ln_1 = LayerNorm(config.hidden_size, config.layer_norm_eps)
        self.attn = GPTAttention(config)
        self.ln_2 = LayerNorm(config.hidden_size, config.layer_norm_eps)
        self.fc_in = Linear(config.hidden_size, config.intermediate_size)
        self.fc_out = Linear(config.intermediate_size, config.hidden_size)
        self.drop = Dropout(config.dropout)

    def forward(self, h):
        h = h + self.attn(self.ln_1(h))
        h = h + self.drop(self.fc_out(F.gelu(self.fc_in(self.ln_2(h)))))
        return h


class GPTForCausalLM(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.wte = Embedding(config.vocab_size, config.hidden_size,
                             weight_attr=I.Normal(0.0, 0.02))
        self.wpe = Embedding(config.max_position_embeddings,
                             config.hidden_size,
                             weight_attr=I.Normal(0.0, 0.02))
        self.blocks = LayerList([GPTBlock(config)
                                 for _ in range(config.num_hidden_layers)])
        self.ln_f = LayerNorm(config.hidden_size, config.layer_norm_eps)
        self.lm_head = Linear(config.hidden_size, config.vocab_size,
                              bias_attr=False)

    def forward(self, input_ids):
        l = input_ids.shape[1]
        pos = Tensor(jnp.arange(l, dtype=jnp.int32)[None, :])
        h = self.wte(input_ids) + self.wpe(pos)
        for blk in self.blocks:
            h = blk(h)
        return self.lm_head(self.ln_f(h))


def shard_gpt(model: GPTForCausalLM, mesh, tp_axis="mp", fsdp_axis=None):
    """Placement rules for GPT: qkv/fc_in column-parallel, out_proj/fc_out
    row-parallel (same algebra as shard_llama)."""
    from ..distributed.api import shard_parameter

    for name, p in model.named_parameters():
        if p is None:
            continue
        if any(s in name for s in ("qkv_proj", "fc_in", "lm_head", "wte")):
            tp_dim, fsdp_dim = (1, 0) if p._data.ndim > 1 else (0, None)
        elif any(s in name for s in ("out_proj", "fc_out")):
            tp_dim, fsdp_dim = (0, 1) if p._data.ndim > 1 else (None, 0)
        else:
            tp_dim, fsdp_dim = None, None
        shard_parameter(p, mesh, tp_axis, fsdp_axis, tp_dim, fsdp_dim)
    return model
