"""Llama family: the flagship LM for the framework's headline benchmark.

ref: test/auto_parallel/hybrid_strategy/semi_auto_parallel_llama_model.py
(LlamaAttention/LlamaMLP/LlamaRMSNorm/LlamaForCausalLM and their
shard_tensor placement choices), python/paddle/nn/functional/flash_attention.py
(attention entry). TPU-native design: the decoder stack is ordinary Layer
code; parallelism is *data placement* — `shard_llama` attaches
NamedShardings (GSPMD) to the parameters and one `jax.jit` of the train
step compiles the whole hybrid dp x fsdp x tp program with XLA
collectives over ICI. RoPE/GQA/SwiGLU keep every matmul large and
bfloat16-friendly for the MXU; attention rides the Pallas flash kernel.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.autograd import apply_op
from ..nn import functional as F
from ..nn.container import LayerList
from ..nn.layer import Layer
from ..nn.layers_common import Embedding, Linear
from ..nn.layers_conv_norm import RMSNorm
from ..nn import initializer as I

__all__ = [
    "LlamaConfig", "LlamaModel", "LlamaForCausalLM",
    "LlamaPretrainingCriterion", "shard_llama",
]


@dataclass
class LlamaConfig:
    """Defaults are Llama-2 7B (ref: semi_auto_llama.py model config)."""
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32          # < heads => GQA (Llama-2 70B / 3)
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-6
    rope_theta: float = 10000.0
    use_flash_attention: bool = True
    sequence_parallel: bool = False        # shard activations on seq axis
    tie_word_embeddings: bool = False
    dtype: str = "float32"
    # recompute each decoder block in backward (ref: fleet recompute /
    # paddle.distributed.fleet.utils.recompute) = jax.checkpoint
    recompute: bool = False
    # context parallelism (above-parity vs reference, SURVEY §2.2): when a
    # mesh + axis are set, attention runs the ring kernel with K/V blocks
    # rotating over ICI and the sequence sharded across the axis
    cp_mesh: object = None
    cp_axis: str = "sp"

    @staticmethod
    def tiny(**kw):
        """Small config for tests / dry runs."""
        base = dict(vocab_size=128, hidden_size=64, intermediate_size=128,
                    num_hidden_layers=2, num_attention_heads=4,
                    num_key_value_heads=2, max_position_embeddings=128)
        base.update(kw)
        return LlamaConfig(**base)


def _rope_cos_sin(seq_len, head_dim, theta, dtype=jnp.float32,
                  position_offset=0):
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2,
                                           dtype=jnp.float32) / head_dim))
    pos = jnp.arange(position_offset, position_offset + seq_len,
                     dtype=jnp.float32)
    freqs = jnp.outer(pos, inv_freq)              # [L, D/2]
    return jnp.cos(freqs).astype(dtype), jnp.sin(freqs).astype(dtype)


def _apply_rope(x, cos, sin):
    """x: [B, L, H, D] -> rotated. Pairs (x1, x2) are the two halves, the
    Llama 'rotate_half' convention (ref: semi_auto_parallel_llama_model.py
    apply_rotary_pos_emb)."""
    d2 = x.shape[-1] // 2
    x1, x2 = x[..., :d2], x[..., d2:]
    cos = cos[None, :, None, :]
    sin = sin[None, :, None, :]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1).astype(x.dtype)


class LlamaAttention(Layer):
    """GQA attention with RoPE; the sdpa is the Pallas flash kernel when
    tiling allows (ref: LlamaAttention in semi_auto_parallel_llama_model.py)."""

    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.hidden_size = config.hidden_size
        self.num_heads = config.num_attention_heads
        self.num_kv_heads = config.num_key_value_heads
        self.head_dim = config.hidden_size // config.num_attention_heads
        kv_out = self.num_kv_heads * self.head_dim
        self.q_proj = Linear(self.hidden_size, self.hidden_size,
                             bias_attr=False)
        self.k_proj = Linear(self.hidden_size, kv_out, bias_attr=False)
        self.v_proj = Linear(self.hidden_size, kv_out, bias_attr=False)
        self.o_proj = Linear(self.hidden_size, self.hidden_size,
                             bias_attr=False)

    def forward(self, hidden_states, attention_mask=None, cache=None,
                position_offset=0):
        b, l, _ = hidden_states.shape
        q = self.q_proj(hidden_states).reshape([b, l, self.num_heads,
                                                self.head_dim])
        k = self.k_proj(hidden_states).reshape([b, l, self.num_kv_heads,
                                                self.head_dim])
        v = self.v_proj(hidden_states).reshape([b, l, self.num_kv_heads,
                                                self.head_dim])

        # the whole rope+attend runs through apply_op so eager autograd
        # records one fused node
        cache_in = []
        if cache is not None and cache[0] is not None:
            cache_in = [cache[0], cache[1]]

        def attn_impl(qa, ka, va, *cache_arrs):
            cos, sin = _rope_cos_sin(l, self.head_dim,
                                     self.config.rope_theta,
                                     position_offset=position_offset)
            qa = _apply_rope(qa, cos, sin)
            ka = _apply_rope(ka, cos, sin)
            if cache_arrs:
                ka = jnp.concatenate([cache_arrs[0], ka], axis=1)
                va = jnp.concatenate([cache_arrs[1], va], axis=1)
            rep = self.num_heads // self.num_kv_heads
            new_k, new_v = ka, va
            if rep > 1:
                ka = jnp.repeat(ka, rep, axis=2)
                va = jnp.repeat(va, rep, axis=2)
            from ..ops.pallas.flash_attention import (_sdpa_xla,
                                                      flash_attention)
            if (self.config.cp_mesh is not None and not cache_arrs
                    and attention_mask is None):
                from ..distributed.ring_attention import ring_attention
                out = ring_attention(qa, ka, va, self.config.cp_mesh,
                                     self.config.cp_axis, causal=True)
            elif (not cache_arrs and attention_mask is None
                    and self.config.use_flash_attention):
                out = flash_attention(qa, ka, va, True, None)
            else:
                # decode (Lq < Lk) and/or explicit-mask path
                out = _sdpa_xla(qa, ka, va, causal=True,
                                mask=attention_mask)
            return out.reshape(b, l, self.hidden_size), new_k, new_v

        if attention_mask is not None:
            attention_mask = attention_mask._data if isinstance(
                attention_mask, Tensor) else attention_mask
        out, new_k, new_v = apply_op(
            attn_impl, q, k, v, *cache_in, op_name="llama_attention")
        out = self.o_proj(out)
        if cache is not None:
            return out, (new_k, new_v)
        return out


class LlamaMLP(Layer):
    """SwiGLU FFN (ref: LlamaMLP in semi_auto_parallel_llama_model.py)."""

    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.gate_proj = Linear(config.hidden_size, config.intermediate_size,
                                bias_attr=False)
        self.up_proj = Linear(config.hidden_size, config.intermediate_size,
                              bias_attr=False)
        self.down_proj = Linear(config.intermediate_size, config.hidden_size,
                                bias_attr=False)

    def forward(self, x):
        return self.down_proj(F.silu(self.gate_proj(x)) * self.up_proj(x))


class LlamaDecoderLayer(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.self_attn = LlamaAttention(config)
        self.mlp = LlamaMLP(config)
        self.input_layernorm = RMSNorm(config.hidden_size,
                                       config.rms_norm_eps)
        self.post_attention_layernorm = RMSNorm(config.hidden_size,
                                                config.rms_norm_eps)

    def forward(self, hidden_states, attention_mask=None, cache=None,
                position_offset=0):
        residual = hidden_states
        h = self.input_layernorm(hidden_states)
        if cache is not None:
            h, new_cache = self.self_attn(h, attention_mask, cache,
                                          position_offset)
        else:
            h = self.self_attn(h, attention_mask, None, position_offset)
        h = residual + h
        residual = h
        h = self.post_attention_layernorm(h)
        h = self.mlp(h)
        h = residual + h
        if cache is not None:
            return h, new_cache
        return h


class LlamaModel(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.embed_tokens = Embedding(config.vocab_size, config.hidden_size,
                                      weight_attr=I.Normal(0.0, 0.02))
        self.layers = LayerList([LlamaDecoderLayer(config)
                                 for _ in range(config.num_hidden_layers)])
        self.norm = RMSNorm(config.hidden_size, config.rms_norm_eps)

    def forward(self, input_ids, attention_mask=None, caches=None,
                position_offset=0):
        h = self.embed_tokens(input_ids)
        if self.config.sequence_parallel:
            h = _seq_constraint(h)
        new_caches = [] if caches is not None else None
        for i, layer in enumerate(self.layers):
            cache_i = caches[i] if caches is not None else None
            if self.config.recompute and caches is None:
                h = _remat_layer(layer, h, attention_mask, position_offset)
            elif caches is not None:
                h, c = layer(h, attention_mask, cache_i, position_offset)
                new_caches.append(c)
            else:
                h = layer(h, attention_mask, None, position_offset)
            if self.config.sequence_parallel:
                h = _seq_constraint(h)
        h = self.norm(h)
        if caches is not None:
            return h, new_caches
        return h


def _remat_layer(layer, h, attention_mask, position_offset):
    """jax.checkpoint over one decoder block — the TPU-native recompute
    (ref: paddle.distributed.fleet.utils.recompute). The layer's actual
    Parameter objects are passed to apply_op so eager backward routes
    gradients to them."""
    params = [p for _, p in layer.named_parameters()]

    def fn(h_arr, *param_arrs):
        old = [p._data for p in params]
        try:
            for p, a in zip(params, param_arrs):
                p._data = a
            out = layer(Tensor(h_arr), attention_mask, None, position_offset)
            return out._data
        finally:
            for p, o in zip(params, old):
                p._data = o

    return apply_op(jax.checkpoint(fn), h, *params,
                    op_name="remat_decoder_layer")


def _seq_constraint(h):
    """Activation sharding constraint along the sequence axis ('sp' mesh
    axis) — Megatron sequence parallel as pure placement
    (ref: fleet/utils/sequence_parallel_utils.py)."""
    def f(x):
        try:
            from jax.sharding import PartitionSpec as P
            return jax.lax.with_sharding_constraint(
                x, P(None, "sp", None))
        except Exception:
            return x
    return apply_op(f, h, op_name="seq_parallel_constraint")


class LlamaForCausalLM(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.llama = LlamaModel(config)
        if not config.tie_word_embeddings:
            self.lm_head = Linear(config.hidden_size, config.vocab_size,
                                  bias_attr=False)

    def _logits(self, h):
        if self.config.tie_word_embeddings:
            # project through the transposed embedding table
            return apply_op(lambda hh, w: hh @ w.T, h,
                            self.llama.embed_tokens.weight,
                            op_name="tied_lm_head")
        return self.lm_head(h)

    def forward(self, input_ids, attention_mask=None, caches=None,
                position_offset=0):
        out = self.llama(input_ids, attention_mask, caches, position_offset)
        if caches is not None:
            h, new_caches = out
            return self._logits(h), new_caches
        return self._logits(out)

    def generate(self, input_ids, max_new_tokens=32):
        """Greedy decode with per-layer KV caches (inference parity check,
        not the serving path)."""
        ids = input_ids
        caches = [(None, None)] * self.config.num_hidden_layers
        logits, caches = self.forward(ids, caches=caches)
        for _ in range(max_new_tokens):
            next_id = jnp.argmax(logits._data[:, -1, :], axis=-1)[:, None]
            offset = caches[0][0]._data.shape[1] if isinstance(
                caches[0][0], Tensor) else caches[0][0].shape[1]
            ids = Tensor(jnp.concatenate([ids._data, next_id], axis=1))
            logits, caches = self.forward(
                Tensor(next_id), caches=caches, position_offset=offset)
        return ids


@jax.custom_vjp
def _grad_safe_barrier(lg, lb):
    return jax.lax.optimization_barrier((lg, lb))


def _grad_safe_barrier_fwd(lg, lb):
    return jax.lax.optimization_barrier((lg, lb)), None


def _grad_safe_barrier_bwd(_, ct):
    return ct


# optimization_barrier has no differentiation rule in jax 0.4.37; the
# barrier only orders the forward dependency chain, so the cotangents
# pass through untouched
_grad_safe_barrier.defvjp(_grad_safe_barrier_fwd, _grad_safe_barrier_bwd)


class LlamaPretrainingCriterion(Layer):
    """Causal-LM loss: shifted next-token cross entropy
    (ref: LlamaPretrainingCriterion in semi_auto_parallel_llama_model.py)."""

    def __init__(self, config: Optional[LlamaConfig] = None):
        super().__init__()

    def forward(self, logits, labels):
        def f(lg, lb):
            import jax
            import jax.numpy as jnp

            from ..ops.fused_ce import fused_softmax_ce_mean
            # barrier ties label prep (and any reshard GSPMD inserts for
            # it) into the logits' dependency chain: label-side
            # collectives would otherwise be independent of the model's
            # collective chain and can race it on the XLA:CPU in-process
            # rendezvous (deadlock in the CP dryrun); on TPU the labels
            # are tiny and the barrier costs nothing
            lg, lb = _grad_safe_barrier(lg, lb)
            # shift the LABELS (tiny int array), not the logits: slicing
            # lg[:, :-1] copies the whole [B, L, V] tensor (262 MB at
            # the 1B-scale geometry) and leaves an odd L-1 chunk size;
            # the final position is masked out via ignore_index instead
            shifted = jnp.concatenate(
                [lb[:, 1:], jnp.full((lb.shape[0], 1), -100, lb.dtype)],
                axis=1)
            # the dynamic valid count (inside fused CE) keeps padded
            # batches correct: labels may already carry -100 positions,
            # which must leave the mean's denominator too. Its reduction
            # is serialized behind the barrier above, so it cannot race
            # the model's collective chain.
            return fused_softmax_ce_mean(lg, shifted, ignore_index=-100)
        return apply_op(f, logits, labels, op_name="causal_lm_loss")


# ---------------------------------------------------------------------------
# Parallel placement rules (ref: the shard_tensor calls sprinkled through
# semi_auto_parallel_llama_model.py, expressed here as one rule table).
# ---------------------------------------------------------------------------

def shard_llama(model: LlamaForCausalLM, mesh, tp_axis: Optional[str] = "mp",
                fsdp_axis: Optional[str] = None):
    """Attach NamedShardings to every parameter: tensor-parallel column/row
    splits on `tp_axis`, ZeRO-3-style parameter sharding on `fsdp_axis`.

    Mirrors the reference placements: column-parallel weights (q/k/v, gate/up,
    lm_head, embedding hidden dim) shard their OUT dim on tp; row-parallel
    (o_proj, down_proj) shard their IN dim. With weight layout [in, out]:
    column => Shard(1), row => Shard(0). FSDP shards the remaining dim.
    """
    from ..distributed.api import shard_parameter

    for name, p in model.named_parameters():
        if p is None:
            continue
        if any(s in name for s in ("embed_tokens", "q_proj", "k_proj",
                                   "v_proj", "gate_proj", "up_proj",
                                   "lm_head")):
            tp_dim, fsdp_dim = 1, 0               # column parallel
        elif any(s in name for s in ("o_proj", "down_proj")):
            tp_dim, fsdp_dim = 0, 1               # row parallel
        else:                                      # norms
            tp_dim, fsdp_dim = None, None
        shard_parameter(p, mesh, tp_axis, fsdp_axis, tp_dim, fsdp_dim)
    return model
