"""Mixture-of-Experts with expert parallelism.

ref: python/paddle/incubate/distributed/models/moe/moe_layer.py:263
(MoELayer), moe/gate/{naive,switch,gshard}_gate.py, and the alltoall
dispatch ops global_scatter/global_gather
(fluid/operators/collective/global_scatter_op.cu.cc:349).

TPU-native design: the GShard dense dispatch algebra — one-hot combine
weights einsum'd against tokens — instead of the reference's
ragged alltoall. Expert weights live as one stacked [E, ...] array whose
leading dim is sharded on the 'ep' mesh axis; when token batches are
sharded too, XLA GSPMD lowers the dispatch einsum into the same
all-to-all over ICI the reference issues through NCCL. Every expert FFN
is a single batched matmul on the MXU (no per-expert Python loop).
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from ..core.autograd import apply_op
from ..core.tensor import Parameter, Tensor
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer import Layer

__all__ = ["NaiveGate", "SwitchGate", "GShardGate", "MoELayer"]


class _BaseGate(Layer):
    def __init__(self, d_model: int, num_experts: int):
        super().__init__()
        self.num_experts = num_experts
        self.weight = self.create_parameter(
            [d_model, num_experts], default_initializer=I.XavierUniform())


class NaiveGate(_BaseGate):
    """Top-k softmax gate (ref: moe/gate/naive_gate.py)."""

    def __init__(self, d_model, num_experts, top_k=2):
        super().__init__(d_model, num_experts)
        self.top_k = top_k


class SwitchGate(_BaseGate):
    """Top-1 gate with load-balancing aux loss (ref: moe/gate/switch_gate.py)."""

    def __init__(self, d_model, num_experts):
        super().__init__(d_model, num_experts)
        self.top_k = 1


class GShardGate(_BaseGate):
    """Top-2 gate with capacity + aux loss (ref: moe/gate/gshard_gate.py)."""

    def __init__(self, d_model, num_experts):
        super().__init__(d_model, num_experts)
        self.top_k = 2


def _gshard_dispatch(gate_logits, top_k, capacity):
    """Pure dispatch algebra: logits [T, E] -> (combine [T, E, C],
    dispatch-bool [T, E, C], aux_loss). The GShard formulation: per-expert
    positions via a cumsum over the token axis, tokens past capacity
    dropped."""
    T, E = gate_logits.shape
    probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)

    # aux load-balance loss (Switch/GShard form): E * sum(fraction_tokens *
    # fraction_probs) over experts, using the top-1 assignment
    top1 = jnp.argmax(probs, axis=-1)
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(jax.nn.one_hot(top1, E, dtype=jnp.float32), axis=0)
    aux_loss = E * jnp.sum(me * ce)

    combine = jnp.zeros((T, E, capacity), jnp.float32)
    dispatch = jnp.zeros((T, E, capacity), bool)
    used = jnp.zeros((T, E), bool)
    counts = jnp.zeros((E,), jnp.float32)  # slots taken per expert so far
    # iterate k choices (k is tiny and static -> unrolled by trace)
    for _ in range(min(top_k, E)):
        choice = jnp.argmax(jnp.where(used, -jnp.inf, probs), axis=-1)
        oh = jax.nn.one_hot(choice, E, dtype=jnp.float32)        # [T, E]
        # slot index continues where the previous iterations stopped, so
        # 2nd-choice tokens never collide with 1st-choice tokens
        pos = (jnp.cumsum(oh, axis=0) - 1.0 + counts[None, :]) * oh
        in_cap = pos < capacity
        pos_oh = jax.nn.one_hot(pos.astype(jnp.int32), capacity,
                                dtype=jnp.float32)               # [T, E, C]
        w = (probs * oh * in_cap)[..., None] * pos_oh
        combine = combine + w
        dispatch = dispatch | (w > 0)
        used = used | (oh > 0)
        counts = counts + oh.sum(axis=0)
    return combine, dispatch, aux_loss


# dispatch_mode="auto" crossover (tokens per forward): below this the
# dense one-hot algebra's quadratic-in-T einsums still win on the MXU;
# above it the linear index/grouped-matmul path wins. Measured on v5e
# at top_k=2, capacity_factor=1.25, E=16, H=1024, F=4096 (dense/index
# 0.80x @ 8K tokens, 0.89x @ 16K, 1.72x @ 32K). Both paths' dispatch
# costs scale together with top_k*capacity_factor (everything is
# proportional to the E*C slot count), so the crossover is kept as a
# flat token threshold; configs far from the measured one should set
# dispatch_mode explicitly.
_AUTO_DENSE_TOKENS = 24576


class MoELayer(Layer):
    """ref: moe_layer.py:263 MoELayer(d_model, experts, gate, ...). Experts
    are a stacked SwiGLU/relu FFN; `ep_mesh_axis` shards the expert dim for
    expert parallelism (the reference's global_scatter/global_gather
    alltoall becomes a GSPMD-lowered all-to-all).
    """

    def __init__(self, d_model: int, d_hidden: int, num_experts: int,
                 gate: str = "gshard", top_k: int = 2,
                 capacity_factor: float = 1.25, activation: str = "gelu",
                 dispatch_mode: str = "index"):
        super().__init__()
        self.d_model = d_model
        self.num_experts = num_experts
        self.capacity_factor = capacity_factor
        if dispatch_mode not in ("index", "dense", "auto"):
            raise ValueError(
                f"dispatch_mode must be 'index', 'dense' or 'auto', got "
                f"{dispatch_mode!r}")
        # "index": gather/scatter dispatch + grouped-matmul experts,
        # O(E*C*H) (see incubate.moe_dispatch — the scalable path).
        # "dense": one-hot einsum algebra, O(T*E*C*H) — also the numeric
        # reference the tests align against.
        # "auto": dense below _AUTO_DENSE_TOKENS tokens, index above.
        # Dense dispatch/combine einsums cost ~T * (E*C) * H flops with
        # E*C ~ top_k*capacity_factor*T — quadratic in T but pure MXU
        # work, so at small T they beat the index path's gathers
        # (measured bf16 on v5e, E=16 H=1024 F=4096: dense/index =
        # 0.80x @ 8K tokens, 0.89x @ 16K, 1.72x @ 32K).
        self.dispatch_mode = dispatch_mode
        if gate == "naive":
            self.gate = NaiveGate(d_model, num_experts, top_k)
        elif gate == "switch":
            self.gate = SwitchGate(d_model, num_experts)
        elif gate == "gshard":
            self.gate = GShardGate(d_model, num_experts)
        else:
            raise ValueError(f"unknown gate {gate!r}")
        self.top_k = self.gate.top_k
        self.activation = activation
        scale = 1.0 / math.sqrt(d_model)
        self.w_in = Parameter(
            I.Uniform(-scale, scale)((num_experts, d_model, d_hidden),
                                     jnp.float32))
        self.w_out = Parameter(
            I.Uniform(-scale, scale)((num_experts, d_hidden, d_model),
                                     jnp.float32))
        self.aux_loss: Optional[Tensor] = None

    def forward(self, x):
        """x: [B, L, H] -> [B, L, H]; stores load-balance loss in
        self.aux_loss (add it to the training loss, matching the
        reference's gate loss contract)."""
        b, l, h = x.shape
        capacity = max(1, int(self.capacity_factor * b * l *
                              self.top_k / self.num_experts))
        act = {"gelu": jax.nn.gelu, "relu": jax.nn.relu,
               "silu": jax.nn.silu}[self.activation]

        mode = self.dispatch_mode
        if mode == "auto":
            mode = "dense" if b * l < _AUTO_DENSE_TOKENS else "index"
        if mode == "index":
            from .moe_dispatch import moe_forward_indices

            def impl(x_arr, gate_w, w_in, w_out):
                tokens = x_arr.reshape(b * l, h)
                out, aux = moe_forward_indices(
                    tokens, gate_w, w_in, w_out, self.top_k, capacity, act)
                return out.reshape(b, l, h), aux
        else:
            def impl(x_arr, gate_w, w_in, w_out):
                tokens = x_arr.reshape(b * l, h)
                logits = tokens.astype(jnp.float32) @ gate_w.astype(
                    jnp.float32)
                combine, dispatch, aux = _gshard_dispatch(
                    logits, self.top_k, capacity)
                # dispatch: [T,E,C] x [T,H] -> [E,C,H] (the alltoall moment)
                xs = jnp.einsum("tec,th->ech", dispatch.astype(x_arr.dtype),
                                tokens)
                hdn = act(jnp.einsum("ech,ehf->ecf", xs, w_in))
                ys = jnp.einsum("ecf,efh->ech", hdn, w_out)
                out = jnp.einsum("tec,ech->th",
                                 combine.astype(x_arr.dtype), ys)
                return out.reshape(b, l, h), aux

        out, aux = apply_op(impl, x, self.gate.weight, self.w_in,
                            self.w_out, op_name="moe_layer")
        self.aux_loss = aux
        return out

    def shard_experts(self, mesh, ep_axis: str = "ep"):
        """Shard the stacked expert weights' leading (expert) dim on the
        'ep' mesh axis — expert parallelism as placement."""
        from ..distributed.api import shard_parameter
        shard_parameter(self.w_in, mesh, tp_axis=ep_axis, tp_dim=0)
        shard_parameter(self.w_out, mesh, tp_axis=ep_axis, tp_dim=0)
        return self
