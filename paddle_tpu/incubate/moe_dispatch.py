"""Index-based MoE dispatch/combine: the scalable replacement for the
dense one-hot GShard algebra.

ref: the reference dispatches with ragged alltoall ops
(fluid/operators/collective/global_scatter_op.cu.cc:349 global_scatter /
global_gather) + a CUTLASS grouped GEMM
(phi/kernels/fusion/cutlass/fused_moe_kernel.cu). TPU-native: capacity-
bounded dispatch becomes a GATHER (tokens -> [E, C, H] expert buffers)
and combine becomes a per-token top-k gather — both O(E*C*H) instead of
the one-hot einsum's O(T*E*C*H), and both plain XLA gathers that GSPMD
re-shards over the 'ep' mesh axis with all-to-all collectives (asserted
by tests/test_moe HLO inspection). The expert FFN runs on the
fixed-capacity batched expert GEMM (XLA schedules it at near matmul
peak; the Pallas grouped matmul serves the ragged-group case).
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

__all__ = ["capacity_dispatch_indices", "moe_forward_indices"]


def capacity_dispatch_indices(gate_logits, top_k: int, capacity: int):
    """GShard capacity dispatch as index tables.

    gate_logits: [T, E] float. Returns:
      token_idx [E, C] int32  — token filling each expert slot (0 if empty)
      slot_used [E, C] bool   — slot occupancy
      expert_k  [T, K] int32  — k-th expert choice per token
      slot_k    [T, K] int32  — slot the token landed in (clamped if dropped)
      weight_k  [T, K] float32 — gate prob, 0 for dropped tokens
      aux_loss  scalar        — Switch/GShard load-balance loss
    Position math matches incubate.moe._gshard_dispatch (the dense
    oracle): per-round cumsum over tokens, later rounds continue where
    earlier rounds stopped, tokens past capacity dropped.
    """
    t, e = gate_logits.shape
    probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)

    top1 = jnp.argmax(probs, axis=-1)
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(jax.nn.one_hot(top1, e, dtype=jnp.float32), axis=0)
    aux_loss = e * jnp.sum(me * ce)

    used = jnp.zeros((t, e), bool)
    counts = jnp.zeros((e,), jnp.float32)
    expert_k, slot_k, weight_k = [], [], []
    for _ in range(min(top_k, e)):
        choice = jnp.argmax(jnp.where(used, -jnp.inf, probs), axis=-1)
        oh = jax.nn.one_hot(choice, e, dtype=jnp.float32)        # [T, E]
        pos_table = jnp.cumsum(oh, axis=0) - 1.0 + counts[None, :]
        pos = jnp.take_along_axis(pos_table, choice[:, None],
                                  axis=1)[:, 0]                  # [T]
        in_cap = pos < capacity
        w = jnp.take_along_axis(probs, choice[:, None], axis=1)[:, 0]
        expert_k.append(choice.astype(jnp.int32))
        slot_k.append(jnp.clip(pos, 0, capacity - 1).astype(jnp.int32))
        weight_k.append(jnp.where(in_cap, w, 0.0))
        used = used | (oh > 0)
        counts = counts + oh.sum(axis=0)

    expert_k = jnp.stack(expert_k, axis=1)
    slot_k = jnp.stack(slot_k, axis=1)
    weight_k = jnp.stack(weight_k, axis=1)

    # slot tables via scatter of the valid (expert, slot) -> token edges
    flat = expert_k * capacity + slot_k                          # [T, K]
    valid = weight_k > 0
    safe_flat = jnp.where(valid, flat, e * capacity)  # park invalid
    token_idx = jnp.zeros((e * capacity + 1,), jnp.int32).at[
        safe_flat.reshape(-1)].set(
        jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32)[:, None],
                         flat.shape).reshape(-1))
    slot_used = jnp.zeros((e * capacity + 1,), bool).at[
        safe_flat.reshape(-1)].set(valid.reshape(-1))
    return (token_idx[:-1].reshape(e, capacity),
            slot_used[:-1].reshape(e, capacity),
            expert_k, slot_k, weight_k, aux_loss)


def moe_forward_indices(tokens, gate_w, w_in, w_out, top_k: int,
                        capacity: int, act) -> Tuple[jax.Array, jax.Array]:
    """Full MoE forward on the index dispatch: tokens [T, H] -> [T, H].

    Expert FFN runs as a batched einsum over the fixed-capacity
    [E, C, H] layout — one dense MXU GEMM per expert, which XLA
    schedules at near matmul peak (see the measurement note below).
    """
    t, h = tokens.shape
    e, _, f = w_in.shape
    (token_idx, slot_used, expert_k, slot_k, weight_k,
     aux) = capacity_dispatch_indices(
        tokens.astype(jnp.float32) @ gate_w.astype(jnp.float32),
        top_k, capacity)
    c = token_idx.shape[1]

    xs = tokens[token_idx.reshape(-1)].reshape(e, c, h)   # dispatch gather
    xs = jnp.where(slot_used[..., None], xs, 0).astype(tokens.dtype)

    # Fixed capacity means every expert's slot block is the SAME size —
    # the expert FFN is then a plain batched GEMM, which XLA schedules
    # at near matmul peak (measured on v5e at E16 C5120 H1024 F4096
    # fwd+bwd: einsum 21.4 ms = 0.98 MFU vs 35.7 ms = 0.59 MFU for the
    # Pallas grouped-matmul path; the reference's CUTLASS fused MoE GEMM
    # plays this exact role, fused_moe_kernel.cu). The Pallas kernel
    # (ops/pallas/grouped_matmul.py) remains the path for RAGGED group
    # sizes, where no fixed batch shape exists.
    hdn = act(jnp.einsum("ech,ehf->ecf", xs, w_in))
    ys = jnp.einsum("ecf,efh->ech", hdn, w_out)

    # combine: per-token weighted gather of its k slots
    flat_idx = (expert_k * c + slot_k).reshape(-1)        # [T*K]
    picked = ys.reshape(e * c, h)[flat_idx].reshape(t, -1, h)
    out = jnp.sum(picked * weight_k[..., None].astype(picked.dtype),
                  axis=1)
    return out.astype(tokens.dtype), aux
