"""incubate long-tail surface: LookAhead / ModelAverage optimizers,
fused masked softmax, identity_loss, and the graph/segment aliases.

ref: python/paddle/incubate/__init__.py __all__; impls under
incubate/optimizer/lookahead.py, optimizer/modelaverage.py,
operators/softmax_mask_fuse*.py, nn/loss.py identity_loss, and the
graph_* names that alias paddle.geometric's ops.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.autograd import apply_op
from ..core.tensor import Tensor

__all__ = [
    "LookAhead", "ModelAverage", "softmax_mask_fuse",
    "softmax_mask_fuse_upper_triangle", "identity_loss",
    "graph_send_recv", "graph_khop_sampler", "graph_sample_neighbors",
    "graph_reindex", "segment_sum", "segment_mean", "segment_max",
    "segment_min",
]

# segment ops are the geometric primitives under their legacy incubate
# names (the reference re-exports the same functions); the graph_* ops
# keep the reference incubate SIGNATURES, which differ from the
# geometric ones (positional order / parameter names), so they are thin
# wrappers rather than aliases.
from ..geometric import (  # noqa: E402,F401
    segment_max, segment_mean, segment_min, segment_sum,
)


def graph_send_recv(x, src_index, dst_index, pool_type="sum",
                    out_size=None, name=None):
    """Legacy name of geometric.send_u_recv with the reference's
    ``pool_type`` parameter (ref: incubate/operators/graph_send_recv)."""
    from ..geometric import send_u_recv
    return send_u_recv(x, src_index, dst_index, reduce_op=pool_type,
                       out_size=out_size)


def graph_reindex(x, neighbors, count, value_buffer=None,
                  index_buffer=None, flag_buffer_hashtable=False,
                  name=None):
    """Legacy name of geometric.reindex_graph (ref:
    incubate/operators/graph_reindex; the buffer args are a GPU
    hashtable optimization with no host-side analog)."""
    from ..geometric import reindex_graph
    return reindex_graph(x, neighbors, count)


def graph_sample_neighbors(row, colptr, input_nodes, eids=None,
                           perm_buffer=None, sample_size=-1,
                           return_eids=False, flag_perm_buffer=False,
                           name=None):
    """Legacy name of geometric.sample_neighbors with the reference's
    positional order — eids/perm_buffer BEFORE sample_size (ref:
    incubate/operators/graph_sample_neighbors)."""
    from ..geometric import sample_neighbors
    return sample_neighbors(row, colptr, input_nodes,
                            sample_size=sample_size, eids=eids,
                            return_eids=return_eids)


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       sorted_eids=None, return_eids=False, name=None):
    """Multi-hop neighbor sampling (ref: incubate/graph_khop_sampler):
    chained sample_neighbors over ``sample_sizes`` hops with one id
    space — dst ids come from each edge's actual frontier node (a
    revisited node keeps its id), not from positional numbering.
    Host-side like every sampling op here."""
    from ..geometric import sample_neighbors

    base = np.asarray(input_nodes.numpy()
                      if isinstance(input_nodes, Tensor) else input_nodes
                      ).reshape(-1)
    order = {int(v): i for i, v in enumerate(base)}
    nodes = list(base)
    srcs, dsts, cnts = [], [], []
    frontier = base
    for size in sample_sizes:
        neigh, cnt = sample_neighbors(
            row, colptr, Tensor(jnp.asarray(frontier)),
            sample_size=size)
        nv = np.asarray(neigh.numpy()).reshape(-1)
        cv = np.asarray(cnt.numpy()).reshape(-1)
        dsts.append(np.repeat(
            np.array([order[int(v)] for v in frontier], np.int64), cv))
        for v in nv:
            if int(v) not in order:
                order[int(v)] = len(nodes)
                nodes.append(v)
        srcs.append(np.array([order[int(v)] for v in nv], np.int64))
        cnts.append(cv)
        frontier = nv
    src = np.concatenate(srcs) if srcs else np.empty(0, np.int64)
    dst = np.concatenate(dsts) if dsts else np.empty(0, np.int64)
    cnt_all = np.concatenate(cnts) if cnts else np.empty(0, np.int64)
    out_nodes = np.asarray(nodes, dtype=base.dtype)
    return (Tensor(jnp.asarray(src)), Tensor(jnp.asarray(dst)),
            Tensor(jnp.asarray(out_nodes)),
            Tensor(jnp.asarray(cnt_all)))


def softmax_mask_fuse(x, mask, name=None):
    """softmax(x + mask) in one fused computation (ref:
    incubate/operators/softmax_mask_fuse.py — a CUDA fusion there; one
    XLA fusion here)."""
    import jax

    def f(a, m):
        return jax.nn.softmax(a.astype(jnp.float32) + m.astype(
            jnp.float32), axis=-1).astype(a.dtype)
    return apply_op(f, x, mask, op_name="softmax_mask_fuse")


def softmax_mask_fuse_upper_triangle(x, name=None):
    """Causal (upper-triangle masked) softmax over the last two dims
    (ref: incubate/operators/softmax_mask_fuse_upper_triangle.py)."""
    import jax

    def f(a):
        q, k = a.shape[-2], a.shape[-1]
        keep = jnp.tril(jnp.ones((q, k), bool), k=k - q)
        logits = jnp.where(keep, a.astype(jnp.float32), -1e30)
        return jax.nn.softmax(logits, axis=-1).astype(a.dtype)
    return apply_op(f, x, op_name="softmax_mask_fuse_upper_triangle")


def identity_loss(x, reduction="none"):
    """Marks (and optionally reduces) the final loss (ref:
    incubate/nn/loss.py identity_loss; int codes 0/1/2 = sum/mean/none
    accepted like the reference)."""
    red = {0: "sum", 1: "mean", 2: "none"}.get(reduction, reduction)
    if red == "none":
        return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
    if red == "mean":
        return apply_op(lambda a: jnp.mean(a), x, op_name="identity_loss")
    if red == "sum":
        return apply_op(lambda a: jnp.sum(a), x, op_name="identity_loss")
    raise ValueError(f"unknown reduction {reduction!r}")


class LookAhead:
    """Lookahead optimizer wrapper (ref: incubate/optimizer/lookahead.py,
    Zhang et al. 2019): the inner optimizer updates fast weights every
    step; every k steps the slow weights move alpha of the way to the
    fast weights and the fast weights reset onto them."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        if not 0.0 <= alpha <= 1.0:
            raise ValueError("alpha must be in [0, 1]")
        if not (isinstance(k, int) and k > 0):
            raise ValueError("k must be a positive integer")
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = k
        self._step_count = 0
        self._slow = {}

    def __getattr__(self, item):
        return getattr(self.inner_optimizer, item)

    def _params(self):
        return self.inner_optimizer._parameter_list

    def step(self):
        # slow weights are HELD snapshots: they must never alias a
        # param buffer, because the fused/captured optimizer step
        # DONATES param buffers to XLA (deleted after the update) —
        # an aliased slow weight would be read-after-free on the next
        # sync point
        if not self._slow:
            for p in self._params():
                self._slow[id(p)] = jnp.copy(p._data)
        self.inner_optimizer.step()
        self._step_count += 1
        if self._step_count % self.k == 0:
            for p in self._params():
                slow = self._slow[id(p)]
                new_slow = (slow.astype(jnp.float32) + self.alpha *
                            (p._data.astype(jnp.float32) -
                             slow.astype(jnp.float32))).astype(p._data.dtype)
                self._slow[id(p)] = new_slow
                p._data = jnp.copy(new_slow)

    def minimize(self, loss, *args, **kwargs):
        loss.backward()
        self.step()
        self.inner_optimizer.clear_grad()

    def clear_grad(self):
        self.inner_optimizer.clear_grad()

    def state_dict(self):
        return {"inner": self.inner_optimizer.state_dict(),
                "step_count": self._step_count}


class ModelAverage:
    """Running parameter average with a growing window (ref:
    incubate/optimizer/modelaverage.py): accumulates parameter sums;
    apply() swaps averaged weights in (optionally restorable),
    restore() swaps the trained weights back. The window restarts when
    num_accumulates exceeds min(max_average_window,
    num_updates * average_window_rate) — the reference's contract."""

    def __init__(self, average_window_rate, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        self.average_window_rate = float(average_window_rate)
        self.min_average_window = int(min_average_window)
        self.max_average_window = int(max_average_window)
        self._params = list(parameters or [])
        self._sum = {id(p): jnp.zeros_like(p._data, jnp.float32)
                     for p in self._params}
        self._num_accumulates = 0
        self._num_updates = 0
        self._backup = None

    def step(self):
        self._num_updates += 1
        self._num_accumulates += 1
        window = min(self.max_average_window,
                     self._num_updates * self.average_window_rate)
        if (self._num_accumulates >= self.min_average_window
                and self._num_accumulates >= window):
            # restart the window: keep only the latest value. jnp.array
            # (not astype) forces a COPY: astype on an f32 param is the
            # identity, and an aliased sum would be deleted under us by
            # the next donating (fused/captured) optimizer step
            for p in self._params:
                self._sum[id(p)] = jnp.array(p._data, jnp.float32)
            self._num_accumulates = 1
        else:
            for p in self._params:
                self._sum[id(p)] = (self._sum[id(p)]
                                    + p._data.astype(jnp.float32))

    def apply(self, executor=None, need_restore=True):
        self._backup = {id(p): p._data for p in self._params}
        n = max(self._num_accumulates, 1)
        for p in self._params:
            p._data = (self._sum[id(p)] / n).astype(p._data.dtype)
        if not need_restore:
            self._backup = None
        return _RestoreCtx(self)

    def restore(self, executor=None):
        if self._backup is None:
            return
        for p in self._params:
            p._data = self._backup[id(p)]
        self._backup = None


class _RestoreCtx:
    """apply() is usable as a context manager (with ma.apply(): ...)."""

    def __init__(self, ma):
        self._ma = ma

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self._ma.restore()
        return False
