"""paddle.incubate.inference namespace (ref:
python/paddle/incubate/__init__.py exports ``inference``): the
inference API re-exported — the predictor/serving stack lives in
paddle_tpu.inference."""
from ..inference import (  # noqa: F401
    Config, Predictor, load_inference_model, save_inference_model)
