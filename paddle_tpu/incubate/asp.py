"""ASP — automatic structured (2:4) sparsity.

ref: python/paddle/incubate/asp/asp.py (prune_model :319, decorate :233,
set_excluded_layers :55, reset_excluded_layers :144) and utils.py mask
algorithms. TPU note: the MXU has no 2:4 sparse execution unit, so the
value here is sparsity-aware *training* (masks maintained through the
optimizer step exactly like the reference's
OptimizerWithSparsityGuarantee); the masked weights compress for serving.
"""
from __future__ import annotations

import weakref
from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor

__all__ = ["calculate_density", "check_sparsity", "create_mask",
           "decorate", "prune_model", "set_excluded_layers",
           "reset_excluded_layers"]

_excluded_layers: List[str] = []
# id(param) -> (weakref(param), mask). The weakref guards against CPython
# id reuse: a dead entry whose id was recycled by an unrelated parameter
# must not silently mask it. Dead entries are swept on each prune_model.
_masks: Dict[int, Tuple["weakref.ref", jnp.ndarray]] = {}


def _mask_for(p) -> Optional[jnp.ndarray]:
    entry = _masks.get(id(p))
    if entry is None:
        return None
    ref, mask = entry
    if ref() is not p:  # stale id-reuse entry
        del _masks[id(p)]
        return None
    return mask


def set_excluded_layers(param_names, main_program=None):
    """ref: asp.py:55 — layers whose params are never pruned."""
    _excluded_layers.extend(param_names)


def reset_excluded_layers(main_program=None):
    """ref: asp.py:144."""
    _excluded_layers.clear()


def calculate_density(x) -> float:
    """ref: utils.py calculate_density: nonzero fraction."""
    arr = np.asarray(x.numpy() if isinstance(x, Tensor) else x)
    return float(np.count_nonzero(arr)) / max(arr.size, 1)


_MASK_ALGOS = ("mask_1d",)


def create_mask(x, func_name: str = "mask_1d", n: int = 2,
                m: int = 4) -> np.ndarray:
    """n:m structured mask along the last dim: keep the n
    largest-magnitude entries of every m consecutive weights
    (ref: utils.py create_mask / get_mask_1d). The reference's 2-D
    algorithms (mask_2d_greedy/best) are not implemented — fail loudly
    rather than silently downgrade."""
    if func_name not in _MASK_ALGOS:
        raise NotImplementedError(
            f"mask algorithm {func_name!r} not supported (available: "
            f"{_MASK_ALGOS}); the reference's 2-D algorithms are a "
            f"documented gap")
    arr = np.asarray(x.numpy() if isinstance(x, Tensor) else x)
    flat = arr.reshape(-1, arr.shape[-1])
    if arr.shape[-1] % m != 0:
        raise ValueError(
            f"last dim {arr.shape[-1]} must be divisible by m={m}")
    groups = flat.reshape(flat.shape[0], -1, m)
    order = np.argsort(-np.abs(groups), axis=-1)
    mask = np.zeros_like(groups)
    np.put_along_axis(mask, order[..., :n], 1.0, axis=-1)
    return mask.reshape(arr.shape).astype(arr.dtype)


def check_sparsity(x, n: int = 2, m: int = 4) -> bool:
    """True iff every m-group along the last dim has <= n nonzeros
    (ref: utils.py check_sparsity)."""
    arr = np.asarray(x.numpy() if isinstance(x, Tensor) else x)
    if arr.shape[-1] % m != 0:
        return False
    groups = arr.reshape(-1, arr.shape[-1] // m, m)
    return bool((np.count_nonzero(groups, axis=-1) <= n).all())


def _excluded(name: str) -> bool:
    """Exact name or dotted-prefix match (substring matching would make
    '0.weight' also exclude '10.weight')."""
    for ex in _excluded_layers:
        if name == ex or name.startswith(ex + "."):
            return True
    return False


def _prunable(name: str, p: Tensor) -> bool:
    if _excluded(name):
        return False
    d = p._data
    # the reference prunes FC/conv weights, not biases/norms
    return d.ndim >= 2 and d.shape[-1] % 4 == 0


def prune_model(model, n: int = 2, m: int = 4, mask_algo: str = "mask_1d",
                with_mask: bool = True):
    """Apply n:m masks to the model's prunable weights; with_mask=True
    (default) also remembers them so a decorated optimizer keeps pruned
    entries at zero (ref: asp.py:319)."""
    for k in [k for k, (ref, _) in _masks.items() if ref() is None]:
        del _masks[k]  # sweep dead params so ids can't be misapplied
    pruned = {}
    for name, p in model.named_parameters():
        if not _prunable(name, p):
            continue
        mask = jnp.asarray(create_mask(p, mask_algo, n, m))
        p._data = (p._data * mask).astype(p._data.dtype)
        if with_mask:
            _masks[id(p)] = (weakref.ref(p), mask)
        pruned[name] = mask
    return pruned


class OptimizerWithSparsityGuarantee:
    """Re-applies the ASP masks after every step so pruned weights stay
    exactly zero through training (ref: asp.py:506)."""

    def __init__(self, optimizer):
        self._optimizer = optimizer

    def __getattr__(self, item):
        return getattr(self._optimizer, item)

    def step(self, *args, **kwargs):
        out = self._optimizer.step(*args, **kwargs)
        for p in self._optimizer._parameter_list:
            mask = _mask_for(p)
            if mask is not None:
                p._data = (p._data * mask).astype(p._data.dtype)
        return out

    def minimize(self, loss, *args, **kwargs):
        res = self._optimizer.minimize(loss, *args, **kwargs)
        for p in self._optimizer._parameter_list:
            mask = _mask_for(p)
            if mask is not None:
                p._data = (p._data * mask).astype(p._data.dtype)
        return res


def decorate(optimizer) -> OptimizerWithSparsityGuarantee:
    """ref: asp.py:233."""
    return OptimizerWithSparsityGuarantee(optimizer)
