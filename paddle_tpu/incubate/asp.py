"""ASP — automatic structured (2:4) sparsity.

ref: python/paddle/incubate/asp/asp.py (prune_model :319, decorate :233,
set_excluded_layers :55, reset_excluded_layers :144) and utils.py mask
algorithms. TPU note: the MXU has no 2:4 sparse execution unit, so the
value here is sparsity-aware *training* (masks maintained through the
optimizer step exactly like the reference's
OptimizerWithSparsityGuarantee); the masked weights compress for serving.
"""
from __future__ import annotations

import weakref
from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor

__all__ = ["calculate_density", "check_mask_2d", "check_sparsity",
           "create_mask", "decorate", "prune_model",
           "set_excluded_layers", "reset_excluded_layers"]

_excluded_layers: List[str] = []
# id(param) -> (weakref(param), mask). The weakref guards against CPython
# id reuse: a dead entry whose id was recycled by an unrelated parameter
# must not silently mask it. Dead entries are swept on each prune_model.
_masks: Dict[int, Tuple["weakref.ref", jnp.ndarray]] = {}


def _mask_for(p) -> Optional[jnp.ndarray]:
    entry = _masks.get(id(p))
    if entry is None:
        return None
    ref, mask = entry
    if ref() is not p:  # stale id-reuse entry
        del _masks[id(p)]
        return None
    return mask


def set_excluded_layers(param_names, main_program=None):
    """ref: asp.py:55 — layers whose params are never pruned."""
    _excluded_layers.extend(param_names)


def reset_excluded_layers(main_program=None):
    """ref: asp.py:144."""
    _excluded_layers.clear()


def calculate_density(x) -> float:
    """ref: utils.py calculate_density: nonzero fraction."""
    arr = np.asarray(x.numpy() if isinstance(x, Tensor) else x)
    return float(np.count_nonzero(arr)) / max(arr.size, 1)


_MASK_ALGOS = ("mask_1d", "mask_2d_greedy", "mask_2d_best")


def _blocks_2d(arr: np.ndarray, m: int):
    """Zero-pad a 2-D array to multiples of m and tile it into
    (n_blocks, m, m) blocks (row-major block order)."""
    pad_r = (-arr.shape[0]) % m
    pad_c = (-arr.shape[1]) % m
    p = np.pad(arr, ((0, pad_r), (0, pad_c)))
    rows, cols = p.shape
    blocks = (p.reshape(rows // m, m, cols // m, m)
              .transpose(0, 2, 1, 3).reshape(-1, m, m))
    return blocks, (rows, cols)


def _unblock_2d(blocks, padded_shape, orig_shape, m: int) -> np.ndarray:
    rows, cols = padded_shape
    out = (blocks.reshape(rows // m, cols // m, m, m)
           .transpose(0, 2, 1, 3).reshape(rows, cols))
    return out[:orig_shape[0], :orig_shape[1]]


def _mask_2d_greedy(mat: np.ndarray, n: int, m: int) -> np.ndarray:
    """2-D n:m mask, greedy: per m x m block, admit entries in
    descending |value| order while the entry's row and column each still
    have < n kept entries (ref: utils.py get_mask_2d_greedy)."""
    blocks, pshape = _blocks_2d(np.abs(mat), m)
    n_blocks = len(blocks)
    order = np.argsort(-blocks.reshape(n_blocks, -1), axis=1)
    masks = np.zeros_like(blocks)
    row_used = np.zeros((n_blocks, m), np.int64)
    col_used = np.zeros((n_blocks, m), np.int64)
    bidx = np.arange(n_blocks)
    # vectorized across blocks: walk rank positions; at each rank every
    # block admits its candidate iff that entry's row and column still
    # have capacity (one candidate per block per rank, so plain fancy
    # indexing — no duplicate-index hazard)
    for rank in range(m * m):
        i, j = np.divmod(order[:, rank], m)
        ok = (row_used[bidx, i] < n) & (col_used[bidx, j] < n)
        masks[bidx[ok], i[ok], j[ok]] = 1.0
        row_used[bidx[ok], i[ok]] += 1
        col_used[bidx[ok], j[ok]] += 1
    return _unblock_2d(masks, pshape, mat.shape, m)


_patterns_2d_cache: Dict[Tuple[int, int], np.ndarray] = {}


def _valid_2d_patterns(n: int, m: int) -> np.ndarray:
    """All m x m binary patterns with exactly n ones per row and at most
    n per column, as a (P, m, m) array (ref: utils.py
    _compute_valid_2d_patterns)."""
    key = (n, m)
    cached = _patterns_2d_cache.get(key)
    if cached is not None:
        return cached
    if m > 6:
        raise NotImplementedError(
            f"mask_2d_best pattern enumeration is exponential in m "
            f"(got m={m}); use mask_2d_greedy for m > 6")
    import itertools
    row_choices = []
    for keep in itertools.combinations(range(m), n):
        row = np.zeros(m)
        row[list(keep)] = 1.0
        row_choices.append(row)
    pats: List[np.ndarray] = []

    def _extend(chosen, col_sum):
        if len(chosen) == m:
            pats.append(np.stack(chosen))
            return
        # prune: remaining rows must still be able to fill every column
        # to <= n without exceeding it
        for row in row_choices:
            new_sum = col_sum + row
            if (new_sum <= n).all():
                _extend(chosen + [row], new_sum)

    _extend([], np.zeros(m))
    out = np.stack(pats)
    _patterns_2d_cache[key] = out
    return out


def _mask_2d_best(mat: np.ndarray, n: int, m: int) -> np.ndarray:
    """2-D n:m mask maximizing retained L1 magnitude: score every valid
    pattern against each |block| and take the argmax (ref: utils.py
    get_mask_2d_best; we score |values| so negative weights rank by
    magnitude)."""
    pats = _valid_2d_patterns(n, m)
    blocks, pshape = _blocks_2d(np.abs(mat), m)
    scores = blocks.reshape(len(blocks), -1) @ pats.reshape(len(pats), -1).T
    masks = pats[np.argmax(scores, axis=1)]
    return _unblock_2d(masks, pshape, mat.shape, m)


def _as_2d(arr: np.ndarray) -> np.ndarray:
    """Collapse leading dims so the 2-D mask algorithms see
    (rows, last_dim) — the reduction (input-channel) dim stays minor."""
    return arr.reshape(1, -1) if arr.ndim == 1 else \
        arr.reshape(-1, arr.shape[-1])


def create_mask(x, func_name: str = "mask_1d", n: int = 2,
                m: int = 4) -> np.ndarray:
    """n:m structured mask (ref: utils.py create_mask): ``mask_1d``
    keeps the n largest-magnitude entries of every m consecutive weights
    along the last dim; ``mask_2d_greedy``/``mask_2d_best`` build m x m
    block patterns with <= n survivors per row AND column (greedy
    magnitude order vs exhaustive pattern search maximizing L1)."""
    if func_name not in _MASK_ALGOS:
        raise NotImplementedError(
            f"mask algorithm {func_name!r} not supported (available: "
            f"{_MASK_ALGOS})")
    arr = np.asarray(x.numpy() if isinstance(x, Tensor) else x)
    if func_name in ("mask_2d_greedy", "mask_2d_best"):
        algo = _mask_2d_greedy if func_name == "mask_2d_greedy" \
            else _mask_2d_best
        mask2d = algo(_as_2d(arr.astype(np.float64)), n, m)
        return mask2d.reshape(arr.shape).astype(arr.dtype)
    flat = arr.reshape(-1, arr.shape[-1])
    if arr.shape[-1] % m != 0:
        raise ValueError(
            f"last dim {arr.shape[-1]} must be divisible by m={m}")
    groups = flat.reshape(flat.shape[0], -1, m)
    order = np.argsort(-np.abs(groups), axis=-1)
    mask = np.zeros_like(groups)
    np.put_along_axis(mask, order[..., :n], 1.0, axis=-1)
    return mask.reshape(arr.shape).astype(arr.dtype)


def check_mask_2d(x, n: int = 2, m: int = 4) -> bool:
    """True iff every m x m block (zero-padded tiling of the collapsed
    2-D view) has <= n nonzeros in each row and each column (ref:
    utils.py check_mask_2d)."""
    arr = _as_2d(np.asarray(x.numpy() if isinstance(x, Tensor) else x))
    blocks, _ = _blocks_2d(arr, m)
    nz = blocks != 0
    return bool((nz.sum(axis=2) <= n).all() and (nz.sum(axis=1) <= n).all())


def check_sparsity(x, n: int = 2, m: int = 4,
                   func_name: str = "check_1d") -> bool:
    """``check_1d``: every m-group along the last dim has <= n nonzeros;
    ``check_2d``: the 2-D block property (ref: utils.py check_sparsity +
    CheckMethod.get_checking_method). Mask-algo names are accepted and
    mapped to their checking method, as the reference's
    CheckMethod.get_checking_method does."""
    to_check = {"check_1d": "check_1d", "mask_1d": "check_1d",
                "check_2d": "check_2d", "mask_2d_greedy": "check_2d",
                "mask_2d_best": "check_2d"}
    if func_name not in to_check:
        raise NotImplementedError(
            f"unknown check {func_name!r} (available: "
            f"{sorted(to_check)})")
    if to_check[func_name] == "check_2d":
        return check_mask_2d(x, n, m)
    arr = np.asarray(x.numpy() if isinstance(x, Tensor) else x)
    if arr.shape[-1] % m != 0:
        return False
    groups = arr.reshape(-1, arr.shape[-1] // m, m)
    return bool((np.count_nonzero(groups, axis=-1) <= n).all())


def _excluded(name: str) -> bool:
    """Exact name or dotted-prefix match (substring matching would make
    '0.weight' also exclude '10.weight')."""
    for ex in _excluded_layers:
        if name == ex or name.startswith(ex + "."):
            return True
    return False


def _prunable(name: str, p: Tensor) -> bool:
    if _excluded(name):
        return False
    d = p._data
    # the reference prunes FC/conv weights, not biases/norms
    return d.ndim >= 2 and d.shape[-1] % 4 == 0


def prune_model(model, n: int = 2, m: int = 4, mask_algo: str = "mask_1d",
                with_mask: bool = True):
    """Apply n:m masks to the model's prunable weights; with_mask=True
    (default) also remembers them so a decorated optimizer keeps pruned
    entries at zero (ref: asp.py:319)."""
    for k in [k for k, (ref, _) in _masks.items() if ref() is None]:
        del _masks[k]  # sweep dead params so ids can't be misapplied
    pruned = {}
    for name, p in model.named_parameters():
        if not _prunable(name, p):
            continue
        mask = jnp.asarray(create_mask(p, mask_algo, n, m))
        p._data = (p._data * mask).astype(p._data.dtype)
        if with_mask:
            _masks[id(p)] = (weakref.ref(p), mask)
        pruned[name] = mask
    return pruned


class OptimizerWithSparsityGuarantee:
    """Re-applies the ASP masks after every step so pruned weights stay
    exactly zero through training (ref: asp.py:506)."""

    def __init__(self, optimizer):
        self._optimizer = optimizer

    def __getattr__(self, item):
        return getattr(self._optimizer, item)

    def step(self, *args, **kwargs):
        out = self._optimizer.step(*args, **kwargs)
        for p in self._optimizer._parameter_list:
            mask = _mask_for(p)
            if mask is not None:
                p._data = (p._data * mask).astype(p._data.dtype)
        return out

    def minimize(self, loss, *args, **kwargs):
        res = self._optimizer.minimize(loss, *args, **kwargs)
        for p in self._optimizer._parameter_list:
            mask = _mask_for(p)
            if mask is not None:
                p._data = (p._data * mask).astype(p._data.dtype)
        return res


def decorate(optimizer) -> OptimizerWithSparsityGuarantee:
    """ref: asp.py:233."""
    return OptimizerWithSparsityGuarantee(optimizer)
