"""incubate.nn — fused transformer building blocks.

ref: python/paddle/incubate/nn/layer/fused_transformer.py
(FusedMultiHeadAttention, FusedFeedForward, FusedTransformerEncoderLayer),
layer/fused_linear.py, layer/fused_dropout_add.py. TPU-native: "fused"
means routed through the Pallas flash kernel / fused norm ops where they
exist and expressed as single jit-friendly expressions XLA fuses
elsewhere — same API, compiler does the fusion.
"""
from __future__ import annotations

from ... import nn as _nn
from ...nn.functional.attention import scaled_dot_product_attention
from . import functional
from .functional import fused_dropout_add

__all__ = [
    "FusedLinear", "FusedDropoutAdd", "FusedMultiHeadAttention",
    "FusedFeedForward", "FusedTransformerEncoderLayer", "functional",
]



class FusedLinear(_nn.Linear):
    """ref: layer/fused_linear.py — same math, XLA fuses bias add."""


class FusedDropoutAdd(_nn.Layer):
    """ref: layer/fused_dropout_add.py."""

    def __init__(self, p=0.5, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.mode = mode

    def forward(self, x, y):
        return fused_dropout_add(x, y, self.p, self.training, self.mode)


class FusedMultiHeadAttention(_nn.Layer):
    """Pre/post-LN self-attention block with residual, driven through the
    flash-attention path (ref: fused_transformer.py
    FusedMultiHeadAttention)."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False,
                 qkv_weight_attr=None, qkv_bias_attr=None,
                 linear_weight_attr=None, linear_bias_attr=None,
                 pre_ln_scale_attr=None, pre_ln_bias_attr=None,
                 ln_scale_attr=None, ln_bias_attr=None, epsilon=1e-5,
                 nranks=1, ring_id=-1, name=None):
        super().__init__()
        if embed_dim % num_heads != 0:
            raise ValueError(
                f"num_heads ({num_heads}) must divide embed_dim "
                f"({embed_dim})")
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self.qkv = _nn.Linear(embed_dim, 3 * embed_dim)
        self.out_proj = _nn.Linear(embed_dim, embed_dim)
        self.ln = _nn.LayerNorm(embed_dim, epsilon=epsilon)
        self.dropout = _nn.Dropout(dropout_rate)
        self.attn_dropout_rate = attn_dropout_rate

    def forward(self, x, attn_mask=None, cache=None):
        residual = x
        if self.normalize_before:
            x = self.ln(x)
        b, l, _ = x.shape
        qkv = self.qkv(x).reshape([b, l, 3, self.num_heads, self.head_dim])
        q = qkv[:, :, 0]
        k = qkv[:, :, 1]
        v = qkv[:, :, 2]
        attn = scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask,
            dropout_p=self.attn_dropout_rate if self.training else 0.0)
        out = self.out_proj(attn.reshape([b, l, self.embed_dim]))
        out = residual + self.dropout(out)
        if not self.normalize_before:
            out = self.ln(out)
        return out


class FusedFeedForward(_nn.Layer):
    """ref: fused_transformer.py FusedFeedForward."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None,
                 ln2_bias_attr=None, nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.normalize_before = normalize_before
        self.linear1 = _nn.Linear(d_model, dim_feedforward)
        self.linear2 = _nn.Linear(dim_feedforward, d_model)
        self.ln = _nn.LayerNorm(d_model, epsilon=epsilon)
        self.dropout = _nn.Dropout(dropout_rate)
        self.act_dropout = _nn.Dropout(
            dropout_rate if act_dropout_rate is None else act_dropout_rate)
        self.activation = getattr(_nn.functional, activation)

    def forward(self, x):
        residual = x
        if self.normalize_before:
            x = self.ln(x)
        x = self.act_dropout(self.activation(self.linear1(x)))
        x = residual + self.dropout(self.linear2(x))
        if not self.normalize_before:
            x = self.ln(x)
        return x


class FusedTransformerEncoderLayer(_nn.Layer):
    """ref: fused_transformer.py FusedTransformerEncoderLayer."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False):
        super().__init__()
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate=dropout_rate,
            attn_dropout_rate=(dropout_rate if attn_dropout_rate is None
                               else attn_dropout_rate),
            normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before)

    def forward(self, src, src_mask=None, cache=None):
        return self.ffn(self.fused_attn(src, attn_mask=src_mask))
