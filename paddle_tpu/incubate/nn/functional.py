"""incubate.nn.functional — fused functional ops.

ref: python/paddle/incubate/nn/functional/ (fused_matmul_bias, fused_dropout_add,
fused_rms_norm, fused_layer_norm, fused_bias_act, swiglu,
fused_rotary_position_embedding).

ref: python/paddle/incubate/nn/layer/fused_transformer.py
(FusedMultiHeadAttention, FusedFeedForward, FusedTransformerEncoderLayer),
layer/fused_linear.py, layer/fused_dropout_add.py, and
incubate/nn/functional/ (fused_linear, fused_dropout_add, fused_rms_norm,
fused_layer_norm, fused_bias_act, fused_rotary_position_embedding,
swiglu). TPU-native: "fused" means routed through the Pallas flash kernel
/ fused norm ops where they exist and expressed as single jit-friendly
expressions XLA fuses elsewhere — same API, compiler does the fusion.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...core.autograd import apply_op
from ...core.tensor import Tensor
from ...nn.functional.norm import layer_norm, rms_norm

__all__ = [
    "fused_linear", "fused_dropout_add", "fused_rms_norm",
    "fused_layer_norm", "fused_bias_act", "swiglu",
    "fused_rotary_position_embedding",
    "fused_layernorm_residual_dropout",
]


# --------------------------- functional ------------------------------------

def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    """ref: incubate/nn/functional/fused_matmul_bias.py fused_linear."""
    def f(a, w, *b):
        w = w.T if transpose_weight else w
        out = a @ w
        if b:
            out = out + b[0]
        return out
    args = [x, weight] + ([bias] if bias is not None else [])
    return apply_op(f, *args, op_name="fused_linear")


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train",
                      name=None):
    """dropout(x) + y in one op (ref: fused_dropout_add.py)."""
    from ...nn.functional.common import _rng_key_tensor
    if not training or p == 0.0:
        if not training and mode == "downscale_in_infer" and p > 0.0:
            # raw masks at train time -> scale by keep prob at inference
            # (same contract as nn.functional.dropout, common.py)
            return apply_op(lambda a, b: (a * (1.0 - p) + b).astype(b.dtype),
                            x, y, op_name="fused_dropout_add")
        return apply_op(lambda a, b: a + b, x, y,
                        op_name="fused_dropout_add")
    if p >= 1.0:  # everything dropped; where()-vjp at p=1 would NaN
        return apply_op(lambda a, b: (a * 0 + b).astype(b.dtype), x, y,
                        op_name="fused_dropout_add")
    key_t = _rng_key_tensor()  # drawn only when 0 < p < 1

    def f(a, b, key):
        keep = jax.random.bernoulli(key, 1.0 - p, a.shape)
        scale = 1.0 / (1.0 - p) if mode == "upscale_in_train" else 1.0
        a = a * keep.astype(a.dtype) * scale
        return (a + b).astype(b.dtype)
    return apply_op(f, x, y, key_t, op_name="fused_dropout_add")


def fused_rms_norm(x, norm_weight=None, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, name=None):
    """ref: incubate/nn/functional/fused_rms_norm.py (maps to the Pallas
    rms_norm path on TPU)."""
    out = rms_norm(x, norm_weight, epsilon)
    if norm_bias is not None:
        out = apply_op(lambda a, b: a + b, out, norm_bias, op_name="add")
    return out


def fused_layer_norm(x, norm_weight=None, norm_bias=None, epsilon=1e-5,
                     begin_norm_axis=1, name=None):
    """ref: incubate/nn/functional/fused_layer_norm.py."""
    xd = x._data if isinstance(x, Tensor) else x
    shape = list(xd.shape[begin_norm_axis:])
    return layer_norm(x, shape, norm_weight, norm_bias, epsilon)


def fused_bias_act(x, bias=None, act_method="gelu", name=None):
    """ref: incubate/nn/functional/fused_bias_act.py."""
    acts = {"gelu": jax.nn.gelu, "relu": jax.nn.relu,
            "silu": jax.nn.silu, "swiglu": None}
    if act_method not in acts:
        raise ValueError(f"unsupported act_method {act_method!r}")

    def f(a, *b):
        if b:
            a = a + b[0]
        if act_method == "swiglu":
            u, v = jnp.split(a, 2, axis=-1)
            return jax.nn.silu(u) * v
        return acts[act_method](a)
    args = [x] + ([bias] if bias is not None else [])
    return apply_op(f, *args, op_name="fused_bias_act")


def swiglu(x, y=None, name=None):
    """ref: incubate/nn/functional/swiglu.py: silu(x) * y (y defaults to
    the second half of x)."""
    if y is None:
        return apply_op(
            lambda a: jax.nn.silu(jnp.split(a, 2, -1)[0])
            * jnp.split(a, 2, -1)[1], x, op_name="swiglu")
    return apply_op(lambda a, b: jax.nn.silu(a) * b, x, y,
                    op_name="swiglu")


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None,
                                    use_neox_rotary_style=True, name=None):
    """ref: incubate/nn/functional/fused_rotary_position_embedding.py.
    q/k: [B, L, H, D]; sin/cos: [..., max_len, ..., D] tables (built for
    positions 0..L-1 if not given); position_ids: [B, L] gather indices
    into the tables (e.g. the KV-cache decode offset)."""
    qd = q._data if isinstance(q, Tensor) else q
    b, l, h, d = qd.shape
    inv = 1.0 / (10000.0 ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))

    if sin is None or cos is None:
        if position_ids is not None:
            # compute angles straight from the (possibly traced) ids — no
            # data-dependent table size, safe under jit
            pid = (position_ids._data if isinstance(position_ids, Tensor)
                   else jnp.asarray(position_ids)).astype(jnp.float32)
            freqs = pid[..., None] * inv       # [B, L, D/2]
        else:
            freqs = (jnp.arange(l, dtype=jnp.float32)[None, :, None]
                     * inv)                    # [1, L, D/2]
        if use_neox_rotary_style:
            emb = jnp.concatenate([freqs, freqs], -1)
        else:  # interleaved pairs: (f0, f0, f1, f1, ...)
            emb = jnp.repeat(freqs, 2, axis=-1)
        s_bc = jnp.sin(emb)[:, :, None, :]
        c_bc = jnp.cos(emb)[:, :, None, :]
    else:
        sin_v = (sin._data if isinstance(sin, Tensor)
                 else jnp.asarray(sin)).reshape(-1, d)
        cos_v = (cos._data if isinstance(cos, Tensor)
                 else jnp.asarray(cos)).reshape(-1, d)
        if position_ids is not None:
            pid = (position_ids._data if isinstance(position_ids, Tensor)
                   else jnp.asarray(position_ids))
            s_bc = jnp.take(sin_v, pid, axis=0)[:, :, None, :]
            c_bc = jnp.take(cos_v, pid, axis=0)[:, :, None, :]
        else:
            s_bc = sin_v[None, :l, None, :]
            c_bc = cos_v[None, :l, None, :]

    def rot(a):
        if use_neox_rotary_style:
            half = a.shape[-1] // 2
            return jnp.concatenate([-a[..., half:], a[..., :half]], -1)
        # interleaved: (-x1, x0, -x3, x2, ...)
        x = a.reshape(a.shape[:-1] + (a.shape[-1] // 2, 2))
        x = jnp.stack([-x[..., 1], x[..., 0]], axis=-1)
        return x.reshape(a.shape)

    def f(a):
        a32 = a.astype(jnp.float32)
        return (a32 * c_bc.astype(jnp.float32)
                + rot(a32) * s_bc.astype(jnp.float32)).astype(a.dtype)

    outs = [apply_op(f, t, op_name="fused_rope") if t is not None else None
            for t in (q, k, v)]
    return tuple(outs)


def fused_layernorm_residual_dropout(x, residual, norm_weight=None,
                                     norm_bias=None, p=0.0, epsilon=1e-5,
                                     training=True, name=None):
    """dropout(x) + residual, then layer_norm — ONE traced op, so XLA
    emits a single fused HBM pass (ref: phi/kernels/fusion/gpu/
    fused_layernorm_residual_dropout_bias — the reference hand-fuses this
    because its eager path pays a kernel launch per piece; here fusion is
    the compiler's job and this op just guarantees one dispatch).
    Returns (out, dropout_plus_residual) like the reference kernel."""
    from ...nn.functional.common import _rng_key_tensor
    drop = p if training else 0.0
    extras = []
    if 0.0 < drop < 1.0:  # p>=1 drops everything, no rng needed
        extras.append(_rng_key_tensor())
    if norm_weight is not None:
        extras.append(norm_weight)
    if norm_bias is not None:
        extras.append(norm_bias)

    def f(a, res, *rest):
        i = 0
        if drop >= 1.0:
            a = jnp.zeros_like(a)  # not a mask: p=1 drops everything
        elif drop > 0.0:
            key = rest[i]
            i += 1
            keep = jax.random.bernoulli(key, 1.0 - drop, a.shape)
            # multiply by the (static) inverse keep-prob instead of
            # dividing under where(): the where-vjp would emit 0/0=NaN
            # grads at p->1
            a = (a * keep.astype(a.dtype) *
                 (1.0 / (1.0 - drop))).astype(res.dtype)
        w = rest[i] if norm_weight is not None else None
        if norm_weight is not None:
            i += 1
        b = rest[i] if norm_bias is not None else None
        summed = a + res
        # stats in fp32 (bf16 mantissa is too short at real hidden dims;
        # same contract as nn.functional.layer_norm and the ref kernel)
        s32 = summed.astype(jnp.float32)
        mu = s32.mean(-1, keepdims=True)
        var = s32.var(-1, keepdims=True)
        out = (s32 - mu) / jnp.sqrt(var + epsilon)
        if w is not None:
            out = out * w
        if b is not None:
            out = out + b
        return out.astype(summed.dtype), summed

    return apply_op(f, x, residual, *extras,
                    op_name="fused_layernorm_residual_dropout")
