"""paddle.incubate analog: experimental features.

ref: python/paddle/incubate/ — the pieces with TPU relevance are the MoE
stack (incubate/distributed/models/moe/) and fused transformer layers
(incubate/nn/); fused ops are already XLA fusions here.
"""
from . import moe  # noqa: F401
from . import asp  # noqa: F401
from . import nn  # noqa: F401
