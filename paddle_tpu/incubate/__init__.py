"""paddle.incubate analog: experimental features.

ref: python/paddle/incubate/ — the pieces with TPU relevance are the MoE
stack (incubate/distributed/models/moe/) and fused transformer layers
(incubate/nn/); fused ops are already XLA fusions here.
"""
from . import moe  # noqa: F401
from . import asp  # noqa: F401
from . import nn  # noqa: F401
from . import inference  # noqa: F401
from .extras import (  # noqa: F401
    LookAhead, ModelAverage, graph_khop_sampler, graph_reindex,
    graph_sample_neighbors, graph_send_recv, identity_loss, segment_max,
    segment_mean, segment_min, segment_sum, softmax_mask_fuse,
    softmax_mask_fuse_upper_triangle)
