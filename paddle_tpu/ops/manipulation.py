"""Shape / layout manipulation ops. ref: python/paddle/tensor/manipulation.py"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.autograd import apply_op
from ..core.dtype import convert_dtype
from ..core.tensor import Tensor


def _shape_arg(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in np.asarray(shape._data))
    return tuple(int(s._data) if isinstance(s, Tensor) else int(s)
                 for s in shape)


def reshape(x, shape, name=None):
    s = _shape_arg(shape)
    return apply_op(lambda a: jnp.reshape(a, s), x, op_name="reshape")


def reshape_(x, shape, name=None):
    x._data = jnp.reshape(x._data, _shape_arg(shape))
    return x


def transpose(x, perm, name=None):
    return apply_op(lambda a: jnp.transpose(a, perm), x, op_name="transpose")


def moveaxis(x, source, destination, name=None):
    return apply_op(lambda a: jnp.moveaxis(a, source, destination), x,
                    op_name="moveaxis")


def swapaxes(x, axis0, axis1, name=None):
    return apply_op(lambda a: jnp.swapaxes(a, axis0, axis1), x,
                    op_name="swapaxes")


def concat(x, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    tensors = list(x)
    return apply_op(lambda *arrs: jnp.concatenate(arrs, axis=axis), *tensors,
                    op_name="concat")


def stack(x, axis=0, name=None):
    tensors = list(x)
    return apply_op(lambda *arrs: jnp.stack(arrs, axis=axis), *tensors,
                    op_name="stack")


def split(x, num_or_sections, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    if isinstance(num_or_sections, int):
        n = x.shape[axis] if isinstance(x, Tensor) else x.shape[axis]
        out = apply_op(
            lambda a: tuple(jnp.split(a, num_or_sections, axis=axis)), x,
            op_name="split")
    else:
        secs = [int(s) for s in num_or_sections]
        # allow one -1 section
        total = x.shape[axis]
        if -1 in secs:
            known = int(np.sum([s for s in secs if s != -1]))
            secs[secs.index(-1)] = total - known
        points = list(np.cumsum(secs)[:-1])
        out = apply_op(lambda a: tuple(jnp.split(a, points, axis=axis)), x,
                       op_name="split")
    return list(out) if isinstance(out, tuple) else [out]


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def unbind(x, axis=0, name=None):
    n = x.shape[axis]
    out = apply_op(
        lambda a: tuple(jnp.squeeze(s, axis)
                        for s in jnp.split(a, n, axis=axis)),
        x, op_name="unbind")
    return list(out) if isinstance(out, tuple) else [out]


def squeeze(x, axis=None, name=None):
    def f(a):
        if axis is None:
            return jnp.squeeze(a)
        ax = axis if isinstance(axis, (list, tuple)) else [axis]
        ax = tuple(i for i in ax if a.shape[i] == 1)
        return jnp.squeeze(a, ax) if ax else a
    return apply_op(f, x, op_name="squeeze")


def unsqueeze(x, axis, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else (axis,)
    return apply_op(lambda a: jnp.expand_dims(a, ax), x, op_name="unsqueeze")


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    def f(a):
        nd = a.ndim
        s = start_axis if start_axis >= 0 else nd + start_axis
        e = stop_axis if stop_axis >= 0 else nd + stop_axis
        new_shape = a.shape[:s] + (-1,) + a.shape[e + 1:]
        return jnp.reshape(a, new_shape)
    return apply_op(f, x, op_name="flatten")


def expand(x, shape, name=None):
    s = _shape_arg(shape)

    def f(a):
        # paddle semantics: -1 keeps the original dim; only legal for dims
        # that exist in the input (trailing alignment)
        tgt = list(s)
        off = len(tgt) - a.ndim
        for i in range(len(tgt)):
            if tgt[i] == -1:
                if i < off:
                    raise ValueError(
                        f"expand: -1 at position {i} refers to a new leading "
                        f"dim; sizes of added dims must be given explicitly")
                tgt[i] = a.shape[i - off]
        return jnp.broadcast_to(a, tuple(tgt))
    return apply_op(f, x, op_name="expand")


def broadcast_to(x, shape, name=None):
    return expand(x, shape)


def expand_as(x, y, name=None):
    return expand(x, y.shape)


def broadcast_tensors(inputs, name=None):
    arrs = [t._data if isinstance(t, Tensor) else jnp.asarray(t)
            for t in inputs]
    shape = jnp.broadcast_shapes(*[a.shape for a in arrs])
    return [expand(t, shape) for t in inputs]


def tile(x, repeat_times, name=None):
    r = _shape_arg(repeat_times)
    return apply_op(lambda a: jnp.tile(a, r), x, op_name="tile")


def repeat_interleave(x, repeats, axis=None, name=None):
    rd = repeats._data if isinstance(repeats, Tensor) else repeats
    return apply_op(lambda a: jnp.repeat(a, rd, axis=axis), x,
                    op_name="repeat_interleave")


def flip(x, axis, name=None):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else (axis,)
    return apply_op(lambda a: jnp.flip(a, ax), x, op_name="flip")


def roll(x, shifts, axis=None, name=None):
    return apply_op(lambda a: jnp.roll(a, shifts, axis=axis), x,
                    op_name="roll")


def rot90(x, k=1, axes=(0, 1), name=None):
    return apply_op(lambda a: jnp.rot90(a, k, axes), x, op_name="rot90")


def gather(x, index, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())

    def f(a, idx):
        return jnp.take(a, idx.reshape(-1) if idx.ndim > 1 else idx,
                        axis=axis)
    return apply_op(f, x, index, op_name="gather")


def gather_nd(x, index, name=None):
    def f(a, idx):
        return a[tuple(jnp.moveaxis(idx, -1, 0))]
    return apply_op(f, x, index, op_name="gather_nd")


def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    def f(a, idx):
        if broadcast:
            tgt = list(a.shape)
            tgt[axis] = idx.shape[axis]
            idx = jnp.broadcast_to(idx, tuple(tgt))
        return jnp.take_along_axis(a, idx, axis=axis)
    return apply_op(f, arr, indices, op_name="take_along_axis")


def put_along_axis(arr, indices, values, axis, reduce="assign",
                   include_self=True, broadcast=True, name=None):
    def f(a, idx, v):
        v = jnp.broadcast_to(v, idx.shape) if jnp.ndim(v) == 0 or \
            v.shape != idx.shape else v
        dims = [jnp.arange(s).reshape(
            [-1 if i == d else 1 for i in range(idx.ndim)])
            for d, s in enumerate(idx.shape)]
        full_idx = tuple(idx if d == axis else
                         jnp.broadcast_to(dims[d], idx.shape)
                         for d in range(idx.ndim))
        at = a.at[full_idx]
        if reduce == "assign":
            return at.set(v)
        if reduce in ("add", "sum"):
            return at.add(v)
        if reduce in ("mul", "multiply"):
            return at.multiply(v)
        if reduce == "amax":
            return at.max(v)
        if reduce == "amin":
            return at.min(v)
        raise ValueError(f"unknown reduce {reduce}")
    return apply_op(f, arr, indices, values, op_name="put_along_axis")


def scatter(x, index, updates, overwrite=True, name=None):
    def f(a, idx, upd):
        if overwrite:
            return a.at[idx].set(upd)
        return a.at[idx].add(upd)
    return apply_op(f, x, index, updates, op_name="scatter")


def scatter_nd_add(x, index, updates, name=None):
    def f(a, idx, upd):
        return a.at[tuple(jnp.moveaxis(idx, -1, 0))].add(upd)
    return apply_op(f, x, index, updates, op_name="scatter_nd_add")


def scatter_nd(index, updates, shape, name=None):
    s = _shape_arg(shape)

    def f(idx, upd):
        z = jnp.zeros(s, upd.dtype)
        return z.at[tuple(jnp.moveaxis(idx, -1, 0))].add(upd)
    return apply_op(f, index, updates, op_name="scatter_nd")


def index_select(x, index, axis=0, name=None):
    def f(a, idx):
        return jnp.take(a, idx, axis=axis)
    return apply_op(f, x, index, op_name="index_select")


def index_add(x, index, axis, value, name=None):
    def f(a, idx, v):
        moved = jnp.moveaxis(a, axis, 0)
        vm = jnp.moveaxis(v, axis, 0)
        out = moved.at[idx].add(vm)
        return jnp.moveaxis(out, 0, axis)
    return apply_op(f, x, index, value, op_name="index_add")


def index_put(x, indices, value, accumulate=False, name=None):
    idxs = tuple(i._data if isinstance(i, Tensor) else i for i in indices)

    def f(a, v):
        at = a.at[idxs]
        return at.add(v) if accumulate else at.set(v)
    return apply_op(f, x, value, op_name="index_put")


def masked_select(x, mask, name=None):
    xd = np.asarray(x._data if isinstance(x, Tensor) else x)
    md = np.asarray(mask._data if isinstance(mask, Tensor) else mask)
    return Tensor(jnp.asarray(xd[np.broadcast_to(md, xd.shape)]))


def masked_fill(x, mask, value, name=None):
    v = value._data if isinstance(value, Tensor) else value

    def f(a, m):
        return jnp.where(m, jnp.asarray(v, a.dtype), a)
    return apply_op(f, x, mask, op_name="masked_fill")


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        from .math import nonzero
        return nonzero(condition, as_tuple=True)
    return apply_op(lambda c, a, b: jnp.where(c, a, b), condition, x, y,
                    op_name="where")


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    pd = _shape_arg(pad) if not isinstance(pad, (list, tuple)) else [
        int(p.item()) if isinstance(p, Tensor) else int(p) for p in pad]

    def f(a):
        nd = a.ndim
        if len(pd) == 2 * nd:
            width = [(pd[2 * i], pd[2 * i + 1]) for i in range(nd)]
        else:
            # paddle NCHW convention: pad is [left,right,top,bottom...] on
            # trailing spatial dims, reversed pair order
            n_spatial = len(pd) // 2
            width = [(0, 0)] * (nd - n_spatial)
            spatial = [(pd[2 * i], pd[2 * i + 1]) for i in range(n_spatial)]
            if data_format in ("NHWC", "NLC", "NDHWC"):
                width = [(0, 0)] + spatial[::-1] + [(0, 0)]
            else:
                width = [(0, 0), (0, 0)] + spatial[::-1]
        if mode == "constant":
            return jnp.pad(a, width, constant_values=value)
        jmode = {"reflect": "reflect", "replicate": "edge",
                 "circular": "wrap"}[mode]
        return jnp.pad(a, width, mode=jmode)
    return apply_op(f, x, op_name="pad")


import builtins as _builtins  # noqa: E402


def slice(input, axes, starts, ends, name=None):
    def _v(lst):
        return [int(v.item()) if isinstance(v, Tensor) else int(v)
                for v in lst]
    axes, starts, ends = list(axes), _v(starts), _v(ends)

    def f(a):
        idx = [_builtins.slice(None)] * a.ndim
        for ax, s, e in zip(axes, starts, ends):
            idx[ax] = _builtins.slice(s, e)
        return a[tuple(idx)]
    return apply_op(f, input, op_name="slice")


def strided_slice(x, axes, starts, ends, strides, name=None):
    def f(a):
        idx = [_builtins.slice(None)] * a.ndim
        for ax, s, e, st in zip(axes, starts, ends, strides):
            idx[ax] = _builtins.slice(s, e, st)
        return a[tuple(idx)]
    return apply_op(f, x, op_name="strided_slice")


def crop(x, shape=None, offsets=None, name=None):
    s = _shape_arg(shape)
    off = _shape_arg(offsets) if offsets is not None else (0,) * len(s)

    def f(a):
        idx = tuple(_builtins.slice(o, o + d) for o, d in zip(off, s))
        return a[idx]
    return apply_op(f, x, op_name="crop")


def as_strided(x, shape, stride, offset=0, name=None):
    xd = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    flat = jnp.ravel(xd)
    idx = offset + sum(
        np.indices(shape)[i] * stride[i] for i in range(len(shape)))
    return Tensor(flat[jnp.asarray(idx)])


def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    return x.astype(convert_dtype(shape_or_dtype))


def numel(x, name=None):
    return Tensor(jnp.asarray(x.size, jnp.int64))


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    def f(idx):
        per = (index_num + nshards - 1) // nshards
        lo, hi = shard_id * per, (shard_id + 1) * per
        ok = (idx >= lo) & (idx < hi)
        return jnp.where(ok, idx - lo, ignore_value)
    return apply_op(f, input, op_name="shard_index")


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    pre = prepend._data if isinstance(prepend, Tensor) else prepend
    app = append._data if isinstance(append, Tensor) else append
    return apply_op(lambda a: jnp.diff(a, n=n, axis=axis, prepend=pre,
                                       append=app), x, op_name="diff")


def atleast_1d(*inputs):
    out = [apply_op(jnp.atleast_1d, t, op_name="atleast_1d") for t in inputs]
    return out[0] if len(out) == 1 else out


def atleast_2d(*inputs):
    out = [apply_op(jnp.atleast_2d, t, op_name="atleast_2d") for t in inputs]
    return out[0] if len(out) == 1 else out


def atleast_3d(*inputs):
    out = [apply_op(jnp.atleast_3d, t, op_name="atleast_3d") for t in inputs]
    return out[0] if len(out) == 1 else out


def tensordot(x, y, axes=2, name=None):
    return apply_op(lambda a, b: jnp.tensordot(a, b, axes=axes), x, y,
                    op_name="tensordot")


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col. ref: python/paddle/nn/functional/common.py unfold"""
    ks = kernel_sizes if isinstance(kernel_sizes, (list, tuple)) \
        else [kernel_sizes] * 2
    st = strides if isinstance(strides, (list, tuple)) else [strides] * 2
    pd = paddings if isinstance(paddings, (list, tuple)) else [paddings] * 2
    if len(pd) == 2:
        pd = [pd[0], pd[1], pd[0], pd[1]]
    dl = dilations if isinstance(dilations, (list, tuple)) else [dilations] * 2

    def f(a):
        n, c, h, w = a.shape
        a = jnp.pad(a, ((0, 0), (0, 0), (pd[0], pd[2]), (pd[1], pd[3])))
        oh = (a.shape[2] - (dl[0] * (ks[0] - 1) + 1)) // st[0] + 1
        ow = (a.shape[3] - (dl[1] * (ks[1] - 1) + 1)) // st[1] + 1
        patches = jax.lax.conv_general_dilated_patches(
            a, filter_shape=ks, window_strides=st, padding="VALID",
            rhs_dilation=dl, dimension_numbers=("NCHW", "OIHW", "NCHW"))
        return patches.reshape(n, c * ks[0] * ks[1], oh * ow)
    return apply_op(f, x, op_name="unfold")
