"""Op-table loader: ops.yaml -> native C++ OpRegistry (+ Python mirror).

ref: the reference's build-time codegen consumes paddle/phi/ops/yaml/
ops.yaml to generate its C++ API/grad-nodes/bindings (SURVEY §2.1 codegen
suite row). Here the same single-source table populates the native
OpRegistry (kernel-dispatch metadata: arity, vjp, SPMD rule) at import —
kernels are traced XLA programs, so there is no C++ kernel body to
generate, only descriptors to serve dispatch and introspection.
"""
from __future__ import annotations

import os
from typing import Dict, Optional

__all__ = ["get_op_info", "list_ops", "num_ops", "OP_TABLE"]

_HERE = os.path.dirname(os.path.abspath(__file__))

OP_TABLE: Dict[str, dict] = {}


# YAML 1.1 scalar resolution, mirroring PyYAML's SafeLoader resolvers
# exactly (tests assert agreement): bool WORDS in their three accepted
# casings; ints incl. sign/underscores and the 0x/0o-less octal, hex,
# binary forms; floats REQUIRE a dot (so `1e5` stays a string, as
# PyYAML resolves it). The old ``int(v) if v.isdigit()`` mis-parsed
# ``-1``/``1.5e-3`` as strings — silent descriptor corruption when
# PyYAML is absent.
import re as _re

_YAML_BOOLS = {}
for _w, _b in (("yes", True), ("no", False), ("true", True),
               ("false", False), ("on", True), ("off", False)):
    for _form in (_w, _w.capitalize(), _w.upper()):
        _YAML_BOOLS[_form] = _b
_YAML_NULLS = {"", "~", "null", "Null", "NULL"}
_YAML_INT = _re.compile(
    r"^[-+]?(0b[0-1_]+|0x[0-9a-fA-F_]+|0[0-7_]+|(0|[1-9][0-9_]*))$")
_YAML_FLOAT = _re.compile(  # YAML 1.1: the exponent SIGN is mandatory
    r"^[-+]?([0-9][0-9_]*\.[0-9_]*([eE][-+][0-9]+)?"
    r"|\.[0-9_]+([eE][-+][0-9]+)?)$")
_YAML_INF = _re.compile(r"^[-+]?\.(inf|Inf|INF)$")
_YAML_NAN = _re.compile(r"^\.(nan|NaN|NAN)$")


def _parse_scalar(v: str):
    if len(v) >= 2 and v[0] == v[-1] and v[0] in ("'", '"'):
        return v[1:-1]
    if v in _YAML_NULLS:
        return None
    b = _YAML_BOOLS.get(v)
    if b is not None:
        return b
    if _YAML_INT.match(v):
        s = v.replace("_", "")
        sign, mag = (s[0], s[1:]) if s[0] in "+-" else ("", s)
        try:
            if mag.startswith(("0b", "0x")):
                n = int(mag, 0)
            elif mag.startswith("0") and mag != "0":
                n = int(mag, 8)  # YAML 1.1 leading-zero octal
            else:
                n = int(mag)
        except ValueError:  # degenerate all-underscore digits
            return v
        return -n if sign == "-" else n
    if _YAML_FLOAT.match(v):
        return float(v.replace("_", ""))
    if _YAML_INF.match(v):
        return float("-inf") if v[0] == "-" else float("inf")
    if _YAML_NAN.match(v):
        return float("nan")
    return v


def _parse_yaml_fallback(text: str) -> list:
    """Minimal parser for our flat ``ops:`` list-of-mappings schema;
    asserted against PyYAML in tests/test_ops_yaml_coverage.py."""
    ops, cur = [], None
    for line in text.splitlines():
        s = line.strip()
        if s.startswith("#") or not s:
            continue
        if s.startswith("- name:"):
            cur = {"name": _parse_scalar(s.split(":", 1)[1].strip())}
            ops.append(cur)
        elif cur is not None and ":" in s and s != "ops:":
            # exact header match: a prefix test would silently drop any
            # future descriptor key that happens to start with "ops"
            k, v = s.split(":", 1)
            cur[k.strip()] = _parse_scalar(v.strip())
    return ops


def _load_yaml() -> list:
    path = os.path.join(_HERE, "ops.yaml")
    with open(path) as f:
        text = f.read()
    try:
        import yaml
        return yaml.safe_load(text)["ops"]
    except ImportError:
        return _parse_yaml_fallback(text)


_FUSABLE_CLASSES = (False, True, "reduce", "epilogue", "attention")

# The shape-spec vocabulary for the analysis plane's abstract
# interpreter (analysis/shapes.py declares one evaluator per id and
# asserts it covers exactly this tuple): how an op's output
# (shape, dtype) follows from its inputs + attrs. Declared here —
# import-light, loaded with the table — so a typo'd spec fails at
# import, not at the first capture plan.
SHAPE_SPECS = ("elementwise", "broadcast", "reduce", "matmul", "linear",
               "cast", "attention")


def _norm_fusable(name: str, v):
    """Validate the ops.yaml `fusable` marker class at load time so a
    typo ('fusable: reduction') can't silently disable fusion for an op
    the tests then assert fuses."""
    if v is None:
        return False
    if v not in _FUSABLE_CLASSES:
        raise ValueError(
            f"ops.yaml: op {name!r} declares unknown fusable class "
            f"{v!r}; expected one of {_FUSABLE_CLASSES}")
    return v


def _norm_shape_spec(name: str, v, fusable):
    """Validate the ops.yaml `shape:` spec id at load time (the
    _norm_fusable pattern): every fusable op must declare how its
    output aval follows from its inputs, and the id must name an
    evaluator analysis/shapes.py actually implements — otherwise the
    capture planner's abstract interpretation silently loses the op."""
    if v is None:
        if fusable:
            raise ValueError(
                f"ops.yaml: op {name!r} is marked fusable:{fusable!r} "
                f"but declares no `shape:` spec — the capture planner "
                f"cannot abstractly interpret it; pick one of "
                f"{SHAPE_SPECS}")
        return None
    if v not in SHAPE_SPECS:
        raise ValueError(
            f"ops.yaml: op {name!r} declares unknown shape spec "
            f"{v!r}; expected one of {SHAPE_SPECS}")
    return v


def _register_all():
    from .._native import lib
    for entry in _load_yaml():
        name = entry["name"]
        info = {
            "module": entry.get("module", ""),
            "nin": int(entry.get("nin", 1)),
            "nargs": int(entry.get("nargs", 1)),
            "has_vjp": bool(entry.get("vjp", True)),
            "spmd_rule": entry.get("spmd", ""),
            # variadic ops (concat/stack/einsum/...) dispatch one
            # positional per tensor: the arity gate skips the cap
            "variadic": bool(entry.get("variadic", False)),
            # lazy-eager fusion class (core/fusion.py): False (not
            # fusable), True (elementwise chain member), "reduce"
            # (reduction terminator), "epilogue" (contraction/epilogue
            # host), "attention" (analysis-plane-only: the eager DAG
            # never defers it, but the capture planner's abstract
            # interpreter reads its shape spec instead of treating
            # attention as an opaque boundary). Python-mirror-only —
            # the native descriptor layout predates the field
            "fusable": _norm_fusable(name, entry.get("fusable", False)),
        }
        # analysis-plane shape/dtype spec (see SHAPE_SPECS above):
        # validated against `fusable` so the two markers can't drift
        info["shape_spec"] = _norm_shape_spec(
            name, entry.get("shape"), info["fusable"])
        OP_TABLE[name] = info
        if lib is not None:
            lib.op_register(name, info["nin"], info["nargs"],
                            info["has_vjp"], info["spmd_rule"])


def get_op_info(name: str) -> Optional[dict]:
    """Descriptor for a registered op; prefers the native registry
    (KernelFactory analog), falling back to the Python mirror."""
    from .._native import lib
    mirror = OP_TABLE.get(name)
    if lib is not None:
        d = lib.op_lookup(name)
        if d is not None:
            # one shape regardless of backend: native descriptor merged
            # over the Python mirror (which carries e.g. 'module')
            return {**(mirror or {}), **d}
    return mirror


def list_ops():
    return sorted(OP_TABLE)


def num_ops() -> int:
    return len(OP_TABLE)


def dispatch_counts() -> Dict[str, int]:
    """Eager dispatches per op name since process start — apply_op's
    dispatch gate (core.autograd._op_gate) feeds this; the registry is on
    the hot path, not introspection-only."""
    from ..core.autograd import _op_gate_cache
    return {name: entry[1] for name, entry in _op_gate_cache.items()}


_register_all()
