"""Op-table loader: ops.yaml -> native C++ OpRegistry (+ Python mirror).

ref: the reference's build-time codegen consumes paddle/phi/ops/yaml/
ops.yaml to generate its C++ API/grad-nodes/bindings (SURVEY §2.1 codegen
suite row). Here the same single-source table populates the native
OpRegistry (kernel-dispatch metadata: arity, vjp, SPMD rule) at import —
kernels are traced XLA programs, so there is no C++ kernel body to
generate, only descriptors to serve dispatch and introspection.
"""
from __future__ import annotations

import os
from typing import Dict, Optional

__all__ = ["get_op_info", "list_ops", "num_ops", "OP_TABLE"]

_HERE = os.path.dirname(os.path.abspath(__file__))

OP_TABLE: Dict[str, dict] = {}


def _load_yaml() -> list:
    path = os.path.join(_HERE, "ops.yaml")
    with open(path) as f:
        text = f.read()
    try:
        import yaml
        return yaml.safe_load(text)["ops"]
    except ImportError:  # minimal fallback parser for our flat schema
        ops, cur = [], None
        for line in text.splitlines():
            s = line.strip()
            if s.startswith("#") or not s:
                continue
            if s.startswith("- name:"):
                cur = {"name": s.split(":", 1)[1].strip()}
                ops.append(cur)
            elif cur is not None and ":" in s and not s.startswith("ops"):
                k, v = s.split(":", 1)
                v = v.strip()
                cur[k.strip()] = (v == "true" if v in ("true", "false")
                                  else int(v) if v.isdigit() else v)
        return ops


def _register_all():
    from .._native import lib
    for entry in _load_yaml():
        name = entry["name"]
        info = {
            "module": entry.get("module", ""),
            "nin": int(entry.get("nin", 1)),
            "nargs": int(entry.get("nargs", 1)),
            "has_vjp": bool(entry.get("vjp", True)),
            "spmd_rule": entry.get("spmd", ""),
            # variadic ops (concat/stack/einsum/...) dispatch one
            # positional per tensor: the arity gate skips the cap
            "variadic": bool(entry.get("variadic", False)),
        }
        OP_TABLE[name] = info
        if lib is not None:
            lib.op_register(name, info["nin"], info["nargs"],
                            info["has_vjp"], info["spmd_rule"])


def get_op_info(name: str) -> Optional[dict]:
    """Descriptor for a registered op; prefers the native registry
    (KernelFactory analog), falling back to the Python mirror."""
    from .._native import lib
    mirror = OP_TABLE.get(name)
    if lib is not None:
        d = lib.op_lookup(name)
        if d is not None:
            # one shape regardless of backend: native descriptor merged
            # over the Python mirror (which carries e.g. 'module')
            return {**(mirror or {}), **d}
    return mirror


def list_ops():
    return sorted(OP_TABLE)


def num_ops() -> int:
    return len(OP_TABLE)


def dispatch_counts() -> Dict[str, int]:
    """Eager dispatches per op name since process start — apply_op's
    dispatch gate (core.autograd._op_gate) feeds this; the registry is on
    the hot path, not introspection-only."""
    from ..core.autograd import _op_gate_cache
    return {name: entry[1] for name, entry in _op_gate_cache.items()}


_register_all()
