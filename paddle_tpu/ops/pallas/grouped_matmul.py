"""Grouped (ragged) matmul, Pallas-on-TPU — the MoE expert-FFN kernel.

TPU-native replacement for the reference's CUTLASS grouped GEMM
(ref: paddle/phi/kernels/fusion/cutlass/fused_moe_kernel.cu) used by its
MoE layer (python/paddle/incubate/distributed/models/moe/moe_layer.py).

Contract (megablocks-style): tokens are pre-sorted by expert and each
expert's group is padded to a multiple of the token tile, so every token
tile belongs to exactly ONE expert. The expert id per tile rides in as a
scalar-prefetch operand; the BlockSpec index_map uses it to stream just
that expert's weight tile into VMEM — each tile is one dense MXU matmul,
no wasted FLOPs on other experts (the dense-dispatch fallback pays
O(E) per token instead).

grouped_matmul(lhs [T, K], rhs [E, K, N], group_sizes [E]) -> [T, N],
with rows of group e computed against rhs[e]. Rows beyond sum(group_sizes)
(padding) produce zeros.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    pl = None
    pltpu = None
    _HAS_PALLAS = False

__all__ = ["grouped_matmul", "grouped_matmul_reference",
           "tile_expert_ids"]


def grouped_matmul_reference(lhs, rhs, group_sizes):
    """Dense oracle: per-row expert id via cumsum, one-hot contraction.
    O(T*E*K*N) — correctness baseline only."""
    t = lhs.shape[0]
    e = rhs.shape[0]
    bounds = jnp.cumsum(group_sizes)
    row_expert = jnp.searchsorted(bounds, jnp.arange(t), side="right")
    valid = jnp.arange(t) < bounds[-1]
    oh = jax.nn.one_hot(row_expert, e, dtype=lhs.dtype)       # [T, E]
    out = jnp.einsum("tk,te,ekn->tn", lhs, oh, rhs)
    return out * valid[:, None].astype(lhs.dtype)


def tile_expert_ids(group_sizes, block_t: int, num_tiles: int):
    """Expert id per token tile, given tile-aligned group sizes
    (every group size must be a multiple of block_t)."""
    bounds = jnp.cumsum(group_sizes)
    starts = jnp.arange(num_tiles) * block_t
    return jnp.searchsorted(bounds, starts, side="right").astype(jnp.int32)


def _dot_precision(dtype):
    """Explicit contraction precision per operand dtype. Pinning matters
    twice over: (a) bf16 operands + an ambient fp32/HIGHEST matmul
    precision produce a tpu.matmul Mosaic rejects ("Bad lhs type") —
    bf16 runs the native single-pass MXU path with fp32 accumulation
    from preferred_element_type (measured 44 -> 24 ms on the MoE bench);
    (b) fp32 operands keep HIGHEST so true-fp32 callers don't silently
    drop to bf16 passes under an ambient DEFAULT."""
    return (jax.lax.Precision.HIGHEST if dtype == jnp.float32
            else jax.lax.Precision.DEFAULT)


def _gmm_kernel(ids_ref, lhs_ref, rhs_ref, out_ref, acc_ref, *,
                n_k_tiles):
    # one token tile x one (prefetch-selected) expert weight tile: plain
    # MXU dot in the operands' own dtype with fp32 accumulation in VMEM
    # scratch across the K tiles (K is tiled so block_t can be large —
    # big token tiles amortize the expert-weight streaming that
    # otherwise makes the kernel HBM-bound: measured 1.74 -> 0.91 ms
    # fwd at t=16K,k=1024,n=4096 going block_t 128 -> 512). Precision
    # keys on the PROMOTED dtype: a bf16 x fp32 call promotes to fp32,
    # which must not silently run single-pass bf16 multiplies.
    kk = pl.program_id(2)
    prec = _dot_precision(
        jnp.promote_types(lhs_ref.dtype, rhs_ref.dtype))
    contrib = jnp.dot(lhs_ref[...], rhs_ref[0], precision=prec,
                      preferred_element_type=jnp.float32)

    @pl.when(kk == 0)
    def _init():
        acc_ref[...] = contrib

    @pl.when(kk > 0)
    def _acc():
        acc_ref[...] += contrib

    @pl.when(kk == n_k_tiles - 1)
    def _emit():
        out_ref[...] = acc_ref[...].astype(out_ref.dtype)


def _gmm_drhs_kernel(ids_ref, lhs_ref, g_ref, out_ref):
    """drhs[e] = sum over e's token tiles of lhs_tileᵀ @ g_tile. The grid
    is (k_tile, n_tile, token_tile MINOR) so for fixed (k, n) tiles every
    token tile of one expert is consecutive — the output block stays
    resident in VMEM across those steps and accumulates. K tiling keeps
    the [block_t, block_k] lhs tile inside VMEM at large block_t."""
    i = pl.program_id(2)  # token tile (minor/fastest)
    is_first = (i == 0) | (ids_ref[i] != ids_ref[jnp.maximum(i - 1, 0)])
    # dot_general contracting on lhs axis 0 == lhsᵀ @ g without a
    # materialized in-kernel transpose (a bf16 tile transpose trips the
    # Mosaic compiler; contraction-dim choice is free on the MXU)
    contrib = jax.lax.dot_general(
        lhs_ref[...], g_ref[...], (((0,), (0,)), ((), ())),
        precision=_dot_precision(
            jnp.promote_types(lhs_ref.dtype, g_ref.dtype)),
        preferred_element_type=jnp.float32).astype(out_ref.dtype)

    @pl.when(is_first)
    def _init():
        out_ref[0] = contrib

    @pl.when(jnp.logical_not(is_first))
    def _acc():
        out_ref[0] += contrib


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _gmm_pallas(lhs, rhs, tile_ids, block_t):
    return _gmm_fwd_impl(lhs, rhs, tile_ids, block_t)


# empirical VMEM model (validated against the compiler's scoped-stack
# accounting at K=4096): ~3x the naive tile sum covers double buffering
# of every ref plus in-kernel f32 temporaries
_VMEM_WORDS = int(13.5 * 1024 * 1024) // 4  # fp32 words under the 16MB cap


def _pick_blocks(k: int, n: int, block_t: int):
    """(block_n, block_k) for the fwd kernel's working set — the
    [block_k, block_n] weight tile, [block_t, block_k] lhs tile,
    [block_t, block_n] out tile and the f32 accumulator — under the
    scoped VMEM limit. Prefers fat N tiles, then fat K tiles (fewer
    accumulation rounds)."""
    for bn in (512, 256, 128):
        if n % bn:
            continue
        for bk in (2048, 1024, 512, 256, 128):
            if k % bk:
                continue
            words = 3 * (bk * bn + block_t * bk + block_t * bn) \
                + block_t * bn
            if words <= _VMEM_WORDS:
                return bn, bk
    return (128 if n % 128 == 0 else n), (128 if k % 128 == 0 else k)


@functools.partial(jax.jit, static_argnames=("block_t",))
def _gmm_fwd_impl(lhs, rhs, tile_ids, block_t):
    t, k = lhs.shape
    e, _, n = rhs.shape
    block_n, block_k = _pick_blocks(k, n, block_t)
    n_k_tiles = k // block_k
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        # K minor: the f32 scratch accumulates across the K tiles of one
        # (token, n) output block before it is emitted
        grid=(t // block_t, n // block_n, n_k_tiles),
        in_specs=[
            pl.BlockSpec((block_t, block_k),
                         lambda i, j, kk, ids: (i, kk)),
            pl.BlockSpec((1, block_k, block_n),
                         lambda i, j, kk, ids: (ids[i], kk, j)),
        ],
        out_specs=pl.BlockSpec((block_t, block_n),
                               lambda i, j, kk, ids: (i, j)),
        scratch_shapes=[pltpu.VMEM((block_t, block_n), jnp.float32)],
    )
    return pl.pallas_call(
        functools.partial(_gmm_kernel, n_k_tiles=n_k_tiles),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((t, n), lhs.dtype),
    )(tile_ids, lhs, rhs)


@functools.partial(jax.jit, static_argnames=("e", "block_t"))
def _gmm_drhs_impl(lhs, g, tile_ids, e, block_t):
    t, k = lhs.shape
    n = g.shape[1]
    block_n, block_k = _pick_blocks(k, n, block_t)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        # token tiles MINOR: see kernel docstring (VMEM-resident
        # accumulation over each expert's consecutive token tiles)
        grid=(k // block_k, n // block_n, t // block_t),
        in_specs=[
            pl.BlockSpec((block_t, block_k),
                         lambda kk, j, i, ids: (i, kk)),
            pl.BlockSpec((block_t, block_n),
                         lambda kk, j, i, ids: (i, j)),
        ],
        out_specs=pl.BlockSpec((1, block_k, block_n),
                               lambda kk, j, i, ids: (ids[i], kk, j)),
    )
    out = pl.pallas_call(
        _gmm_drhs_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((e, k, n), jnp.float32),
    )(tile_ids, lhs, g)
    # experts with no tiles never get written: mask whatever VMEM held
    present = jnp.zeros((e,), bool).at[tile_ids].set(True)
    return jnp.where(present[:, None, None], out, 0.0)


def _gmm_vjp_fwd(lhs, rhs, tile_ids, block_t):
    return _gmm_fwd_impl(lhs, rhs, tile_ids, block_t), (lhs, rhs, tile_ids)


def _gmm_vjp_bwd(block_t, res, g):
    lhs, rhs, tile_ids = res
    dlhs = _gmm_fwd_impl(g, jnp.swapaxes(rhs, 1, 2), tile_ids, block_t)
    drhs = _gmm_drhs_impl(lhs, g, tile_ids, rhs.shape[0], block_t)
    return dlhs.astype(lhs.dtype), drhs.astype(rhs.dtype), None


_gmm_pallas.defvjp(_gmm_vjp_fwd, _gmm_vjp_bwd)


def _use_pallas(t, k, n, block_t) -> bool:
    return (_HAS_PALLAS and jax.default_backend() in ("tpu", "axon")
            and t % block_t == 0 and k % 128 == 0 and n % 128 == 0)


def grouped_matmul(lhs, rhs, group_sizes, block_t: int = 128,
                   tile_ids: Optional[jax.Array] = None):
    """Ragged matmul over tile-aligned groups (see module docstring).

    When group sizes are not tile-aligned or Pallas is unavailable, falls
    back to the dense reference (correct, slower). ``tile_ids`` may be
    passed when the caller already knows the per-tile expert map (e.g. the
    fixed-capacity MoE layout where every group is exactly C rows).
    ``tile_ids`` MUST be non-decreasing: the dRHS backward accumulates
    into one resident VMEM block per expert and decides init-vs-accumulate
    by comparing adjacent ids, so a non-sorted map would silently produce
    wrong weight gradients (forward would still be right).
    """
    t, k = lhs.shape
    e, k2, n = rhs.shape
    if k2 != k:
        raise ValueError(f"lhs K {k} != rhs K {k2}")
    if tile_ids is not None and not isinstance(tile_ids, jax.core.Tracer):
        ids_np = np.asarray(tile_ids)
        if (np.diff(ids_np) < 0).any():
            raise ValueError(
                "grouped_matmul tile_ids must be non-decreasing (tokens "
                "pre-sorted by expert): the dRHS backward accumulates "
                "per-expert output tiles in VMEM and a scattered map "
                "yields wrong weight grads. Sort tokens by expert or use "
                "grouped_matmul_reference.")
    if not _use_pallas(t, k, n, block_t):
        return grouped_matmul_reference(lhs, rhs, group_sizes)
    if tile_ids is None:
        # group sizes must be tile-aligned (and concrete) for the
        # one-expert-per-tile contract; otherwise use the dense fallback
        if isinstance(group_sizes, jax.core.Tracer):
            return grouped_matmul_reference(lhs, rhs, group_sizes)
        sizes = np.asarray(group_sizes)
        if (sizes % block_t != 0).any():
            return grouped_matmul_reference(lhs, rhs, jnp.asarray(sizes))
        tile_ids = tile_expert_ids(jnp.asarray(sizes), block_t,
                                   t // block_t)
        total = int(sizes.sum())
        if total < t:
            # padding tiles get expert id E (clamped to the last expert by
            # the BlockSpec index_map) — zero them to honor the contract
            out = _gmm_pallas(lhs, rhs, jnp.minimum(tile_ids, e - 1),
                              block_t)
            valid = (jnp.arange(t) < total)[:, None]
            return out * valid.astype(out.dtype)
    return _gmm_pallas(lhs, rhs, tile_ids, block_t)
