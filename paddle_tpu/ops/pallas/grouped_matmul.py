"""Grouped (ragged) matmul, Pallas-on-TPU — the MoE expert-FFN kernel.

TPU-native replacement for the reference's CUTLASS grouped GEMM
(ref: paddle/phi/kernels/fusion/cutlass/fused_moe_kernel.cu) used by its
MoE layer (python/paddle/incubate/distributed/models/moe/moe_layer.py).

Contract (megablocks-style): tokens are pre-sorted by expert and each
expert's group is padded to a multiple of the token tile, so every token
tile belongs to exactly ONE expert. The expert id per tile rides in as a
scalar-prefetch operand; the BlockSpec index_map uses it to stream just
that expert's weight tile into VMEM — each tile is one dense MXU matmul,
no wasted FLOPs on other experts (the dense-dispatch fallback pays
O(E) per token instead).

grouped_matmul(lhs [T, K], rhs [E, K, N], group_sizes [E]) -> [T, N],
with rows of group e computed against rhs[e]. Rows beyond sum(group_sizes)
(padding) produce zeros.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    pl = None
    pltpu = None
    _HAS_PALLAS = False

__all__ = ["grouped_matmul", "grouped_matmul_reference",
           "tile_expert_ids"]


def grouped_matmul_reference(lhs, rhs, group_sizes):
    """Dense oracle: per-row expert id via cumsum, one-hot contraction.
    O(T*E*K*N) — correctness baseline only."""
    t = lhs.shape[0]
    e = rhs.shape[0]
    bounds = jnp.cumsum(group_sizes)
    row_expert = jnp.searchsorted(bounds, jnp.arange(t), side="right")
    valid = jnp.arange(t) < bounds[-1]
    oh = jax.nn.one_hot(row_expert, e, dtype=lhs.dtype)       # [T, E]
    out = jnp.einsum("tk,te,ekn->tn", lhs, oh, rhs)
    return out * valid[:, None].astype(lhs.dtype)


def tile_expert_ids(group_sizes, block_t: int, num_tiles: int):
    """Expert id per token tile, given tile-aligned group sizes
    (every group size must be a multiple of block_t)."""
    bounds = jnp.cumsum(group_sizes)
    starts = jnp.arange(num_tiles) * block_t
    return jnp.searchsorted(bounds, starts, side="right").astype(jnp.int32)


def _dot_precision(dtype):
    """Explicit contraction precision per operand dtype. Pinning matters
    twice over: (a) bf16 operands + an ambient fp32/HIGHEST matmul
    precision produce a tpu.matmul Mosaic rejects ("Bad lhs type") —
    bf16 runs the native single-pass MXU path with fp32 accumulation
    from preferred_element_type (measured 44 -> 24 ms on the MoE bench);
    (b) fp32 operands keep HIGHEST so true-fp32 callers don't silently
    drop to bf16 passes under an ambient DEFAULT."""
    return (jax.lax.Precision.HIGHEST if dtype == jnp.float32
            else jax.lax.Precision.DEFAULT)


def _gmm_kernel(ids_ref, lhs_ref, rhs_ref, out_ref):
    # one token tile x one (prefetch-selected) expert weight: plain MXU
    # dot in the operands' own dtype with fp32 accumulation. Precision
    # keys on the PROMOTED dtype: a bf16 x fp32 call promotes to fp32,
    # which must not silently run single-pass bf16 multiplies.
    prec = _dot_precision(
        jnp.promote_types(lhs_ref.dtype, rhs_ref.dtype))
    out_ref[...] = jnp.dot(
        lhs_ref[...], rhs_ref[0], precision=prec,
        preferred_element_type=jnp.float32).astype(out_ref.dtype)


def _gmm_drhs_kernel(ids_ref, lhs_ref, g_ref, out_ref):
    """drhs[e] = sum over e's token tiles of lhs_tileᵀ @ g_tile. The grid
    is (n_tile MAJOR, token_tile minor) so for a fixed n tile every
    token tile of one expert is consecutive — the output block stays
    resident in VMEM across those steps and accumulates."""
    i = pl.program_id(1)  # token tile (minor/fastest)
    is_first = (i == 0) | (ids_ref[i] != ids_ref[jnp.maximum(i - 1, 0)])
    # dot_general contracting on lhs axis 0 == lhsᵀ @ g without a
    # materialized in-kernel transpose (a bf16 tile transpose trips the
    # Mosaic compiler; contraction-dim choice is free on the MXU)
    contrib = jax.lax.dot_general(
        lhs_ref[...], g_ref[...], (((0,), (0,)), ((), ())),
        precision=_dot_precision(
            jnp.promote_types(lhs_ref.dtype, g_ref.dtype)),
        preferred_element_type=jnp.float32).astype(out_ref.dtype)

    @pl.when(is_first)
    def _init():
        out_ref[0] = contrib

    @pl.when(jnp.logical_not(is_first))
    def _acc():
        out_ref[0] += contrib


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _gmm_pallas(lhs, rhs, tile_ids, block_t):
    return _gmm_fwd_impl(lhs, rhs, tile_ids, block_t)


def _pick_block_n(n: int, k: int, block_t: int) -> int:
    """Tile the output/N dim so the working set — the [1, K, block_n]
    weight tile (double-buffered), the [block_t, K] lhs tile, and the
    [block_t, block_n] out tile — fits the ~16MB scoped VMEM limit (a
    full [1, K, N] tile blows it at real FFN widths)."""
    # empirical model (validated against the compiler's scoped-stack
    # accounting at K=4096): ~3x the naive tile sum covers double
    # buffering of every ref plus in-kernel f32 temporaries
    budget = int(13.5 * 1024 * 1024) // 4  # fp32 words under the 16MB cap
    for b in (512, 256, 128):
        if n % b == 0 and \
                3 * (k * b + block_t * k + block_t * b) <= budget:
            return b
    return 128 if n % 128 == 0 else n


@functools.partial(jax.jit, static_argnames=("block_t",))
def _gmm_fwd_impl(lhs, rhs, tile_ids, block_t):
    t, k = lhs.shape
    e, _, n = rhs.shape
    block_n = _pick_block_n(n, k, block_t)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(t // block_t, n // block_n),
        in_specs=[
            pl.BlockSpec((block_t, k), lambda i, j, ids: (i, 0)),
            pl.BlockSpec((1, k, block_n), lambda i, j, ids: (ids[i], 0, j)),
        ],
        out_specs=pl.BlockSpec((block_t, block_n),
                               lambda i, j, ids: (i, j)),
    )
    return pl.pallas_call(
        _gmm_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((t, n), lhs.dtype),
    )(tile_ids, lhs, rhs)


@functools.partial(jax.jit, static_argnames=("e", "block_t"))
def _gmm_drhs_impl(lhs, g, tile_ids, e, block_t):
    t, k = lhs.shape
    n = g.shape[1]
    block_n = _pick_block_n(n, k, block_t)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n // block_n, t // block_t),  # n MAJOR: see kernel docstring
        in_specs=[
            pl.BlockSpec((block_t, k), lambda j, i, ids: (i, 0)),
            pl.BlockSpec((block_t, block_n), lambda j, i, ids: (i, j)),
        ],
        out_specs=pl.BlockSpec((1, k, block_n),
                               lambda j, i, ids: (ids[i], 0, j)),
    )
    out = pl.pallas_call(
        _gmm_drhs_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((e, k, n), jnp.float32),
    )(tile_ids, lhs, g)
    # experts with no tiles never get written: mask whatever VMEM held
    present = jnp.zeros((e,), bool).at[tile_ids].set(True)
    return jnp.where(present[:, None, None], out, 0.0)


def _gmm_vjp_fwd(lhs, rhs, tile_ids, block_t):
    return _gmm_fwd_impl(lhs, rhs, tile_ids, block_t), (lhs, rhs, tile_ids)


def _gmm_vjp_bwd(block_t, res, g):
    lhs, rhs, tile_ids = res
    dlhs = _gmm_fwd_impl(g, jnp.swapaxes(rhs, 1, 2), tile_ids, block_t)
    drhs = _gmm_drhs_impl(lhs, g, tile_ids, rhs.shape[0], block_t)
    return dlhs.astype(lhs.dtype), drhs.astype(rhs.dtype), None


_gmm_pallas.defvjp(_gmm_vjp_fwd, _gmm_vjp_bwd)


def _use_pallas(t, k, n, block_t) -> bool:
    return (_HAS_PALLAS and jax.default_backend() in ("tpu", "axon")
            and t % block_t == 0 and k % 128 == 0 and n % 128 == 0)


def grouped_matmul(lhs, rhs, group_sizes, block_t: int = 128,
                   tile_ids: Optional[jax.Array] = None):
    """Ragged matmul over tile-aligned groups (see module docstring).

    When group sizes are not tile-aligned or Pallas is unavailable, falls
    back to the dense reference (correct, slower). ``tile_ids`` may be
    passed when the caller already knows the per-tile expert map (e.g. the
    fixed-capacity MoE layout where every group is exactly C rows).
    ``tile_ids`` MUST be non-decreasing: the dRHS backward accumulates
    into one resident VMEM block per expert and decides init-vs-accumulate
    by comparing adjacent ids, so a non-sorted map would silently produce
    wrong weight gradients (forward would still be right).
    """
    t, k = lhs.shape
    e, k2, n = rhs.shape
    if k2 != k:
        raise ValueError(f"lhs K {k} != rhs K {k2}")
    if tile_ids is not None and not isinstance(tile_ids, jax.core.Tracer):
        ids_np = np.asarray(tile_ids)
        if (np.diff(ids_np) < 0).any():
            raise ValueError(
                "grouped_matmul tile_ids must be non-decreasing (tokens "
                "pre-sorted by expert): the dRHS backward accumulates "
                "per-expert output tiles in VMEM and a scattered map "
                "yields wrong weight grads. Sort tokens by expert or use "
                "grouped_matmul_reference.")
    if not _use_pallas(t, k, n, block_t):
        return grouped_matmul_reference(lhs, rhs, group_sizes)
    if tile_ids is None:
        # group sizes must be tile-aligned (and concrete) for the
        # one-expert-per-tile contract; otherwise use the dense fallback
        if isinstance(group_sizes, jax.core.Tracer):
            return grouped_matmul_reference(lhs, rhs, group_sizes)
        sizes = np.asarray(group_sizes)
        if (sizes % block_t != 0).any():
            return grouped_matmul_reference(lhs, rhs, jnp.asarray(sizes))
        tile_ids = tile_expert_ids(jnp.asarray(sizes), block_t,
                                   t // block_t)
        total = int(sizes.sum())
        if total < t:
            # padding tiles get expert id E (clamped to the last expert by
            # the BlockSpec index_map) — zero them to honor the contract
            out = _gmm_pallas(lhs, rhs, jnp.minimum(tile_ids, e - 1),
                              block_t)
            valid = (jnp.arange(t) < total)[:, None]
            return out * valid.astype(out.dtype)
    return _gmm_pallas(lhs, rhs, tile_ids, block_t)
