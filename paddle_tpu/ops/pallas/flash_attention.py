"""Flash attention, Pallas-on-TPU — forward AND backward kernels.

TPU-native replacement for the reference's flash-attention wrapper
(ref: paddle/phi/kernels/gpu/flash_attn_kernel.cu fwd +
flash_attn_grad_kernel.cu bwd, which call the vendored third_party/flashattn
CUDA lib). Design: online-softmax tiling over the KV sequence so logits
never materialize in HBM, with block sizes aligned to the MXU (128).

Forward emits the per-row logsumexp; backward uses the standard two-kernel
flash recipe — a dq kernel tiled over Q blocks and a dk/dv kernel tiled
over KV blocks, both re-computing P from (q, k, lse) so memory stays
O(L·D) instead of O(L²). Falls back to a recompute-based XLA VJP when
Pallas is unavailable (CPU mesh tests) or shapes don't tile.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

__all__ = ["flash_attention_fwd", "flash_attention",
           "flash_attention_segmented"]

_NEG_INF = -1e30


def _sdpa_xla(q, k, v, causal=False, scale=None, mask=None,
              dropout_p=0.0, seed=None, dropout_key=None):
    """Numeric oracle, layout [B, L, H, D]. `mask` is additive, broadcast
    against [B, H, Lq, Lk] logits. Handles Lq < Lk (KV-cache decode) by
    offsetting the causal diagonal. Dropout is deterministic given
    ``seed`` (or an explicit ``dropout_key``) so the VJP fallback can
    replay the identical mask. This is THE reference oracle —
    nn.functional's _sdpa_reference delegates here."""
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    if dropout_p >= 1.0:
        # everything dropped: zeros with zero (not NaN) gradients — the
        # 1/(1-p) rescale below would divide by zero
        return jnp.zeros_like(q)
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    logits = jnp.einsum("bhqd,bhkd->bhqk", qt, kt).astype(jnp.float32) * s
    if causal:
        ql, kl = logits.shape[-2], logits.shape[-1]
        cm = jnp.tril(jnp.ones((ql, kl), bool), k=kl - ql)
        logits = jnp.where(cm, logits, _NEG_INF)
    if mask is not None:
        logits = logits + mask
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    if dropout_p > 0.0:
        if dropout_key is None and seed is not None:
            dropout_key = jax.random.PRNGKey(jnp.asarray(seed).reshape(()))
        if dropout_key is not None:
            keep = jax.random.bernoulli(dropout_key, 1.0 - dropout_p,
                                        probs.shape)
            probs = jnp.where(keep, probs / (1.0 - dropout_p),
                              0.0).astype(probs.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vt)
    return jnp.swapaxes(out, 1, 2)


try:  # Pallas import is deferred-safe: CPU wheels ship it but TPU lowering
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    pl = None
    pltpu = None
    _HAS_PALLAS = False


def _keep_mask(seed_ref, b, qi, ki, block_q, block_k, seq_len, dropout_p):
    """Deterministic per-tile dropout keep-mask. Seeding with the
    (seed, batch-head, q-tile, k-tile) tuple makes the mask a pure
    function of absolute tile position, so forward and both backward
    kernels regenerate identical bits regardless of their grid order
    (ref: the flash_attn CUDA kernels thread a philox offset the same
    way, paddle/phi/kernels/gpu/flash_attn_kernel.cu seed/offset args).
    Mosaic caps prng_seed at 2 values, so the tile coordinate folds into
    one int32 — injective because qi < L/block_q and ki < L/block_k."""
    nq = seq_len // block_q
    nk = seq_len // block_k
    tile = (b * nq + qi) * nk + ki
    pltpu.prng_seed(seed_ref[0], tile)
    bits = pltpu.prng_random_bits((block_q, block_k))
    bits = jax.lax.bitcast_convert_type(bits, jnp.uint32)
    thresh = jnp.uint32(min(int(dropout_p * (2 ** 32)), 2 ** 32 - 1))
    return bits >= thresh


# ---------------------------------------------------------------------------
# forward kernel: one (batch*head, q-block) program; inner loop tiles KV
# with online softmax; also emits logsumexp for the backward pass
# ---------------------------------------------------------------------------
def _fwd_kernel(*refs, block_q, block_k, seq_len, causal, scale,
                segmented=False, dropout_p=0.0, fold_bh=False):
    if dropout_p > 0.0:
        seed_ref, *refs = refs
    else:
        seed_ref = None
    q_ref, k_ref, v_ref, *rest = refs
    if segmented:
        seg_ref, o_ref, lse_ref = rest
    else:
        seg_ref = None
        o_ref, lse_ref = rest
    if fold_bh:
        # layout-native path: grid (b, h, i) over [B, L, H*D] arrays;
        # (b, h) folds into one id so the dropout tile seed stays unique
        # across heads. Data blocks look identical to the [BH, L, D]
        # path; only lse rides in [B, H, L, 1].
        b = pl.program_id(0) * pl.num_programs(1) + pl.program_id(1)
        qi = pl.program_id(2)
    else:
        b = pl.program_id(0)
        qi = pl.program_id(1)
    # operands stay in their native dtype (bf16 on the bench path) for
    # every MXU dot, with f32 accumulation via preferred_element_type —
    # f32 multiplies run the MXU at a fraction of bf16 rate (measured
    # on v5e at the BERT d=64 geometry: fwd kernel 1.12 -> 0.64 ms,
    # bwd pair 2.9 -> 1.5 ms per layer); softmax statistics stay f32
    q = q_ref[0]  # [block_q, d]

    m = jnp.full((block_q, 1), _NEG_INF, jnp.float32)
    l = jnp.zeros((block_q, 1), jnp.float32)
    acc = jnp.zeros((block_q, q.shape[-1]), jnp.float32)

    q_offset = qi * block_q
    num_k_blocks = seq_len // block_k
    if causal:
        num_k_blocks_eff = (q_offset + block_q + block_k - 1) // block_k
    else:
        num_k_blocks_eff = num_k_blocks
    if segmented:
        seg_q = seg_ref[0, pl.ds(q_offset, block_q), :]  # [block_q, 1]

    def body(ki, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(ki * block_k, block_k), :]
        v_blk = v_ref[0, pl.ds(ki * block_k, block_k), :]
        logits = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            precision=_prec(q, k_blk),
            preferred_element_type=jnp.float32) * scale
        if causal:
            q_ids = q_offset + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_ids = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            logits = jnp.where(q_ids >= k_ids, logits, _NEG_INF)
        if segmented:
            # varlen packing: tokens attend within their segment only
            seg_k = seg_ref[0, pl.ds(ki * block_k, block_k), :]
            logits = jnp.where(seg_q == seg_k.reshape(1, block_k),
                               logits, _NEG_INF)
        m_new = jnp.maximum(m, logits.max(axis=-1, keepdims=True))
        p = jnp.exp(logits - m_new)
        alpha = jnp.exp(m - m_new)
        # softmax statistics (l, lse) use the UNdropped probabilities;
        # dropout zeroes entries of the numerator only — dividing by the
        # full l afterwards is exactly dropout(softmax(s)) since the
        # normalization is linear
        l_new = alpha * l + p.sum(axis=-1, keepdims=True)
        if dropout_p > 0.0:
            keep = _keep_mask(seed_ref, b, qi, ki, block_q, block_k,
                              seq_len, dropout_p)
            p = jnp.where(keep, p, 0.0)
        acc_new = alpha * acc + jax.lax.dot(
            p.astype(v_blk.dtype), v_blk, precision=_prec(v_blk),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, num_k_blocks_eff, body, (m, l, acc))
    if dropout_p > 0.0:
        acc = acc * (1.0 / (1.0 - dropout_p))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
    lse_val = m + jnp.log(jnp.maximum(l, 1e-30))
    if fold_bh:
        lse_ref[0, 0] = lse_val  # [B, H, L, 1] block (1, 1, block_q, 1)
    else:
        lse_ref[0] = lse_val


def _prec(*operands):
    """Explicit contraction precision: bf16 operands must run DEFAULT
    (the native single-pass MXU path — an ambient fp32/HIGHEST precision
    produces a tpu.matmul Mosaic rejects with 'Bad lhs type'), f32
    operands keep HIGHEST. One rule for every Pallas kernel: shared
    with grouped_matmul."""
    from .grouped_matmul import _dot_precision
    dt = operands[0].dtype
    for o in operands[1:]:
        dt = jnp.promote_types(dt, o.dtype)
    return _dot_precision(dt)


# ---------------------------------------------------------------------------
# backward kernels (standard flash bwd algebra):
#   P  = exp(scale·QKᵀ − lse)          (recomputed per tile)
#   dV = Pᵀ @ dO
#   dS = P ∘ (dO @ Vᵀ − Δ) · scale     with Δ = rowsum(dO ∘ O)
#   dQ = dS @ K ;  dK = dSᵀ @ Q
# ---------------------------------------------------------------------------
def _bwd_dq_kernel(*refs, block_q, block_k, seq_len, causal, scale,
                   segmented=False, dropout_p=0.0, fold_bh=False):
    if dropout_p > 0.0:
        seed_ref, *refs = refs
    else:
        seed_ref = None
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest = refs
    if segmented:
        seg_ref, dq_ref = rest
    else:
        seg_ref = None
        (dq_ref,) = rest
    if fold_bh:
        b = pl.program_id(0) * pl.num_programs(1) + pl.program_id(1)
        qi = pl.program_id(2)
        lse = lse_ref[0, 0]      # [block_q, 1]
        delta = delta_ref[0, 0]  # [block_q, 1]
    else:
        b = pl.program_id(0)
        qi = pl.program_id(1)
        lse = lse_ref[0]      # [block_q, 1]
        delta = delta_ref[0]  # [block_q, 1]
    q = q_ref[0]   # native dtype: MXU dots run bf16 with f32 acc
    do = do_ref[0]
    q_offset = qi * block_q
    if causal:
        num_k_blocks_eff = (q_offset + block_q + block_k - 1) // block_k
    else:
        num_k_blocks_eff = seq_len // block_k
    if segmented:
        seg_q = seg_ref[0, pl.ds(q_offset, block_q), :]

    def body(ki, dq):
        k_blk = k_ref[0, pl.ds(ki * block_k, block_k), :]
        v_blk = v_ref[0, pl.ds(ki * block_k, block_k), :]
        s = scale * jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            precision=_prec(q, k_blk),
            preferred_element_type=jnp.float32)
        p = jnp.exp(s - lse)
        if causal:
            q_ids = q_offset + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_ids = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            p = jnp.where(q_ids >= k_ids, p, 0.0)
        if segmented:
            seg_k = seg_ref[0, pl.ds(ki * block_k, block_k), :]
            p = jnp.where(seg_q == seg_k.reshape(1, block_k), p, 0.0)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            precision=_prec(do, v_blk),
            preferred_element_type=jnp.float32)
        if dropout_p > 0.0:
            # dS = P ∘ (M∘dP_d/(1−p) − Δ): Δ = rowsum(dO∘O) already
            # equals Σ_k P_d·dP_d, so only the dp term needs the mask
            keep = _keep_mask(seed_ref, b, qi, ki, block_q, block_k,
                              seq_len, dropout_p)
            dp = jnp.where(keep, dp, 0.0) * (1.0 / (1.0 - dropout_p))
        ds = p * (dp - delta) * scale
        return dq + jax.lax.dot(
            ds.astype(k_blk.dtype), k_blk, precision=_prec(k_blk),
            preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(
        0, num_k_blocks_eff, body,
        jnp.zeros((block_q, q.shape[-1]), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(*refs, block_q, block_k, seq_len, causal,
                    scale, segmented=False, dropout_p=0.0,
                    fold_bh=False):
    if dropout_p > 0.0:
        seed_ref, *refs = refs
    else:
        seed_ref = None
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest = refs
    if segmented:
        seg_ref, dk_ref, dv_ref = rest
    else:
        seg_ref = None
        dk_ref, dv_ref = rest
    if fold_bh:
        b = pl.program_id(0) * pl.num_programs(1) + pl.program_id(1)
        ki = pl.program_id(2)
    else:
        b = pl.program_id(0)
        ki = pl.program_id(1)
    k_blk = k_ref[0]      # [block_k, d] native dtype (bf16 MXU dots)
    v_blk = v_ref[0]
    k_offset = ki * block_k
    num_q_blocks = seq_len // block_q
    # causal: only q blocks at or after this kv block contribute
    q_start = k_offset // block_q if causal else 0
    if segmented:
        seg_k = seg_ref[0, pl.ds(k_offset, block_k), :]

    def body(qi, carry):
        dk, dv = carry
        q_blk = q_ref[0, pl.ds(qi * block_q, block_q), :]
        do_blk = do_ref[0, pl.ds(qi * block_q, block_q), :]
        if fold_bh:
            lse = lse_ref[0, 0, pl.ds(qi * block_q, block_q), :]
            delta = delta_ref[0, 0, pl.ds(qi * block_q, block_q), :]
        else:
            lse = lse_ref[0, pl.ds(qi * block_q, block_q), :]
            delta = delta_ref[0, pl.ds(qi * block_q, block_q), :]
        s = scale * jax.lax.dot_general(
            q_blk, k_blk, (((1,), (1,)), ((), ())),
            precision=_prec(q_blk, k_blk),
            preferred_element_type=jnp.float32)  # [block_q, block_k]
        p = jnp.exp(s - lse)
        if causal:
            q_ids = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_ids = k_offset + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            p = jnp.where(q_ids >= k_ids, p, 0.0)
        if segmented:
            seg_q = seg_ref[0, pl.ds(qi * block_q, block_q), :]
            p = jnp.where(seg_q == seg_k.reshape(1, block_k), p, 0.0)
        dp = jax.lax.dot_general(
            do_blk, v_blk, (((1,), (1,)), ((), ())),
            precision=_prec(do_blk, v_blk),
            preferred_element_type=jnp.float32)
        if dropout_p > 0.0:
            # same (seed, b, qi, ki) tuple as fwd/dq — identical mask
            # despite this kernel's transposed grid order
            keep = _keep_mask(seed_ref, b, qi, ki, block_q, block_k,
                              seq_len, dropout_p)
            inv = 1.0 / (1.0 - dropout_p)
            p_d = jnp.where(keep, p, 0.0) * inv   # dropped P for dV
            dp = jnp.where(keep, dp, 0.0) * inv
        else:
            p_d = p
        # contracting dim 0 == transposed-operand dot without an
        # in-kernel transpose (free on the MXU)
        dv_new = dv + jax.lax.dot_general(
            p_d.astype(do_blk.dtype), do_blk, (((0,), (0,)), ((), ())),
            precision=_prec(do_blk),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk_new = dk + jax.lax.dot_general(
            ds.astype(q_blk.dtype), q_blk, (((0,), (0,)), ((), ())),
            precision=_prec(q_blk),
            preferred_element_type=jnp.float32)
        return dk_new, dv_new

    dk, dv = jax.lax.fori_loop(
        q_start, num_q_blocks, body,
        (jnp.zeros((block_k, k_blk.shape[-1]), jnp.float32),
         jnp.zeros((block_k, v_blk.shape[-1]), jnp.float32)))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


@functools.partial(jax.jit, static_argnames=("causal", "scale", "block_q",
                                             "block_k", "dropout_p"))
def _flash_fwd_pallas(q, k, v, causal, scale, block_q=256, block_k=256,
                      dropout_p=0.0, seed=None):
    """q,k,v: [BH, L, D] -> (out [BH, L, D], lse [BH, L]).
    ``seed``: (1,) int32 SMEM scalar, required when dropout_p > 0 —
    dropout masks are regenerated from it in the backward kernels."""
    bh, seq_len, d = q.shape
    grid = (bh, seq_len // block_q)
    kernel = functools.partial(
        _fwd_kernel, block_q=block_q, block_k=block_k, seq_len=seq_len,
        causal=causal, scale=scale, dropout_p=dropout_p)
    seed_specs = ([pl.BlockSpec(memory_space=pltpu.SMEM)]
                  if dropout_p > 0.0 else [])
    seed_args = (seed,) if dropout_p > 0.0 else ()
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=seed_specs + [
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, seq_len, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, seq_len, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, seq_len, d), q.dtype),
            jax.ShapeDtypeStruct((bh, seq_len, 1), jnp.float32),
        ],
    )(*seed_args, q, k, v)


@functools.partial(jax.jit, static_argnames=("causal", "scale", "block_q",
                                             "block_k", "dropout_p"))
def _flash_bwd_pallas(q, k, v, out, lse, do, causal, scale, block_q=256,
                      block_k=256, dropout_p=0.0, seed=None):
    """[BH, L, D] residuals + dO -> (dq, dk, dv)."""
    bh, seq_len, d = q.shape
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)  # [BH, L, 1]
    seed_specs = ([pl.BlockSpec(memory_space=pltpu.SMEM)]
                  if dropout_p > 0.0 else [])
    seed_args = (seed,) if dropout_p > 0.0 else ()

    dq_kernel = functools.partial(
        _bwd_dq_kernel, block_q=block_q, block_k=block_k, seq_len=seq_len,
        causal=causal, scale=scale, dropout_p=dropout_p)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(bh, seq_len // block_q),
        in_specs=seed_specs + [
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, seq_len, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, seq_len, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, seq_len, d), q.dtype),
    )(*seed_args, q, k, v, do, lse, delta)

    dkv_kernel = functools.partial(
        _bwd_dkv_kernel, block_q=block_q, block_k=block_k, seq_len=seq_len,
        causal=causal, scale=scale, dropout_p=dropout_p)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(bh, seq_len // block_k),
        in_specs=seed_specs + [
            pl.BlockSpec((1, seq_len, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, seq_len, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, seq_len, 1), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, seq_len, 1), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, seq_len, d), k.dtype),
            jax.ShapeDtypeStruct((bh, seq_len, d), v.dtype),
        ],
    )(*seed_args, q, k, v, do, lse, delta)
    return dq, dk, dv


@functools.partial(jax.jit, static_argnames=("causal", "scale", "block_q",
                                             "block_k", "dropout_p"))
def _flash_fwd_pallas_blhd(q, k, v, causal, scale, block_q=256,
                           block_k=256, dropout_p=0.0, seed=None):
    """[B, L, H, D] layout-native forward: arrays are viewed as
    [B, L, H*D] (a free minor-dim reshape) and the grid walks (batch,
    head, q-block) with the head selecting a d-wide block of the last
    dim — the kernel consumes the model's own activation layout, so the
    physical [B,H,L,D] transpose copies disappear (measured ~10 ms/step
    of pure copy time at the 1.17B Llama bench geometry). Requires
    d % 128 == 0 (Mosaic block constraint); lse comes back [B, H, L, 1].
    """
    b, seq_len, h, d = q.shape
    qf, kf, vf = (x.reshape(b, seq_len, h * d) for x in (q, k, v))
    grid = (b, h, seq_len // block_q)
    kernel = functools.partial(
        _fwd_kernel, block_q=block_q, block_k=block_k, seq_len=seq_len,
        causal=causal, scale=scale, dropout_p=dropout_p, fold_bh=True)
    seed_specs = ([pl.BlockSpec(memory_space=pltpu.SMEM)]
                  if dropout_p > 0.0 else [])
    seed_args = (seed,) if dropout_p > 0.0 else ()
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=seed_specs + [
            pl.BlockSpec((1, block_q, d), lambda b, h, i: (b, i, h)),
            pl.BlockSpec((1, seq_len, d), lambda b, h, i: (b, 0, h)),
            pl.BlockSpec((1, seq_len, d), lambda b, h, i: (b, 0, h)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, h, i: (b, i, h)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda b, h, i: (b, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, seq_len, h * d), q.dtype),
            jax.ShapeDtypeStruct((b, h, seq_len, 1), jnp.float32),
        ],
    )(*seed_args, qf, kf, vf)
    return out.reshape(b, seq_len, h, d), lse


@functools.partial(jax.jit, static_argnames=("causal", "scale", "block_q",
                                             "block_k", "dropout_p"))
def _flash_bwd_pallas_blhd(q, k, v, out, lse, do, causal, scale,
                           block_q=256, block_k=256, dropout_p=0.0,
                           seed=None):
    """[B, L, H, D] residuals + dO -> (dq, dk, dv) in [B, L, H, D];
    lse/delta ride in [B, H, L, 1] (tiny, cheap to transpose)."""
    b, seq_len, h, d = q.shape
    delta = jnp.transpose(
        jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                axis=-1),
        (0, 2, 1))[..., None]  # [B, H, L, 1]
    qf, kf, vf, dof = (x.reshape(b, seq_len, h * d)
                       for x in (q, k, v, do))
    seed_specs = ([pl.BlockSpec(memory_space=pltpu.SMEM)]
                  if dropout_p > 0.0 else [])
    seed_args = (seed,) if dropout_p > 0.0 else ()

    q_blk_spec = pl.BlockSpec((1, block_q, d), lambda b, h, i: (b, i, h))
    q_seq_spec = pl.BlockSpec((1, seq_len, d), lambda b, h, i: (b, 0, h))
    r_blk_spec = pl.BlockSpec((1, 1, block_q, 1),
                              lambda b, h, i: (b, h, i, 0))
    r_seq_spec = pl.BlockSpec((1, 1, seq_len, 1),
                              lambda b, h, i: (b, h, 0, 0))
    k_blk_spec = pl.BlockSpec((1, block_k, d), lambda b, h, i: (b, i, h))

    dq_kernel = functools.partial(
        _bwd_dq_kernel, block_q=block_q, block_k=block_k,
        seq_len=seq_len, causal=causal, scale=scale, dropout_p=dropout_p,
        fold_bh=True)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(b, h, seq_len // block_q),
        in_specs=seed_specs + [q_blk_spec, q_seq_spec, q_seq_spec,
                               q_blk_spec, r_blk_spec, r_blk_spec],
        out_specs=q_blk_spec,
        out_shape=jax.ShapeDtypeStruct((b, seq_len, h * d), q.dtype),
    )(*seed_args, qf, kf, vf, dof, lse, delta)

    dkv_kernel = functools.partial(
        _bwd_dkv_kernel, block_q=block_q, block_k=block_k,
        seq_len=seq_len, causal=causal, scale=scale, dropout_p=dropout_p,
        fold_bh=True)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(b, h, seq_len // block_k),
        in_specs=seed_specs + [q_seq_spec, k_blk_spec, k_blk_spec,
                               q_seq_spec, r_seq_spec, r_seq_spec],
        out_specs=[k_blk_spec, k_blk_spec],
        out_shape=[
            jax.ShapeDtypeStruct((b, seq_len, h * d), k.dtype),
            jax.ShapeDtypeStruct((b, seq_len, h * d), v.dtype),
        ],
    )(*seed_args, qf, kf, vf, dof, lse, delta)
    return (dq.reshape(b, seq_len, h, d), dk.reshape(b, seq_len, h, d),
            dv.reshape(b, seq_len, h, d))


def _tiles_ok(seq_len, d, block_q, block_k) -> bool:
    # d=64 (BERT-class heads) runs natively: Mosaic lays a [*, 64] tile
    # across half the 128 lanes; measured on v5e the native kernel beats
    # pad-to-128 at the BERT bench geometry (no pad/slice HBM traffic)
    return (seq_len % block_q == 0 and seq_len % block_k == 0
            and d % 64 == 0 and seq_len >= block_q)


_block_tune_cache: dict = {}


def _pick_block(seq_len: int, d: int = 128, sample=None) -> int:
    """Block-size choice. Default: the ladder measured on v5e (1.17B
    Llama, seq 2048, whole train step): 512 tiles ~7% faster than 256,
    256 ~15% faster than 128; 1024 exceeds VMEM.

    FLAGS_pallas_autotune=1 switches to a runtime tuner (the analog of
    the reference's kernels/autotune/cache.h): the first call per
    (seq_len, d) times each candidate on the live arrays and caches the
    winner for the process."""
    from ...core.flags import flag_value
    candidates = [b for b in (512, 256, 128) if seq_len % b == 0]
    if not candidates:
        return 128
    key = ("flash", seq_len, d)
    hit = _block_tune_cache.get(key)
    if hit is not None:
        return hit  # backward reuses the forward's tuned choice
    if sample is None or not flag_value("pallas_autotune"):
        return candidates[0]
    q, k, v = sample
    if isinstance(q, jax.core.Tracer):
        # inside a jit trace there is nothing to measure; do NOT cache —
        # a later eager call can still tune this shape
        return candidates[0]
    import time as _time
    fwd = _flash_fwd_pallas_blhd if q.ndim == 4 else _flash_fwd_pallas
    best, best_t = None, float("inf")
    for blk in candidates:
        try:
            out, _ = fwd(q, k, v, False, 1.0 / math.sqrt(d),
                         block_q=blk, block_k=blk)
            float(jnp.sum(out))  # warm; value fetch = the real barrier
            t0 = _time.perf_counter()
            for _ in range(3):
                out, _ = fwd(q, k, v, False, 1.0 / math.sqrt(d),
                             block_q=blk, block_k=blk)
            float(jnp.sum(out))
            dt = _time.perf_counter() - t0
        except Exception:
            continue
        if dt < best_t:
            best, best_t = blk, dt
    if best is None:
        return candidates[0]  # nothing measured: stay untuned, uncached
    _block_tune_cache[key] = best
    return best


def _use_pallas(l, d) -> bool:
    return (_HAS_PALLAS and jax.default_backend() in ("tpu", "axon")
            and _tiles_ok(l, d, 128, 128))


def _to_bhld(x):
    b, l, h, d = x.shape
    return jnp.swapaxes(x, 1, 2).reshape(b * h, l, d)


def _from_bhld(x, b, h):
    bh, l, d = x.shape
    return jnp.swapaxes(x.reshape(b, h, l, d), 1, 2)


def _as_seed(seed):
    """Normalize to the (1,) int32 SMEM scalar the kernels expect."""
    return jnp.asarray(seed, jnp.int32).reshape(1)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(q, k, v, causal=False, scale=None, dropout_p=0.0,
                    seed=None):
    """[B, L, H, D] in/out (paddle flash-attention layout).

    ``dropout_p``/``seed`` give fused attention-probability dropout
    (ref: flash_attn_kernel.cu p_dropout + philox seed/offset): the keep
    mask is generated inside the kernel from (seed, tile position) and
    regenerated identically in the backward kernels, so dropped
    probabilities never touch HBM. ``seed`` may be a python int or a
    traced int scalar (changes per step under one compiled program)."""
    out, _ = _flash_fwd_res(q, k, v, causal, scale, dropout_p, seed)
    return out


def _flash_fwd_res(q, k, v, causal, scale, dropout_p=0.0, seed=None):
    b, l, h, d = q.shape
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    if dropout_p > 0.0 and seed is None:
        raise ValueError("flash_attention dropout needs a seed")
    if dropout_p >= 1.0:
        raise ValueError(
            "flash_attention dropout_p must be < 1 (p=1 zeroes the "
            "output — handle it at the dropout call site)")
    if _use_pallas(l, d):
        if d % 128 == 0:
            # layout-native kernels: q/k/v/out stay [B, L, H, D] end to
            # end (viewed [B, L, H*D]) — no transpose copies between
            # the projections and the kernel
            blk = _pick_block(l, d, sample=(q, k, v))
            out, lse = _flash_fwd_pallas_blhd(
                q, k, v, causal, s, block_q=blk, block_k=blk,
                dropout_p=float(dropout_p),
                seed=_as_seed(seed) if dropout_p > 0.0 else None)
            return out, (out, lse)
        # d=64 (BERT-class): Mosaic needs the minor block dim % 128, so
        # this path keeps the [B*H, L, D] layout with transposes.
        # Zero-padding d to 128 to ride the layout-native path was
        # measured and LOST (BERT-base MLM 113.0K -> 106.4K tok/s): the
        # pad/slice pairs move 2x the bytes the transposes do, more
        # than the half-lane kernel inefficiency costs.
        qb, kb, vb = _to_bhld(q), _to_bhld(k), _to_bhld(v)
        blk = _pick_block(l, d, sample=(qb, kb, vb))
        out_bhld, lse = _flash_fwd_pallas(
            qb, kb, vb, causal, s, block_q=blk, block_k=blk,
            dropout_p=float(dropout_p),
            seed=_as_seed(seed) if dropout_p > 0.0 else None)
        out = _from_bhld(out_bhld, b, h)
        return out, (out, lse)
    return _sdpa_xla(q, k, v, causal=causal, scale=s,
                     dropout_p=dropout_p, seed=seed), None


def _flash_vjp_fwd(q, k, v, causal, scale, dropout_p, seed):
    out, res = _flash_fwd_res(q, k, v, causal, scale, dropout_p, seed)
    return out, (q, k, v, seed, res)


def _flash_vjp_bwd(causal, scale, dropout_p, residuals, g):
    q, k, v, seed, res = residuals
    b, l, h, d = q.shape
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    if res is not None:  # pallas path: res = (out [B,L,H,D], lse)
        out, lse = res
        blk = _pick_block(l, d)
        if d % 128 == 0:
            dq, dk, dv = _flash_bwd_pallas_blhd(
                q, k, v, out, lse, g, causal, s, block_q=blk,
                block_k=blk, dropout_p=float(dropout_p),
                seed=_as_seed(seed) if dropout_p > 0.0 else None)
            return dq, dk, dv, None
        dq, dk, dv = _flash_bwd_pallas(
            _to_bhld(q), _to_bhld(k), _to_bhld(v), _to_bhld(out), lse,
            _to_bhld(g), causal, s, block_q=blk, block_k=blk,
            dropout_p=float(dropout_p),
            seed=_as_seed(seed) if dropout_p > 0.0 else None)
        return (_from_bhld(dq, b, h), _from_bhld(dk, b, h),
                _from_bhld(dv, b, h), None)
    # fallback: recompute-based XLA VJP (same seed -> identical mask)
    _, vjp = jax.vjp(
        lambda a, b_, c: _sdpa_xla(a, b_, c, causal=causal, scale=s,
                                   dropout_p=dropout_p, seed=seed),
        q, k, v)
    return vjp(g) + (None,)


flash_attention.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention_fwd(q, k, v, causal=False, scale=None, dropout_p=0.0,
                        seed=None):
    """Entry used by nn.functional.attention."""
    return flash_attention(q, k, v, causal, scale, dropout_p, seed)


# ---------------------------------------------------------------------------
# segmented (varlen-packed) flash attention: cu_seqlens -> per-token segment
# ids; kernel tiles mask cross-segment pairs. This is the packing path the
# reference exposes as flash_attn_varlen_qkvpacked
# (ref: python/paddle/nn/functional/flash_attention.py:792).
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("causal", "scale", "block_q",
                                             "block_k"))
def _flash_fwd_pallas_seg(q, k, v, seg, causal, scale, block_q=256,
                          block_k=256):
    """q,k,v: [BH, L, D]; seg: [BH, L, 1] int32 segment ids."""
    bh, seq_len, d = q.shape
    grid = (bh, seq_len // block_q)
    kernel = functools.partial(
        _fwd_kernel, block_q=block_q, block_k=block_k, seq_len=seq_len,
        causal=causal, scale=scale, segmented=True)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, seq_len, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, seq_len, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, seq_len, 1), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, seq_len, d), q.dtype),
            jax.ShapeDtypeStruct((bh, seq_len, 1), jnp.float32),
        ],
    )(q, k, v, seg)


@functools.partial(jax.jit, static_argnames=("causal", "scale", "block_q",
                                             "block_k"))
def _flash_bwd_pallas_seg(q, k, v, out, lse, do, seg, causal, scale,
                          block_q=256, block_k=256):
    bh, seq_len, d = q.shape
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)
    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, block_q=block_q, block_k=block_k,
            seq_len=seq_len, causal=causal, scale=scale, segmented=True),
        grid=(bh, seq_len // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, seq_len, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, seq_len, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, seq_len, 1), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, seq_len, d), q.dtype),
    )(q, k, v, do, lse, delta, seg)

    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, block_q=block_q, block_k=block_k,
            seq_len=seq_len, causal=causal, scale=scale, segmented=True),
        grid=(bh, seq_len // block_k),
        in_specs=[
            pl.BlockSpec((1, seq_len, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, seq_len, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, seq_len, 1), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, seq_len, 1), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, seq_len, 1), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, seq_len, d), k.dtype),
            jax.ShapeDtypeStruct((bh, seq_len, d), v.dtype),
        ],
    )(q, k, v, do, lse, delta, seg)
    return dq, dk, dv


def _sdpa_xla_seg(q, k, v, seg, causal, scale):
    """XLA oracle for segmented attention; seg: [B, L] int32."""
    same = (seg[:, :, None] == seg[:, None, :])  # [B, Lq, Lk]
    mask = jnp.where(same[:, None, :, :], 0.0, _NEG_INF)
    return _sdpa_xla(q, k, v, causal=causal, scale=scale, mask=mask)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def flash_attention_segmented(q, k, v, seg, causal=False, scale=None):
    """[B, L, H, D] + seg [B, L] int32 — attention restricted to equal
    segment ids (varlen packing), composable with causal."""
    out, _ = _flash_seg_fwd_res(q, k, v, seg, causal, scale)
    return out


def _flash_seg_fwd_res(q, k, v, seg, causal, scale):
    b, l, h, d = q.shape
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    if _use_pallas(l, d):
        blk = _pick_block(l, d)
        seg3 = jnp.repeat(seg[:, None, :], h, axis=1).reshape(b * h, l, 1)
        seg3 = seg3.astype(jnp.int32)
        out_bhld, lse = _flash_fwd_pallas_seg(
            _to_bhld(q), _to_bhld(k), _to_bhld(v), seg3, causal, s,
            block_q=blk, block_k=blk)
        return _from_bhld(out_bhld, b, h), (out_bhld, lse, seg3)
    return _sdpa_xla_seg(q, k, v, seg, causal, s), None


def _flash_seg_vjp_fwd(q, k, v, seg, causal, scale):
    out, res = _flash_seg_fwd_res(q, k, v, seg, causal, scale)
    return out, (q, k, v, seg, res)


def _flash_seg_vjp_bwd(causal, scale, residuals, g):
    q, k, v, seg, res = residuals
    b, l, h, d = q.shape
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    if res is not None:
        out_bhld, lse, seg3 = res
        blk = _pick_block(l, d)
        dq, dk, dv = _flash_bwd_pallas_seg(
            _to_bhld(q), _to_bhld(k), _to_bhld(v), out_bhld, lse,
            _to_bhld(g), seg3, causal, s, block_q=blk, block_k=blk)
        return (_from_bhld(dq, b, h), _from_bhld(dk, b, h),
                _from_bhld(dv, b, h), None)
    _, vjp = jax.vjp(
        lambda a, b_, c: _sdpa_xla_seg(a, b_, c, seg, causal, s), q, k, v)
    dq, dk, dv = vjp(g)
    return dq, dk, dv, None


flash_attention_segmented.defvjp(_flash_seg_vjp_fwd, _flash_seg_vjp_bwd)


# analysis-plane aval registration (ops.yaml `fusable: attention` +
# `shape: attention`): the eager fusion DAG never defers attention —
# try_fuse returns None for the class — but the capture planner's
# abstract interpreter grades its `shape:` spec against these REAL
# entry points via jax.eval_shape (core.fusion.infer_output_aval), so
# the declared arithmetic can't drift from what actually runs.
def _register_aval_impls() -> None:
    from ...core.fusion import register_param_impl
    register_param_impl("flash_attention", flash_attention)
    register_param_impl("flash_attention_segmented",
                        flash_attention_segmented)


_register_aval_impls()
