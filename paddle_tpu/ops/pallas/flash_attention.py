"""Flash attention, Pallas-on-TPU.

TPU-native replacement for the reference's flash-attention wrapper
(ref: paddle/phi/kernels/gpu/flash_attn_kernel.cu, which calls the vendored
third_party/flashattn CUDA lib). Design: online-softmax tiling over the KV
sequence so logits never materialize in HBM — the standard flash recipe —
with block sizes aligned to the MXU (128) per the Pallas TPU guide.

Forward is the Pallas kernel; backward is a recompute-based VJP in plain
XLA (flash bwd kernel is a later optimization; remat keeps memory flat).
Falls back to the fused-XLA reference implementation when Pallas is
unavailable (CPU mesh tests) or shapes don't tile.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

__all__ = ["flash_attention_fwd", "flash_attention"]

_NEG_INF = -1e30


def _sdpa_xla(q, k, v, causal=False, scale=None, mask=None):
    """Numeric oracle, layout [B, L, H, D]. `mask` is additive, broadcast
    against [B, H, Lq, Lk] logits. Handles Lq < Lk (KV-cache decode) by
    offsetting the causal diagonal."""
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    logits = jnp.einsum("bhqd,bhkd->bhqk", qt, kt).astype(jnp.float32) * s
    if causal:
        ql, kl = logits.shape[-2], logits.shape[-1]
        cm = jnp.tril(jnp.ones((ql, kl), bool), k=kl - ql)
        logits = jnp.where(cm, logits, _NEG_INF)
    if mask is not None:
        logits = logits + mask
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vt)
    return jnp.swapaxes(out, 1, 2)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_q, block_k, seq_len,
                  causal, scale):
    """One (batch*head, q-block) program; inner loop tiles KV with online
    softmax (running max m, normalizer l, accumulator acc)."""
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale  # [block_q, d]

    m = jnp.full((block_q, 1), _NEG_INF, jnp.float32)
    l = jnp.zeros((block_q, 1), jnp.float32)
    acc = jnp.zeros((block_q, q.shape[-1]), jnp.float32)

    q_offset = qi * block_q
    num_k_blocks = seq_len // block_k
    if causal:
        # only blocks at or before the diagonal contribute
        num_k_blocks_eff = (q_offset + block_q + block_k - 1) // block_k
    else:
        num_k_blocks_eff = num_k_blocks

    def body(ki, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        logits = q @ k_blk.T  # [block_q, block_k]
        if causal:
            q_ids = q_offset + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_ids = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            logits = jnp.where(q_ids >= k_ids, logits, _NEG_INF)
        m_new = jnp.maximum(m, logits.max(axis=-1, keepdims=True))
        p = jnp.exp(logits - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + p.sum(axis=-1, keepdims=True)
        acc_new = alpha * acc + p @ v_blk
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, num_k_blocks_eff, body, (m, l, acc))
    o_ref[0] = (acc / l).astype(o_ref.dtype)


try:  # Pallas import is deferred-safe: CPU wheels ship it but TPU lowering
    from jax.experimental import pallas as pl
    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    pl = None
    _HAS_PALLAS = False


@functools.partial(jax.jit, static_argnames=("causal", "scale", "block_q",
                                             "block_k"))
def _flash_pallas_bhld(q, k, v, causal, scale, block_q=128, block_k=128):
    """q,k,v: [BH, L, D] -> [BH, L, D]."""
    bh, seq_len, d = q.shape
    grid = (bh, seq_len // block_q)
    kernel = functools.partial(
        _flash_kernel, block_q=block_q, block_k=block_k, seq_len=seq_len,
        causal=causal, scale=scale)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, seq_len, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, seq_len, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, seq_len, d), q.dtype),
    )(q, k, v)


def _tiles_ok(seq_len, d, block_q, block_k) -> bool:
    return (seq_len % block_q == 0 and seq_len % block_k == 0
            and d % 128 == 0 and seq_len >= block_q)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention(q, k, v, causal=False, scale=None):
    """[B, L, H, D] in/out (paddle flash-attention layout)."""
    return _flash_fwd_impl(q, k, v, causal, scale)


def _flash_fwd_impl(q, k, v, causal, scale):
    b, l, h, d = q.shape
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    backend = jax.default_backend()
    if _HAS_PALLAS and backend in ("tpu", "axon") and _tiles_ok(l, d, 128, 128):
        def to_bhld(x):
            return jnp.swapaxes(x, 1, 2).reshape(b * h, l, d)
        out = _flash_pallas_bhld(to_bhld(q), to_bhld(k), to_bhld(v),
                                 causal, s)
        return jnp.swapaxes(out.reshape(b, h, l, d), 1, 2)
    return _sdpa_xla(q, k, v, causal=causal, scale=s)


def _flash_vjp_fwd(q, k, v, causal, scale):
    return _flash_fwd_impl(q, k, v, causal, scale), (q, k, v)


def _flash_vjp_bwd(causal, scale, res, g):
    # recompute-based backward in plain XLA; flat memory, MXU-friendly
    q, k, v = res
    _, vjp = jax.vjp(lambda a, b_, c: _sdpa_xla(a, b_, c, causal=causal,
                                                scale=scale), q, k, v)
    return vjp(g)


flash_attention.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention_fwd(q, k, v, causal=False, scale=None):
    """Entry used by nn.functional.attention."""
    return flash_attention(q, k, v, causal, scale)
