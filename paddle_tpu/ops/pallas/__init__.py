"""Pallas TPU kernels — the hand-written hot ops.

The analog of the reference's fused kernel zoo (ref: paddle/phi/kernels/
fusion/, 90k LoC CUDA/CUTLASS): flash attention, fused RoPE, fused
layernorm. Each module exposes a jittable function with a custom_vjp and a
pure-XLA fallback for non-TPU backends (used by the CPU test mesh).
"""
from . import flash_attention  # noqa: F401
from . import paged_attention  # noqa: F401
