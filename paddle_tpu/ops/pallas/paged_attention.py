"""Block-table paged attention, Pallas-on-TPU.

TPU kernel behind the ``serving_cache.paged_attention`` seam: the
pure-jnp tiled walk (the CPU/tier-1 numerics oracle) streams each
slot's mapped KV blocks through XLA gathers; on TPU that per-tile
gather loop is the remaining decode roofline gap (ROADMAP item 1b).
This kernel keeps the identical flat ``(q, pools, tables, positions)``
signature and the identical online-softmax tiling, but lets the Mosaic
pipeline move blocks HBM->VMEM via **scalar-prefetched block-table
indexing** (the vLLM-style recipe): the grid walks (slot, tile) and
each tile's BlockSpec index_map reads ``tables[s, t]`` — prefetched to
SMEM before the body runs — so the next physical block's DMA overlaps
the current tile's MXU work instead of round-tripping a gather.

Contract (shared with the jnp walk, parity-pinned in
tests/test_serving_spec.py):

- row ``(s, t)`` attends every column ``c <= positions[s, t]``;
- GQA runs against the UNEXPANDED pools (``n_rep`` query heads per KV
  head, grouped batched dots — never a repeated pool);
- ``k_scale``/``v_scale`` switch the tile load to int8-dequant mode;
- recycled-block garbage (NaN/inf from a previous request) is
  sanitized per tile, so masked columns contribute exactly zero;
- tiles at or past ``n_tiles`` are skipped (``@pl.when``), so short
  histories pay only their own compute (their DMAs land on the
  clamped block and are overlapped anyway).

``interpret=True`` runs the same kernel through the Pallas interpreter
— how the CPU parity test asserts same-numerics without a TPU.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

try:  # mirror flash_attention's deferred-safe import
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    pl = None
    pltpu = None
    _HAS_PALLAS = False

__all__ = ["paged_attention_kernel", "kernel_available"]

_NEG_INF = -1e30


def kernel_available(interpret: bool = False) -> bool:
    """True when the Pallas paged-attention kernel can run: a TPU-class
    backend (or the interpreter, for CPU parity tests)."""
    if not _HAS_PALLAS:
        return False
    if interpret:
        return True
    return jax.default_backend() in ("tpu", "axon")


def _kernel(tables_ref, pos_ref, nt_ref, *refs, block_size, n_rep, T,
            kvh, head_dim, dequant):
    """One (slot, tile) program. Scalar-prefetch refs: the flat block
    table (drives the BlockSpec index maps — see the pallas_call),
    per-row positions, and the live tile count. Tensor refs:
    q [1, T, H*D] | k/v tile [1, bs, K*D] | (k/v scale [1, bs, K]) |
    out [1, T, H*D]; scratch: m/l [K, T*R] + acc [K, T*R, D] carries
    that live across the sequential tile dimension of the grid."""
    if dequant:
        q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref, m_s, l_s, acc_s = refs
    else:
        q_ref, k_ref, v_ref, o_ref, m_s, l_s, acc_s = refs
        ks_ref = vs_ref = None
    s = pl.program_id(0)
    t = pl.program_id(1)
    R, D = n_rep, head_dim

    @pl.when(t == 0)
    def _init():
        m_s[...] = jnp.full_like(m_s, _NEG_INF)
        l_s[...] = jnp.zeros_like(l_s)
        acc_s[...] = jnp.zeros_like(acc_s)

    @pl.when(t < nt_ref[0])
    def _tile():
        k_t = k_ref[0].reshape(block_size, kvh, D)
        v_t = v_ref[0].reshape(block_size, kvh, D)
        if dequant:
            k_t = k_t.astype(jnp.float32) * ks_ref[0][..., None]
            v_t = v_t.astype(jnp.float32) * vs_ref[0][..., None]
        # recycled blocks may hold non-finite garbage from a previous
        # request — same sanitization as the jnp walk, masked columns
        # must contribute EXACTLY zero (0 * NaN = NaN in the PV dot)
        k_t = jnp.nan_to_num(k_t.astype(jnp.float32))
        v_t = jnp.nan_to_num(v_t.astype(jnp.float32))
        # grouped GQA: [K, T*R, D] x [K, bs, D] batched over KV heads,
        # never expanding the pools n_rep-fold
        q = q_ref[0].reshape(T, kvh, R, D).transpose(1, 0, 2, 3)
        q = q.reshape(kvh, T * R, D).astype(jnp.float32)
        kt = k_t.transpose(1, 0, 2)                    # [K, bs, D]
        vt = v_t.transpose(1, 0, 2)
        scores = jax.lax.dot_general(
            q, kt, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)        # [K, T*R, bs]
        scores = scores * (1.0 / float(np.sqrt(D)))
        cols = t * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (T, block_size), 1)
        posv = jnp.stack([pos_ref[s, i] for i in range(T)])
        ok = cols <= posv[:, None]                     # [T, bs]
        okr = jnp.repeat(ok, R, axis=0)                # rows t*R + r
        scores = jnp.where(okr[None], scores, _NEG_INF)
        m_new = jnp.maximum(m_s[...], jnp.max(scores, axis=-1))
        # a fully-masked row has scores == m_new == -1e30: exp gives 1,
        # re-mask p so its contribution is exactly zero (jnp-walk rule)
        p = jnp.where(okr[None], jnp.exp(scores - m_new[..., None]),
                      0.0)
        corr = jnp.exp(m_s[...] - m_new)
        l_s[...] = l_s[...] * corr + jnp.sum(p, axis=-1)
        pv = jax.lax.dot_general(
            p, vt, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)        # [K, T*R, D]
        acc_s[...] = acc_s[...] * corr[..., None] + pv
        m_s[...] = m_new

    @pl.when(t == pl.num_programs(1) - 1)
    def _done():
        out = acc_s[...] / jnp.maximum(l_s[...], 1e-30)[..., None]
        out = out.reshape(kvh, T, R, D).transpose(1, 0, 2, 3)
        o_ref[0] = out.reshape(T, kvh * R * D).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("block_size", "n_rep", "interpret"))
def _paged_attention_call(q, k_pool, v_pool, tables, positions,
                          n_tiles, k_scale, v_scale, *, block_size,
                          n_rep, interpret):
    S, T, H, D = q.shape
    K = k_pool.shape[2]
    MB = tables.shape[1]
    dequant = k_scale is not None
    kernel = functools.partial(
        _kernel, block_size=block_size, n_rep=n_rep, T=T, kvh=K,
        head_dim=D, dequant=dequant)

    def _phys(s, t, tables_ref, pos_ref, nt_ref):
        # unmapped (-1) and beyond-n_tiles entries clamp to block 0:
        # the DMA still lands somewhere valid, @pl.when skips/masks
        # the compute exactly like the jnp walk's max(tables, 0)
        return jnp.maximum(tables_ref[s, t], 0)

    q_spec = pl.BlockSpec(
        (1, T, H * D), lambda s, t, tr, pr, nr: (s, 0, 0))
    kv_spec = pl.BlockSpec(
        (1, block_size, K * D),
        lambda s, t, tr, pr, nr: (_phys(s, t, tr, pr, nr), 0, 0))
    in_specs = [q_spec, kv_spec, kv_spec]
    args = [q.reshape(S, T, H * D),
            k_pool.reshape(k_pool.shape[0], block_size, K * D),
            v_pool.reshape(v_pool.shape[0], block_size, K * D)]
    if dequant:
        sc_spec = pl.BlockSpec(
            (1, block_size, K),
            lambda s, t, tr, pr, nr: (_phys(s, t, tr, pr, nr), 0, 0))
        in_specs += [sc_spec, sc_spec]
        args += [k_scale, v_scale]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(S, MB),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, T, H * D), lambda s, t, tr, pr, nr: (s, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((K, T * n_rep), jnp.float32),
            pltpu.VMEM((K, T * n_rep), jnp.float32),
            pltpu.VMEM((K, T * n_rep, D), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, T, H * D), q.dtype),
        interpret=interpret,
    )(tables.astype(jnp.int32), positions.astype(jnp.int32),
      jnp.asarray(n_tiles, jnp.int32).reshape(1), *args)
    return out.reshape(S, T, H, D)


def paged_attention_kernel(q, k_pool, v_pool, tables, positions, *,
                           block_size: int, n_rep: int, n_tiles=None,
                           k_scale=None, v_scale=None,
                           interpret: bool = False):
    """Flat-signature drop-in for ``serving_cache.paged_attention``
    (q [S, T, H, D], pools [num_blocks, bs, KVH, D], tables
    [S, max_blocks], positions [S, T]); ``n_tiles`` may be traced —
    it rides in as a scalar-prefetch operand bounding the live tiles.
    """
    if n_tiles is None:
        n_tiles = tables.shape[1]
    return _paged_attention_call(
        q, k_pool, v_pool, tables, positions, n_tiles, k_scale,
        v_scale, block_size=int(block_size), n_rep=int(n_rep),
        interpret=bool(interpret))
