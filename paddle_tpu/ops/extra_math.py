"""Long-tail tensor ops completing the reference's top-level surface.

ref: python/paddle/__init__.py __all__ and python/paddle/tensor/
{math,manipulation,creation,linalg}.py — thin differentiable wrappers
over jnp (XLA fuses them); grouped here to keep the core op modules
focused on the hot surface.
"""
from __future__ import annotations

import math as _pymath

import jax
import jax.numpy as jnp
import numpy as np

from ..core import fusion as _fusion
from ..core.autograd import apply_op
from ..core import random as random_mod
from ..core.tensor import Tensor

# elementwise extra_math ops that opt into lazy-eager chain fusion
# (ops.yaml flags them `fusable`); the registered object must be the
# exact fn each wrapper dispatches through apply_op
_fusion.register_impl("sinc", jnp.sinc)
_fusion.register_impl("copysign", jnp.copysign)
_fusion.register_impl("rad2deg", jnp.rad2deg)
_fusion.register_impl("deg2rad", jnp.deg2rad)

__all__ = [
    "addmm", "add_n", "as_complex", "as_real", "block_diag",
    "broadcast_shape", "bucketize", "cartesian_prod", "cdist",
    "column_stack", "combinations", "complex", "copysign",
    "cumulative_trapezoid", "deg2rad", "diag_embed", "diagflat",
    "diagonal_scatter", "dsplit", "dstack", "frexp", "gammainc",
    "gammaincc", "gammaln", "gcd", "heaviside", "histogram",
    "histogram_bin_edges", "histogramdd", "hsplit", "hstack", "i0", "i0e",
    "i1", "i1e", "index_fill", "is_complex", "is_empty",
    "is_floating_point", "is_integer", "is_tensor", "isin", "isneginf",
    "isposinf", "isreal", "lcm", "ldexp", "log_normal", "logcumsumexp",
    "logit", "logspace", "masked_scatter", "multigammaln", "multiplex",
    "nan_to_num", "nanmedian", "nanquantile", "nextafter", "pdist",
    "poisson", "polar", "polygamma", "quantile", "rad2deg", "randint_like",
    "reduce_as", "renorm", "reverse", "row_stack", "select_scatter",
    "sgn", "signbit", "sinc", "slice_scatter", "standard_gamma",
    "standard_normal", "take", "tensor_split", "trapezoid",
    "tril_indices", "triu_indices", "unflatten", "unique_consecutive",
    "unstack", "vander", "view_as", "vsplit", "vstack",
    "bitwise_left_shift", "bitwise_right_shift",
]


def _d(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def _op(f, *args, name):
    return apply_op(f, *args, op_name=name)


# --------------------------- predicates / info ------------------------------

def is_tensor(x):
    return isinstance(x, Tensor)


def is_complex(x):
    return jnp.issubdtype(_d(x).dtype, jnp.complexfloating)


def is_integer(x):
    return jnp.issubdtype(_d(x).dtype, jnp.integer)


def is_floating_point(x):
    return jnp.issubdtype(_d(x).dtype, jnp.floating)


def is_empty(x, name=None):
    return Tensor(jnp.asarray(_d(x).size == 0))


def isreal(x, name=None):
    def f(a):
        if jnp.issubdtype(a.dtype, jnp.complexfloating):
            return jnp.imag(a) == 0
        return jnp.ones(a.shape, bool)
    return _op(f, x, name="isreal")


def isposinf(x, name=None):
    return _op(lambda a: jnp.isposinf(a), x, name="isposinf")


def isneginf(x, name=None):
    return _op(lambda a: jnp.isneginf(a), x, name="isneginf")


def signbit(x, name=None):
    return _op(jnp.signbit, x, name="signbit")


def isin(x, test_x, assume_unique=False, invert=False, name=None):
    return _op(lambda a, b: jnp.isin(a, b, invert=invert), x, test_x,
               name="isin")


# ------------------------------- math ---------------------------------------

def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return _op(lambda i, a, b: beta * i + alpha * (a @ b), input, x, y,
               name="addmm")


def add_n(inputs, name=None):
    if isinstance(inputs, Tensor):
        inputs = [inputs]
    return _op(lambda *xs: sum(xs[1:], xs[0]), *inputs, name="add_n")


def logit(x, eps=None, name=None):
    def f(a):
        if eps is not None:
            a = jnp.clip(a, eps, 1 - eps)
        return jnp.log(a) - jnp.log1p(-a)
    return _op(f, x, name="logit")


def logcumsumexp(x, axis=None, name=None):
    def f(a):
        if axis is None:
            a = a.reshape(-1)
            ax = 0
        else:
            ax = axis
        return jax.lax.associative_scan(jnp.logaddexp, a, axis=ax)
    return _op(f, x, name="logcumsumexp")


def sinc(x, name=None):
    return _op(jnp.sinc, x, name="sinc")


def heaviside(x, y, name=None):
    return _op(jnp.heaviside, x, y, name="heaviside")


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return _op(lambda a: jnp.nan_to_num(a, nan=nan, posinf=posinf,
                                        neginf=neginf), x,
               name="nan_to_num")


def sgn(x, name=None):
    def f(a):
        if jnp.issubdtype(a.dtype, jnp.complexfloating):
            mag = jnp.abs(a)
            return jnp.where(mag == 0, 0.0 + 0.0j, a / mag)
        return jnp.sign(a)
    return _op(f, x, name="sgn")


def copysign(x, y, name=None):
    return _op(jnp.copysign, x, y, name="copysign")


def nextafter(x, y, name=None):
    return _op(jnp.nextafter, x, y, name="nextafter")


def frexp(x, name=None):
    return _op(lambda a: tuple(jnp.frexp(a)), x, name="frexp")


def ldexp(x, y, name=None):
    return _op(lambda a, b: jnp.ldexp(a, b.astype(jnp.int32)), x, y,
               name="ldexp")


def rad2deg(x, name=None):
    return _op(jnp.rad2deg, x, name="rad2deg")


def deg2rad(x, name=None):
    return _op(jnp.deg2rad, x, name="deg2rad")


def gcd(x, y, name=None):
    return _op(jnp.gcd, x, y, name="gcd")


def lcm(x, y, name=None):
    return _op(jnp.lcm, x, y, name="lcm")


def gammaln(x, name=None):
    return _op(jax.scipy.special.gammaln, x, name="gammaln")


def gammainc(x, y, name=None):
    return _op(jax.scipy.special.gammainc, x, y, name="gammainc")


def gammaincc(x, y, name=None):
    return _op(jax.scipy.special.gammaincc, x, y, name="gammaincc")


def multigammaln(x, p, name=None):
    def f(a):
        c = 0.25 * p * (p - 1) * _pymath.log(_pymath.pi)
        j = jnp.arange(p, dtype=jnp.float32)
        return c + jnp.sum(
            jax.scipy.special.gammaln(a[..., None] - 0.5 * j), -1)
    return _op(f, x, name="multigammaln")


def polygamma(x, n, name=None):
    if n == 0:
        return _op(jax.scipy.special.digamma, x, name="polygamma")
    return _op(lambda a: jax.scipy.special.polygamma(n, a), x,
               name="polygamma")


def i0(x, name=None):
    return _op(jax.scipy.special.i0, x, name="i0")


def i0e(x, name=None):
    return _op(jax.scipy.special.i0e, x, name="i0e")


def i1(x, name=None):
    return _op(jax.scipy.special.i1, x, name="i1")


def i1e(x, name=None):
    return _op(jax.scipy.special.i1e, x, name="i1e")


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    if x is not None:
        return _op(lambda a, b: jnp.trapezoid(a, b, axis=axis), y, x,
                   name="trapezoid")
    return _op(lambda a: jnp.trapezoid(a, dx=dx or 1.0, axis=axis), y,
               name="trapezoid")


def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    def f(a, *maybe_x):
        a = jnp.moveaxis(a, axis, -1)
        if maybe_x:
            xs = jnp.moveaxis(maybe_x[0], axis, -1)
            widths = jnp.diff(xs)
        else:
            widths = (dx or 1.0)
        areas = (a[..., 1:] + a[..., :-1]) / 2 * widths
        return jnp.moveaxis(jnp.cumsum(areas, -1), -1, axis)
    args = [y] + ([x] if x is not None else [])
    return _op(f, *args, name="cumulative_trapezoid")


def quantile(x, q, axis=None, keepdim=False, interpolation="linear",
             name=None):
    return _op(lambda a: jnp.quantile(a, jnp.asarray(q), axis=axis,
                                      keepdims=keepdim,
                                      method=interpolation), x,
               name="quantile")


def nanquantile(x, q, axis=None, keepdim=False, interpolation="linear",
                name=None):
    return _op(lambda a: jnp.nanquantile(a, jnp.asarray(q), axis=axis,
                                         keepdims=keepdim,
                                         method=interpolation), x,
               name="nanquantile")


def nanmedian(x, axis=None, keepdim=False, mode="avg", name=None):
    return _op(lambda a: jnp.nanmedian(a, axis=axis, keepdims=keepdim), x,
               name="nanmedian")


def renorm(x, p, axis, max_norm, name=None):
    def f(a):
        dims = tuple(i for i in range(a.ndim) if i != axis % a.ndim)
        norms = jnp.sum(jnp.abs(a) ** p, axis=dims,
                        keepdims=True) ** (1.0 / p)
        factor = jnp.where(norms > max_norm, max_norm / (norms + 1e-7),
                           1.0)
        return a * factor
    return _op(f, x, name="renorm")


def reduce_as(x, target, name=None):
    def f(a, t):
        extra = a.ndim - t.ndim
        axes = tuple(range(extra)) + tuple(
            i + extra for i in range(t.ndim)
            if t.shape[i] == 1 and a.shape[i + extra] != 1)
        out = jnp.sum(a, axis=axes, keepdims=False)
        return out.reshape(t.shape)
    return _op(f, x, target, name="reduce_as")


# ----------------------- complex-number helpers ------------------------------

def complex(real, imag, name=None):
    return _op(jax.lax.complex, real, imag, name="complex")


def as_complex(x, name=None):
    return _op(lambda a: jax.lax.complex(a[..., 0], a[..., 1]), x,
               name="as_complex")


def as_real(x, name=None):
    return _op(lambda a: jnp.stack([jnp.real(a), jnp.imag(a)], -1), x,
               name="as_real")


def polar(abs, angle, name=None):
    return _op(lambda r, t: jax.lax.complex(r * jnp.cos(t),
                                            r * jnp.sin(t)),
               abs, angle, name="polar")


# --------------------------- random ------------------------------------------

def standard_normal(shape, dtype="float32", name=None):
    key = random_mod.next_key()
    return Tensor(jax.random.normal(key, tuple(shape),
                                    jnp.dtype(dtype)))


def standard_gamma(x, name=None):
    key = random_mod.next_key()
    return _op(lambda a: jax.random.gamma(key, a), x,
               name="standard_gamma")


def poisson(x, name=None):
    key = random_mod.next_key()
    return _op(lambda a: jax.random.poisson(key, a).astype(a.dtype), x,
               name="poisson")


def log_normal(mean=1.0, std=2.0, shape=None, dtype="float32", name=None):
    key = random_mod.next_key()
    return Tensor(jnp.exp(mean + std * jax.random.normal(
        key, tuple(shape or ()), jnp.dtype(dtype))))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    if high is None:
        low, high = 0, low
    key = random_mod.next_key()
    xd = _d(x)
    return Tensor(jax.random.randint(
        key, xd.shape, low, high).astype(jnp.dtype(dtype) if dtype
                                         else xd.dtype))


# ------------------------- shape / stacking ----------------------------------

def broadcast_shape(x_shape, y_shape):
    return list(jnp.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def hstack(x, name=None):
    return _op(lambda *xs: jnp.hstack(xs), *x, name="hstack")


def vstack(x, name=None):
    return _op(lambda *xs: jnp.vstack(xs), *x, name="vstack")


def dstack(x, name=None):
    return _op(lambda *xs: jnp.dstack(xs), *x, name="dstack")


def column_stack(x, name=None):
    return _op(lambda *xs: jnp.column_stack(xs), *x, name="column_stack")


row_stack = vstack


def tensor_split(x, num_or_indices, axis=0, name=None):
    def f(a):
        return tuple(jnp.array_split(a, num_or_indices, axis=axis)) \
            if isinstance(num_or_indices, int) else \
            tuple(jnp.split(a, list(num_or_indices), axis=axis))
    return list(_op(f, x, name="tensor_split"))


def hsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=1 if _d(x).ndim > 1 else 0)


def vsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=0)


def dsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=2)


def unstack(x, axis=0, num=None, name=None):
    n = num or _d(x).shape[axis]
    def f(a):
        return tuple(jnp.moveaxis(a, axis, 0)[i] for i in range(n))
    return list(_op(f, x, name="unstack"))


def unflatten(x, axis, shape, name=None):
    def f(a):
        new = list(a.shape[:axis % a.ndim]) + list(shape) + \
            list(a.shape[axis % a.ndim + 1:])
        return a.reshape(new)
    return _op(f, x, name="unflatten")


def view_as(x, other, name=None):
    return _op(lambda a, b: a.reshape(b.shape), x, other, name="view_as")


def reverse(x, axis, name=None):
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    return _op(lambda a: jnp.flip(a, axis=tuple(axes)), x, name="reverse")


def unique_consecutive(x, return_inverse=False, return_counts=False,
                       axis=None, name=None):
    xv = np.asarray(jax.device_get(_d(x)))
    flat = xv.reshape(-1) if axis is None else xv
    keep = np.ones(len(flat), bool)
    keep[1:] = flat[1:] != flat[:-1]
    out = flat[keep]
    results = [Tensor(jnp.asarray(out))]
    if return_inverse:
        inv = np.cumsum(keep) - 1
        results.append(Tensor(jnp.asarray(inv.astype(np.int64))))
    if return_counts:
        idx = np.flatnonzero(keep)
        counts = np.diff(np.append(idx, len(flat)))
        results.append(Tensor(jnp.asarray(counts.astype(np.int64))))
    return results[0] if len(results) == 1 else tuple(results)


# ----------------------- construction helpers --------------------------------

def block_diag(inputs, name=None):
    return _op(lambda *xs: jax.scipy.linalg.block_diag(*xs), *inputs,
               name="block_diag")


def diagflat(x, offset=0, name=None):
    return _op(lambda a: jnp.diagflat(a, k=offset), x, name="diagflat")


def diag_embed(input, offset=0, dim1=-2, dim2=-1, name=None):
    def f(a):
        n = a.shape[-1] + abs(offset)
        base = jnp.zeros(a.shape[:-1] + (n, n), a.dtype)
        idx = jnp.arange(a.shape[-1])
        r = idx + max(-offset, 0)
        c = idx + max(offset, 0)
        out = base.at[..., r, c].set(a)
        # place the two new axes at dim1/dim2
        nd = out.ndim
        d1, d2 = dim1 % nd, dim2 % nd
        if (d1, d2) != (nd - 2, nd - 1):
            out = jnp.moveaxis(out, (nd - 2, nd - 1), (d1, d2))
        return out
    return _op(f, input, name="diag_embed")


def logspace(start, stop, num, base=10.0, dtype="float32", name=None):
    return Tensor(jnp.logspace(start, stop, int(num), base=base,
                               dtype=jnp.dtype(dtype)))


def vander(x, n=None, increasing=False, name=None):
    return _op(lambda a: jnp.vander(a, N=n, increasing=increasing), x,
               name="vander")


def tril_indices(row, col=None, offset=0, dtype="int64", name=None):
    col = col if col is not None else row
    r, c = np.tril_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]).astype(np.int64)))


def triu_indices(row, col=None, offset=0, dtype="int64", name=None):
    col = col if col is not None else row
    r, c = np.triu_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]).astype(np.int64)))


def cartesian_prod(x, name=None):
    if len(x) == 1:  # ref: tensor/math.py cartesian_prod
        return x[0] if isinstance(x[0], Tensor) else Tensor(_d(x[0]))

    def f(*xs):
        grids = jnp.meshgrid(*xs, indexing="ij")
        return jnp.stack([g.reshape(-1) for g in grids], -1)
    return _op(f, *x, name="cartesian_prod")


def combinations(x, r=2, with_replacement=False, name=None):
    import itertools
    n = _d(x).shape[0]
    combo = itertools.combinations_with_replacement if with_replacement \
        else itertools.combinations
    idx = np.asarray(list(combo(range(n), r)), np.int64)
    if idx.size == 0:
        idx = idx.reshape(0, r)
    return _op(lambda a: a[jnp.asarray(idx)], x, name="combinations")


# ------------------------- scatter-style updates -----------------------------

def slice_scatter(x, value, axes, starts, ends, strides, name=None):
    def f(a, v):
        idx = [slice(None)] * a.ndim
        for ax, s, e, st in zip(axes, starts, ends, strides):
            idx[ax] = slice(s, e, st)
        return a.at[tuple(idx)].set(v)
    return _op(f, x, value, name="slice_scatter")


def select_scatter(x, values, axis, index, name=None):
    def f(a, v):
        idx = [slice(None)] * a.ndim
        idx[axis] = index
        return a.at[tuple(idx)].set(v)
    return _op(f, x, values, name="select_scatter")


def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1, name=None):
    def f(a, v):
        idx = jnp.arange(v.shape[-1])
        r = idx + max(-offset, 0)
        c = idx + max(offset, 0)
        a2 = jnp.moveaxis(a, (axis1, axis2), (-2, -1))
        out = a2.at[..., r, c].set(v)
        return jnp.moveaxis(out, (-2, -1), (axis1, axis2))
    return _op(f, x, y, name="diagonal_scatter")


def index_fill(x, index, axis, value, name=None):
    def f(a, i):
        idx = [slice(None)] * a.ndim
        idx[axis] = i
        return a.at[tuple(idx)].set(value)
    return _op(f, x, index, name="index_fill")


def masked_scatter(x, mask, value, name=None):
    xv = np.asarray(jax.device_get(_d(x))).copy()
    mv = np.asarray(jax.device_get(_d(mask)))
    vv = np.asarray(jax.device_get(_d(value))).reshape(-1)
    mv = np.broadcast_to(mv, xv.shape)
    n = int(mv.sum())
    xv[mv] = vv[:n]
    return Tensor(jnp.asarray(xv))


def multiplex(inputs, index, name=None):
    def f(i, *xs):
        stacked = jnp.stack(xs)                      # [K, B, ...]
        rows = jnp.arange(stacked.shape[1])
        return stacked[i.reshape(-1), rows]
    return _op(f, index, *inputs, name="multiplex")


def take(x, index, mode="raise", name=None):
    if mode == "raise":
        # eager bounds check on the concrete indices preserves the
        # reference's error contract (indices under jit can't raise)
        iv = np.asarray(jax.device_get(_d(index)))
        n = _d(x).size
        if iv.size and (iv.min() < -n or iv.max() >= n):
            raise ValueError(
                f"take index out of range for tensor of {n} elements")
        jmode = "clip"
    else:
        jmode = {"clip": "clip", "wrap": "wrap"}[mode]
    return _op(lambda a, i: jnp.take(a.reshape(-1), i.reshape(-1),
                                     mode=jmode).reshape(i.shape),
               x, index, name="take")


# ----------------------------- histograms ------------------------------------

def histogram(input, bins=100, min=0, max=0, weight=None, density=False,
              name=None):
    xv = np.asarray(jax.device_get(_d(input))).reshape(-1)
    lo, hi = (min, max) if (min != 0 or max != 0) else \
        (float(xv.min()) if xv.size else 0.0,
         float(xv.max()) if xv.size else 1.0)
    wv = np.asarray(jax.device_get(_d(weight))).reshape(-1) \
        if weight is not None else None
    h, _ = np.histogram(xv, bins=bins, range=(lo, hi), weights=wv,
                        density=density)
    return Tensor(jnp.asarray(h if density or weight is not None
                              else h.astype(np.int64)))


def histogram_bin_edges(input, bins=100, min=0, max=0, name=None):
    xv = np.asarray(jax.device_get(_d(input))).reshape(-1)
    rng = (min, max) if (min != 0 or max != 0) else None
    return Tensor(jnp.asarray(
        np.histogram_bin_edges(xv, bins=bins, range=rng)
        .astype(np.float32)))


def histogramdd(x, bins=10, ranges=None, density=False, weights=None,
                name=None):
    xv = np.asarray(jax.device_get(_d(x)))
    wv = np.asarray(jax.device_get(_d(weights))) \
        if weights is not None else None
    h, edges = np.histogramdd(xv, bins=bins, range=ranges,
                              density=density, weights=wv)
    return (Tensor(jnp.asarray(h.astype(np.float32))),
            [Tensor(jnp.asarray(e.astype(np.float32))) for e in edges])


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    def f(a, s):
        side = "right" if right else "left"
        out = jnp.searchsorted(s, a, side=side)
        return out.astype(jnp.int32 if out_int32 else jnp.int64)
    return _op(f, x, sorted_sequence, name="bucketize")


# ------------------------------ distances ------------------------------------

def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary",
          name=None):
    def f(a, b):
        diff = a[..., :, None, :] - b[..., None, :, :]
        if p == 2.0:
            return jnp.sqrt(jnp.sum(diff * diff, -1) + 1e-30)
        return jnp.sum(jnp.abs(diff) ** p, -1) ** (1.0 / p)
    return _op(f, x, y, name="cdist")


def pdist(x, p=2.0, name=None):
    n = _d(x).shape[0]
    r, c = np.triu_indices(n, 1)
    def f(a):
        diff = a[jnp.asarray(r)] - a[jnp.asarray(c)]
        if p == 2.0:
            return jnp.sqrt(jnp.sum(diff * diff, -1) + 1e-30)
        return jnp.sum(jnp.abs(diff) ** p, -1) ** (1.0 / p)
    return _op(f, x, name="pdist")


# ------------------------------ bit ops --------------------------------------

def bitwise_left_shift(x, y, is_arithmetic=True, name=None):
    return _op(jnp.left_shift, x, y, name="bitwise_left_shift")


def bitwise_right_shift(x, y, is_arithmetic=True, name=None):
    if is_arithmetic:
        return _op(jnp.right_shift, x, y, name="bitwise_right_shift")

    def f(a, b):
        # logical shift: reinterpret in the unsigned dtype of the SAME
        # width (uint32 for everything would sign-extend int8/16 and
        # truncate int64)
        ud = jnp.dtype(f"uint{a.dtype.itemsize * 8}")
        ua = a.astype(ud)
        return jax.lax.shift_right_logical(
            ua, b.astype(ud)).astype(a.dtype)
    return _op(f, x, y, name="bitwise_right_shift")
