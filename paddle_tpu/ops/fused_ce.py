"""Memory-lean softmax cross-entropy for big-vocab LM heads.

The reference fuses this on GPU as c_softmax_with_cross_entropy /
fused kernels (ref: fluid/operators/collective/c_softmax_with_
cross_entropy_op.cu, phi/kernels/fusion/). The naive XLA path materializes
an fp32 [B, L, V] log-softmax and saves it for backward — ~4 GB at
(8, 2047, 32000) — the top HBM allocation in the train step. This custom
VJP instead:

  fwd: scan over sequence chunks computing the per-position logsumexp and
       target logit in fp32 — nothing [B, L, V]-sized in fp32, nothing
       extra saved (residuals: the bf16 logits the caller already has,
       labels, and the [B, L] lse);
  bwd: scan over chunks emitting d_logits = (softmax - onehot) · g / N in
       the logits dtype.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["fused_softmax_ce_mean"]


def _chunks(seq_len: int, target: int = 256) -> int:
    """Largest chunk size <= target dividing seq_len (fallback: seq_len)."""
    for c in range(min(target, seq_len), 0, -1):
        if seq_len % c == 0:
            return c
    return seq_len


@functools.partial(jax.custom_vjp, nondiff_argnums=())
def fused_softmax_ce_mean(logits, labels):
    """mean over all positions of -log softmax(logits)[labels].
    logits: [B, L, V] (any float dtype), labels: [B, L] int."""
    loss, _ = _ce_fwd_impl(logits, labels)
    return loss


def _ce_fwd_impl(logits, labels):
    b, l, v = logits.shape
    c = _chunks(l)
    lg = logits.reshape(b, l // c, c, v)
    lb = labels.reshape(b, l // c, c)

    def chunk(carry, xs):
        lg_c, lb_c = xs  # [B, c, V], [B, c]
        f = lg_c.astype(jnp.float32)
        lse = jax.nn.logsumexp(f, axis=-1)               # [B, c]
        tgt = jnp.take_along_axis(
            f, lb_c[..., None].astype(jnp.int32), axis=-1)[..., 0]
        return carry + jnp.sum(lse - tgt), lse

    total, lses = jax.lax.scan(
        chunk, jnp.float32(0.0),
        (jnp.swapaxes(lg, 0, 1), jnp.swapaxes(lb, 0, 1)))
    lse = jnp.swapaxes(lses, 0, 1).reshape(b, l)
    return total / (b * l), lse


def _ce_vjp_fwd(logits, labels):
    loss, lse = _ce_fwd_impl(logits, labels)
    return loss, (logits, labels, lse)


def _ce_vjp_bwd(res, g):
    logits, labels, lse = res
    b, l, v = logits.shape
    c = _chunks(l)
    scale = g / (b * l)

    def chunk(_, xs):
        lg_c, lb_c, lse_c = xs
        p = jnp.exp(lg_c.astype(jnp.float32) - lse_c[..., None])
        onehot = jax.nn.one_hot(lb_c.astype(jnp.int32), v,
                                dtype=jnp.float32)
        return None, ((p - onehot) * scale).astype(logits.dtype)

    _, dl = jax.lax.scan(
        chunk, None,
        (jnp.swapaxes(logits.reshape(b, l // c, c, v), 0, 1),
         jnp.swapaxes(labels.reshape(b, l // c, c), 0, 1),
         jnp.swapaxes(lse.reshape(b, l // c, c), 0, 1)))
    return jnp.swapaxes(dl, 0, 1).reshape(b, l, v), None


fused_softmax_ce_mean.defvjp(_ce_vjp_fwd, _ce_vjp_bwd)
