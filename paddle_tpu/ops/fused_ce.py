"""Memory-lean softmax cross-entropy for big-vocab LM heads.

The reference fuses this on GPU as c_softmax_with_cross_entropy /
fused kernels (ref: fluid/operators/collective/c_softmax_with_
cross_entropy_op.cu, phi/kernels/fusion/). The naive XLA path materializes
an fp32 [B, L, V] log-softmax and saves it for backward — ~4 GB at
(8, 2047, 32000) — the top HBM allocation in the train step. This custom
VJP instead:

  fwd: scan over sequence chunks computing the per-position logsumexp and
       target logit in fp32 — nothing [B, L, V]-sized in fp32, nothing
       extra saved (residuals: the bf16 logits the caller already has,
       labels, and the [B, L] lse);
  bwd: scan over chunks emitting d_logits = (softmax - onehot) · g / N in
       the logits dtype.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["fused_softmax_ce_mean"]


def _chunks(seq_len: int, target: int = 256) -> int:
    """Largest chunk size <= target dividing seq_len (fallback: seq_len)."""
    for c in range(min(target, seq_len), 0, -1):
        if seq_len % c == 0:
            return c
    return seq_len


def _serial_chunks() -> bool:
    """True on the CPU test backend, where chunk collectives must be
    serialized through a loop (see the rendezvous note in _ce_fwd_impl)."""
    return jax.default_backend() == "cpu"


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def fused_softmax_ce_mean(logits, labels, ignore_index=None,
                          valid_count=None):
    """mean over positions of -log softmax(logits)[labels].
    logits: [B, L, V] (any float dtype), labels: [B, L] int.
    ``ignore_index``: positions with that label contribute nothing and
    are excluded from the mean's denominator (ref: cross_entropy
    ignore_index semantics, python/paddle/nn/functional/loss.py).
    ``valid_count``: static count of non-ignored positions when the
    caller knows it (e.g. the causal-LM shift masks exactly one position
    per row) — skips the dynamic count, whose cross-device reduction is
    an extra independent collective in sharded programs (it can race the
    model's own collective chain on the CPU in-process communicator)."""
    loss, _, _ = _ce_fwd_impl(logits, labels, ignore_index, valid_count)
    return loss


def _ce_fwd_impl(logits, labels, ignore_index, valid_count=None):
    b, l, v = logits.shape
    c = _chunks(l)

    # Chunk loop. On TPU: statically unrolled with static slices — a
    # scan would need the chunk axis leading, and that swapaxes
    # materializes a full [B, L, V] transpose copy (262 MB at the Llama
    # headline shape), while a fori_loop costs a per-iteration sync
    # (~0.3 ms each). Unrolled, each chunk's fp32 intermediates fuse
    # into their own reduce fusion and nothing [B, L, V]-sized exists in
    # fp32. On the CPU test backend the chunks must run through a
    # fori_loop instead: unrolled chunks over sharded logits are
    # INDEPENDENT collective chains, and XLA:CPU's in-process rendezvous
    # deadlocks when independent collectives race (real TPU collectives
    # don't have this hazard).
    def chunk_stats(lg_c, lb_c):
        f = lg_c.astype(jnp.float32)
        lse = jax.nn.logsumexp(f, axis=-1)               # [B, c]
        idx = lb_c.astype(jnp.int32)
        if ignore_index is not None:
            idx = jnp.clip(idx, 0, v - 1)  # ignored labels may be -100
        tgt = jnp.take_along_axis(f, idx[..., None], axis=-1)[..., 0]
        per = lse - tgt
        if ignore_index is not None:
            per = jnp.where(lb_c == ignore_index, 0.0, per)
        return jnp.sum(per), lse

    if _serial_chunks():
        def body(i, carry):
            total, lse_acc = carry
            s, lse = chunk_stats(
                jax.lax.dynamic_slice_in_dim(logits, i * c, c, axis=1),
                jax.lax.dynamic_slice_in_dim(labels, i * c, c, axis=1))
            lse_acc = jax.lax.dynamic_update_slice_in_dim(
                lse_acc, lse, i * c, axis=1)
            return total + s, lse_acc
        total, lse = jax.lax.fori_loop(
            0, l // c, body,
            (jnp.float32(0.0), jnp.zeros((b, l), jnp.float32)))
    else:
        total = jnp.float32(0.0)
        lses = []
        for i in range(l // c):
            s, lse = chunk_stats(
                jax.lax.slice_in_dim(logits, i * c, (i + 1) * c, axis=1),
                jax.lax.slice_in_dim(labels, i * c, (i + 1) * c, axis=1))
            total = total + s
            lses.append(lse)
        lse = jnp.concatenate(lses, axis=1)
    if valid_count is not None:
        n_valid = jnp.float32(max(int(valid_count), 1))
    elif ignore_index is None:
        n_valid = jnp.float32(b * l)
    else:
        n_valid = jnp.maximum(
            jnp.sum(labels != ignore_index).astype(jnp.float32), 1.0)
    return total / n_valid, lse, n_valid


def _ce_vjp_fwd(logits, labels, ignore_index, valid_count=None):
    loss, lse, n_valid = _ce_fwd_impl(logits, labels, ignore_index,
                                      valid_count)
    return loss, (logits, labels, lse, n_valid)


def _ce_vjp_bwd(ignore_index, valid_count, res, g):
    logits, labels, lse, n_valid = res
    b, l, v = logits.shape
    c = _chunks(l)
    scale = g / n_valid

    def chunk_grad(lg_c, lb_c, lse_c):
        p = jnp.exp(lg_c.astype(jnp.float32) - lse_c[..., None])
        idx = lb_c.astype(jnp.int32)
        if ignore_index is not None:
            idx = jnp.clip(idx, 0, v - 1)
        onehot = jax.nn.one_hot(idx, v, dtype=jnp.float32)
        d = (p - onehot) * scale
        if ignore_index is not None:
            d = jnp.where((lb_c == ignore_index)[..., None], 0.0, d)
        return d.astype(logits.dtype)

    if _serial_chunks():  # see _ce_fwd_impl: XLA:CPU rendezvous hazard
        def body(i, dl):
            d = chunk_grad(
                jax.lax.dynamic_slice_in_dim(logits, i * c, c, axis=1),
                jax.lax.dynamic_slice_in_dim(labels, i * c, c, axis=1),
                jax.lax.dynamic_slice_in_dim(lse, i * c, c, axis=1))
            return jax.lax.dynamic_update_slice_in_dim(dl, d, i * c,
                                                       axis=1)
        return jax.lax.fori_loop(
            0, l // c, body, jnp.zeros((b, l, v), logits.dtype)), None
    chunks = []
    for i in range(l // c):
        chunks.append(chunk_grad(
            jax.lax.slice_in_dim(logits, i * c, (i + 1) * c, axis=1),
            jax.lax.slice_in_dim(labels, i * c, (i + 1) * c, axis=1),
            jax.lax.slice_in_dim(lse, i * c, (i + 1) * c, axis=1)))
    return jnp.concatenate(chunks, axis=1), None


fused_softmax_ce_mean.defvjp(_ce_vjp_fwd, _ce_vjp_bwd)
