"""Memory-lean softmax cross-entropy for big-vocab LM heads.

The reference fuses this on GPU as c_softmax_with_cross_entropy /
fused kernels (ref: fluid/operators/collective/c_softmax_with_
cross_entropy_op.cu, phi/kernels/fusion/). The naive XLA path materializes
an fp32 [B, L, V] log-softmax and saves it for backward — ~4 GB at
(8, 2047, 32000) — the top HBM allocation in the train step. This custom
VJP instead:

  fwd: scan over sequence chunks computing the per-position logsumexp and
       target logit in fp32 — nothing [B, L, V]-sized in fp32, nothing
       extra saved (residuals: the bf16 logits the caller already has,
       labels, and the [B, L] lse);
  bwd: scan over chunks emitting d_logits = (softmax - onehot) · g / N in
       the logits dtype.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["fused_softmax_ce_mean"]


def _chunks(seq_len: int, target: int = 256) -> int:
    """Largest chunk size <= target dividing seq_len (fallback: seq_len)."""
    for c in range(min(target, seq_len), 0, -1):
        if seq_len % c == 0:
            return c
    return seq_len


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def fused_softmax_ce_mean(logits, labels, ignore_index=None):
    """mean over positions of -log softmax(logits)[labels].
    logits: [B, L, V] (any float dtype), labels: [B, L] int.
    ``ignore_index``: positions with that label contribute nothing and
    are excluded from the mean's denominator (ref: cross_entropy
    ignore_index semantics, python/paddle/nn/functional/loss.py)."""
    loss, _, _ = _ce_fwd_impl(logits, labels, ignore_index)
    return loss


def _ce_fwd_impl(logits, labels, ignore_index):
    b, l, v = logits.shape
    c = _chunks(l)
    lg = logits.reshape(b, l // c, c, v)
    lb = labels.reshape(b, l // c, c)

    def chunk(carry, xs):
        lg_c, lb_c = xs  # [B, c, V], [B, c]
        f = lg_c.astype(jnp.float32)
        lse = jax.nn.logsumexp(f, axis=-1)               # [B, c]
        idx = lb_c.astype(jnp.int32)
        if ignore_index is not None:
            idx = jnp.clip(idx, 0, v - 1)  # ignored labels may be -100
        tgt = jnp.take_along_axis(f, idx[..., None], axis=-1)[..., 0]
        per = lse - tgt
        if ignore_index is not None:
            per = jnp.where(lb_c == ignore_index, 0.0, per)
        return carry + jnp.sum(per), lse

    total, lses = jax.lax.scan(
        chunk, jnp.float32(0.0),
        (jnp.swapaxes(lg, 0, 1), jnp.swapaxes(lb, 0, 1)))
    lse = jnp.swapaxes(lses, 0, 1).reshape(b, l)
    if ignore_index is None:
        n_valid = jnp.float32(b * l)
    else:
        n_valid = jnp.maximum(
            jnp.sum(labels != ignore_index).astype(jnp.float32), 1.0)
    return total / n_valid, lse, n_valid


def _ce_vjp_fwd(logits, labels, ignore_index):
    loss, lse, n_valid = _ce_fwd_impl(logits, labels, ignore_index)
    return loss, (logits, labels, lse, n_valid)


def _ce_vjp_bwd(ignore_index, res, g):
    logits, labels, lse, n_valid = res
    b, l, v = logits.shape
    c = _chunks(l)
    scale = g / n_valid

    def chunk(_, xs):
        lg_c, lb_c, lse_c = xs
        p = jnp.exp(lg_c.astype(jnp.float32) - lse_c[..., None])
        idx = lb_c.astype(jnp.int32)
        if ignore_index is not None:
            idx = jnp.clip(idx, 0, v - 1)
        onehot = jax.nn.one_hot(idx, v, dtype=jnp.float32)
        d = (p - onehot) * scale
        if ignore_index is not None:
            d = jnp.where((lb_c == ignore_index)[..., None], 0.0, d)
        return None, d.astype(logits.dtype)

    _, dl = jax.lax.scan(
        chunk, None,
        (jnp.swapaxes(logits.reshape(b, l // c, c, v), 0, 1),
         jnp.swapaxes(labels.reshape(b, l // c, c), 0, 1),
         jnp.swapaxes(lse.reshape(b, l // c, c), 0, 1)))
    return jnp.swapaxes(dl, 0, 1).reshape(b, l, v), None


fused_softmax_ce_mean.defvjp(_ce_vjp_fwd, _ce_vjp_bwd)
