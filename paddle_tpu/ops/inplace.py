"""Inplace op variants (`op_`) and remaining top-level API odds and ends.

ref: python/paddle/tensor/math.py et al. define `op_` siblings that write
the result into the input tensor. Tensors here wrap immutable jax.Arrays,
so "inplace" = compute functionally, then swap the wrapper's buffer — the
same user-visible contract (the reference's inplace ops likewise break
gradient history unless whitelisted).
"""
from __future__ import annotations

from typing import List

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor

__all__: List[str] = []  # populated by _install()

# top-level functional name -> inplace method/function name
_INPLACE_UNARY = [
    "abs", "acos", "asin", "atan", "atanh", "ceil", "cos", "cosh", "erf",
    "exp", "expm1", "floor", "lgamma", "log", "log10", "log1p", "log2",
    "neg", "reciprocal", "round", "rsqrt", "sigmoid", "sin", "sinh",
    "sqrt", "square", "tan", "tanh", "trunc", "digamma", "frac", "i0",
    "sinc", "logit",
]
_INPLACE_BINARY = [
    "add", "subtract", "multiply", "divide", "remainder", "mod",
    "floor_divide", "floor_mod", "pow", "maximum", "minimum",
    "logical_and", "logical_or", "logical_not", "logical_xor",
    "bitwise_and", "bitwise_or", "bitwise_xor", "bitwise_not",
    "less_than", "less_equal", "greater_than", "greater_equal", "equal",
    "hypot", "copysign", "ldexp", "gcd", "lcm",
    "bitwise_left_shift", "bitwise_right_shift",
]
_INPLACE_OTHER = [
    "clip", "scale", "cumsum", "cumprod", "flatten", "squeeze",
    "unsqueeze", "transpose", "tril", "triu", "cast", "lerp",
    "index_add", "index_put", "index_fill", "masked_fill",
    "masked_scatter", "scatter", "nan_to_num", "renorm", "polygamma",
    "gammainc", "gammaincc", "gammaln", "multigammaln", "t",
]


def _functional(name):
    from .. import ops as _ops
    return getattr(_ops, name, None)


def _make_inplace(fname):
    fn = _functional(fname)
    if fn is None:
        return None

    def inplace(self, *args, **kwargs):
        from ..core import tensor as tensor_mod
        if tensor_mod._mutation_hook is not None:
            tensor_mod._mutation_hook(self)
        out = fn(self, *args, **kwargs)
        self._data = out._data if isinstance(out, Tensor) else out
        return self

    inplace.__name__ = fname + "_"
    inplace.__doc__ = (f"Inplace variant of paddle.{fname} "
                       f"(ref: tensor/*.py {fname}_)")
    return inplace


def _install():
    import paddle_tpu as _p

    installed = []
    for fname in _INPLACE_UNARY + _INPLACE_BINARY + _INPLACE_OTHER:
        if hasattr(Tensor, fname + "_"):
            installed.append(fname + "_")
            continue
        method = _make_inplace(fname)
        if method is None:
            continue
        setattr(Tensor, fname + "_", method)

        # top-level paddle.op_(x, ...) form mirrors the method
        def _toplevel(x, *args, _m=fname + "_", **kwargs):
            return getattr(x, _m)(*args, **kwargs)
        _toplevel.__name__ = fname + "_"
        setattr(_p, fname + "_", _toplevel)
        installed.append(fname + "_")

    # random inplace fills (ref: tensor/random.py)
    from ..core import random as random_mod
    import jax

    def normal_(self, mean=0.0, std=1.0):
        key = random_mod.next_key()
        self._data = (mean + std * jax.random.normal(
            key, self._data.shape)).astype(self._data.dtype)
        return self

    def bernoulli_(self, p=0.5):
        key = random_mod.next_key()
        self._data = jax.random.bernoulli(
            key, p, self._data.shape).astype(self._data.dtype)
        return self

    def cauchy_(self, loc=0, scale=1):
        key = random_mod.next_key()
        self._data = (loc + scale * jax.random.cauchy(
            key, self._data.shape)).astype(self._data.dtype)
        return self

    def geometric_(self, probs):
        # continuous form, matching the reference's
        # uniform_().log_().divide_(log1p(-probs)) chain
        # (ref: tensor/creation.py:3225)
        key = random_mod.next_key()
        u = jax.random.uniform(key, self._data.shape, minval=1e-7,
                               maxval=1.0)
        self._data = (jnp.log(u) / jnp.log1p(-probs)) \
            .astype(self._data.dtype)
        return self

    def log_normal_(self, mean=1.0, std=2.0):
        key = random_mod.next_key()
        self._data = jnp.exp(mean + std * jax.random.normal(
            key, self._data.shape)).astype(self._data.dtype)
        return self

    def uniform_(self, min=-1.0, max=1.0, seed=0):
        key = random_mod.next_key()
        self._data = jax.random.uniform(
            key, self._data.shape, minval=min,
            maxval=max).astype(self._data.dtype)
        return self

    def exponential_(self, lam=1.0):
        key = random_mod.next_key()
        self._data = (jax.random.exponential(key, self._data.shape)
                      / lam).astype(self._data.dtype)
        return self

    for fn in (normal_, bernoulli_, cauchy_, geometric_, log_normal_,
               uniform_, exponential_):
        setattr(Tensor, fn.__name__, fn)
        installed.append(fn.__name__)

    if not hasattr(Tensor, "tolist"):
        Tensor.tolist = lambda self: np.asarray(self.numpy()).tolist()

    __all__.extend(installed)


_install()
