"""Math ops (elementwise, reduction, comparison, logical).

ref: python/paddle/tensor/math.py, logic.py, search.py. Each op is a thin
differentiable wrapper over the jnp equivalent via ``apply_op`` — gradients
come from jax.vjp, so there is no per-op grad kernel to maintain (the analog
of the reference's ~2,663 PHI kernel registrations collapses to XLA).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..core import fusion as _fusion
from ..core.autograd import apply_op
from ..core.dtype import convert_dtype
from ..core.tensor import Tensor


def _t(x):
    """Coerce python scalars / numpy to Tensor-or-raw for apply_op."""
    if isinstance(x, Tensor):
        return x
    return x  # raw scalars pass straight through to jnp


def _unary(jfn, name):
    # pin jfn as the op's canonical impl for lazy-eager chain fusion;
    # whether dispatches actually defer is gated by ops.yaml `fusable`
    _fusion.register_impl(name, jfn)

    def op(x, name=None):
        return apply_op(jfn, _t(x), op_name=name)
    op.__name__ = name
    return op


def _binary(jfn, name):
    _fusion.register_impl(name, jfn)

    def op(x, y, name=None):
        return apply_op(jfn, _t(x), _t(y), op_name=name)
    op.__name__ = name
    return op


# -- elementwise unary -------------------------------------------------------
abs = _unary(jnp.abs, "abs")
sqrt = _unary(jnp.sqrt, "sqrt")
rsqrt = _unary(jax.lax.rsqrt, "rsqrt")
exp = _unary(jnp.exp, "exp")
expm1 = _unary(jnp.expm1, "expm1")
log = _unary(jnp.log, "log")
log2 = _unary(jnp.log2, "log2")
log10 = _unary(jnp.log10, "log10")
log1p = _unary(jnp.log1p, "log1p")
sin = _unary(jnp.sin, "sin")
cos = _unary(jnp.cos, "cos")
tan = _unary(jnp.tan, "tan")
asin = _unary(jnp.arcsin, "asin")
acos = _unary(jnp.arccos, "acos")
atan = _unary(jnp.arctan, "atan")
sinh = _unary(jnp.sinh, "sinh")
cosh = _unary(jnp.cosh, "cosh")
tanh = _unary(jnp.tanh, "tanh")
asinh = _unary(jnp.arcsinh, "asinh")
acosh = _unary(jnp.arccosh, "acosh")
atanh = _unary(jnp.arctanh, "atanh")
floor = _unary(jnp.floor, "floor")
ceil = _unary(jnp.ceil, "ceil")
round = _unary(jnp.round, "round")
trunc = _unary(jnp.trunc, "trunc")
frac = _unary(lambda x: x - jnp.trunc(x), "frac")
sign = _unary(jnp.sign, "sign")
neg = _unary(jnp.negative, "neg")
reciprocal = _unary(lambda x: 1.0 / x, "reciprocal")
square = _unary(jnp.square, "square")
sigmoid = _unary(jax.nn.sigmoid, "sigmoid")
erf = _unary(jax.scipy.special.erf, "erf")
erfinv = _unary(jax.scipy.special.erfinv, "erfinv")
lgamma = _unary(jax.scipy.special.gammaln, "lgamma")
digamma = _unary(jax.scipy.special.digamma, "digamma")
angle = _unary(jnp.angle, "angle")
conj = _unary(jnp.conj, "conj")
real = _unary(jnp.real, "real")
imag = _unary(jnp.imag, "imag")

# -- elementwise binary ------------------------------------------------------
add = _binary(jnp.add, "add")
subtract = _binary(jnp.subtract, "subtract")
multiply = _binary(jnp.multiply, "multiply")
divide = _binary(jnp.divide, "divide")
mod = _binary(jnp.mod, "mod")
remainder = mod
floor_mod = mod
pow = _binary(jnp.power, "pow")
maximum = _binary(jnp.maximum, "maximum")
minimum = _binary(jnp.minimum, "minimum")
fmax = _binary(jnp.fmax, "fmax")
fmin = _binary(jnp.fmin, "fmin")
atan2 = _binary(jnp.arctan2, "atan2")
hypot = _binary(jnp.hypot, "hypot")
logaddexp = _binary(jnp.logaddexp, "logaddexp")


def floor_divide(x, y, name=None):
    return apply_op(jnp.floor_divide, _t(x), _t(y), op_name="floor_divide")


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    if bias_after_scale:
        out = apply_op(lambda a: a * scale + bias, _t(x), op_name="scale")
    else:
        out = apply_op(lambda a: (a + bias) * scale, _t(x), op_name="scale")
    return out


def clip(x, min=None, max=None, name=None):
    mn = min.item() if isinstance(min, Tensor) else min
    mx = max.item() if isinstance(max, Tensor) else max
    return apply_op(lambda a: jnp.clip(a, mn, mx), _t(x), op_name="clip")


def lerp(x, y, weight, name=None):
    return apply_op(lambda a, b, w: a + w * (b - a), _t(x), _t(y), _t(weight),
                    op_name="lerp")


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return apply_op(lambda a: scale_b * jnp.tanh(scale_a * a), _t(x),
                    op_name="stanh")


def multiply_(x, y):
    x._data = x._data * (y._data if isinstance(y, Tensor) else y)
    return x


# -- reductions --------------------------------------------------------------
def _norm_axis(axis):
    if isinstance(axis, Tensor):
        axis = tuple(int(a) for a in np.asarray(axis._data))
    if isinstance(axis, np.ndarray):
        axis = tuple(int(a) for a in axis.reshape(-1))
    if isinstance(axis, list):
        axis = tuple(axis)
    if isinstance(axis, np.integer):
        axis = int(axis)
    return axis


# Reduction terminators: each op has a module-level parametric impl
# ``fn(a, **attrs)`` registered for fusion codegen (`fusable: reduce` in
# ops.yaml), and its wrapper passes the SAME attrs — normalized hashable
# (axis/dtype/keepdim) — to apply_op as fuse_attrs so the dispatch can
# join a pending chain as a terminator node instead of flushing it. The
# per-call lambda stays the eager/fallback body; impl and lambda compute
# identically by construction (the lambda closes over the impl).

def _sum_impl(a, axis=None, dtype=None, keepdim=False):
    return jnp.sum(a, axis=axis, dtype=dtype, keepdims=keepdim)


def _mean_impl(a, axis=None, keepdim=False):
    return jnp.mean(a, axis=axis, keepdims=keepdim)


def _prod_impl(a, axis=None, dtype=None, keepdim=False):
    return jnp.prod(a, axis=axis, dtype=dtype, keepdims=keepdim)


def _max_impl(a, axis=None, keepdim=False):
    return jnp.max(a, axis=axis, keepdims=keepdim)


def _min_impl(a, axis=None, keepdim=False):
    return jnp.min(a, axis=axis, keepdims=keepdim)


def _logsumexp_impl(a, axis=None, keepdim=False):
    return jax.scipy.special.logsumexp(a, axis=axis, keepdims=keepdim)


def _squared_l2_norm_impl(a):
    return jnp.sum(jnp.square(a))


for _n, _f in (("sum", _sum_impl), ("mean", _mean_impl),
               ("prod", _prod_impl), ("max", _max_impl),
               ("min", _min_impl), ("amax", _max_impl),
               ("amin", _min_impl), ("logsumexp", _logsumexp_impl),
               ("squared_l2_norm", _squared_l2_norm_impl)):
    _fusion.register_param_impl(_n, _f)


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    d = convert_dtype(dtype)
    ax, kd = _norm_axis(axis), bool(keepdim)
    return apply_op(
        lambda a: _sum_impl(a, axis=ax, dtype=d, keepdim=kd),
        _t(x), op_name="sum",
        fuse_attrs=(("axis", ax), ("dtype", d), ("keepdim", kd)))


def mean(x, axis=None, keepdim=False, name=None):
    ax, kd = _norm_axis(axis), bool(keepdim)
    return apply_op(
        lambda a: _mean_impl(a, axis=ax, keepdim=kd),
        _t(x), op_name="mean",
        fuse_attrs=(("axis", ax), ("keepdim", kd)))


def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    d = convert_dtype(dtype)
    ax, kd = _norm_axis(axis), bool(keepdim)
    return apply_op(
        lambda a: _prod_impl(a, axis=ax, dtype=d, keepdim=kd),
        _t(x), op_name="prod",
        fuse_attrs=(("axis", ax), ("dtype", d), ("keepdim", kd)))


def max(x, axis=None, keepdim=False, name=None):
    ax, kd = _norm_axis(axis), bool(keepdim)
    return apply_op(
        lambda a: _max_impl(a, axis=ax, keepdim=kd),
        _t(x), op_name="max",
        fuse_attrs=(("axis", ax), ("keepdim", kd)))


def min(x, axis=None, keepdim=False, name=None):
    ax, kd = _norm_axis(axis), bool(keepdim)
    return apply_op(
        lambda a: _min_impl(a, axis=ax, keepdim=kd),
        _t(x), op_name="min",
        fuse_attrs=(("axis", ax), ("keepdim", kd)))


def amax(x, axis=None, keepdim=False, name=None):
    ax, kd = _norm_axis(axis), bool(keepdim)
    return apply_op(
        lambda a: _max_impl(a, axis=ax, keepdim=kd),
        _t(x), op_name="amax",
        fuse_attrs=(("axis", ax), ("keepdim", kd)))


def amin(x, axis=None, keepdim=False, name=None):
    ax, kd = _norm_axis(axis), bool(keepdim)
    return apply_op(
        lambda a: _min_impl(a, axis=ax, keepdim=kd),
        _t(x), op_name="amin",
        fuse_attrs=(("axis", ax), ("keepdim", kd)))


def squared_l2_norm(x, name=None):
    """sum(x**2) as one fused full reduction — the global-grad-norm
    building block (ref: paddle._C_ops.squared_l2_norm, used by
    ClipGradByGlobalNorm)."""
    return apply_op(lambda a: _squared_l2_norm_impl(a), _t(x),
                    op_name="squared_l2_norm", fuse_attrs=())


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    ddof = 1 if unbiased else 0
    return apply_op(
        lambda a: jnp.std(a, axis=_norm_axis(axis), ddof=ddof,
                          keepdims=keepdim), _t(x), op_name="std")


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    ddof = 1 if unbiased else 0
    return apply_op(
        lambda a: jnp.var(a, axis=_norm_axis(axis), ddof=ddof,
                          keepdims=keepdim), _t(x), op_name="var")


def median(x, axis=None, keepdim=False, name=None):
    return apply_op(
        lambda a: jnp.median(a, axis=_norm_axis(axis), keepdims=keepdim),
        _t(x), op_name="median")


def logsumexp(x, axis=None, keepdim=False, name=None):
    ax, kd = _norm_axis(axis), bool(keepdim)
    return apply_op(
        lambda a: _logsumexp_impl(a, axis=ax, keepdim=kd),
        _t(x), op_name="logsumexp",
        fuse_attrs=(("axis", ax), ("keepdim", kd)))


def cumsum(x, axis=None, dtype=None, name=None):
    d = convert_dtype(dtype)
    def f(a):
        if axis is None:
            return jnp.cumsum(a.reshape(-1), dtype=d)
        return jnp.cumsum(a, axis=axis, dtype=d)
    return apply_op(f, _t(x), op_name="cumsum")


def cumprod(x, dim=None, dtype=None, name=None):
    d = convert_dtype(dtype)
    return apply_op(lambda a: jnp.cumprod(a, axis=dim, dtype=d), _t(x),
                    op_name="cumprod")


def cummax(x, axis=None, dtype="int64", name=None):
    # values are differentiable (grad scatters to the running-max
    # positions); dispatch through the tape — direct Tensor()
    # construction silently dropped gradients
    def f(xd):
        if axis is None:
            xd2, ax = xd.reshape(-1), 0
        else:
            xd2, ax = xd, axis
        pos = jnp.arange(xd2.shape[ax]).reshape(
            [-1 if i == ax else 1 for i in range(xd2.ndim)])
        pos = jnp.broadcast_to(pos, xd2.shape)

        def combine(a, b):
            av, ai = a
            bv, bi = b
            take_b = bv >= av  # paddle keeps the later index on ties
            return jnp.where(take_b, bv, av), jnp.where(take_b, bi, ai)

        vals, idx = jax.lax.associative_scan(combine, (xd2, pos),
                                             axis=ax)
        return vals, idx.astype(convert_dtype(dtype))

    return apply_op(f, _t(x), op_name="cummax")


def cummin(x, axis=None, dtype="int64", name=None):
    neg_vals, idx = cummax(-_t(x), axis=axis, dtype=dtype)
    return -neg_vals, idx


# -- comparison / logical ----------------------------------------------------
equal = _binary(jnp.equal, "equal")
not_equal = _binary(jnp.not_equal, "not_equal")
greater_than = _binary(jnp.greater, "greater_than")
greater_equal = _binary(jnp.greater_equal, "greater_equal")
less_than = _binary(jnp.less, "less_than")
less_equal = _binary(jnp.less_equal, "less_equal")
logical_and = _binary(jnp.logical_and, "logical_and")
logical_or = _binary(jnp.logical_or, "logical_or")
logical_xor = _binary(jnp.logical_xor, "logical_xor")
logical_not = _unary(jnp.logical_not, "logical_not")
bitwise_and = _binary(jnp.bitwise_and, "bitwise_and")
bitwise_or = _binary(jnp.bitwise_or, "bitwise_or")
bitwise_xor = _binary(jnp.bitwise_xor, "bitwise_xor")
bitwise_not = _unary(jnp.bitwise_not, "bitwise_not")
isnan = _unary(jnp.isnan, "isnan")
isinf = _unary(jnp.isinf, "isinf")
isfinite = _unary(jnp.isfinite, "isfinite")


def equal_all(x, y, name=None):
    return apply_op(lambda a, b: jnp.array_equal(a, b), _t(x), _t(y),
                    op_name="equal_all")


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply_op(
        lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol,
                                  equal_nan=equal_nan),
        _t(x), _t(y), op_name="allclose")


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply_op(
        lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol,
                                 equal_nan=equal_nan),
        _t(x), _t(y), op_name="isclose")


def all(x, axis=None, keepdim=False, name=None):
    return apply_op(
        lambda a: jnp.all(a, axis=_norm_axis(axis), keepdims=keepdim),
        _t(x), op_name="all")


def any(x, axis=None, keepdim=False, name=None):
    return apply_op(
        lambda a: jnp.any(a, axis=_norm_axis(axis), keepdims=keepdim),
        _t(x), op_name="any")


# -- search / sort -----------------------------------------------------------
def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    d = convert_dtype(dtype)
    def f(a):
        r = jnp.argmax(a, axis=axis, keepdims=keepdim and axis is not None)
        return r.astype(d)
    return apply_op(f, _t(x), op_name="argmax")


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    d = convert_dtype(dtype)
    def f(a):
        r = jnp.argmin(a, axis=axis, keepdims=keepdim and axis is not None)
        return r.astype(d)
    return apply_op(f, _t(x), op_name="argmin")


def argsort(x, axis=-1, descending=False, stable=False, name=None):
    def f(a):
        r = jnp.argsort(a, axis=axis, stable=True)
        if descending:
            r = jnp.flip(r, axis=axis)
        return r.astype(jnp.int64)
    return apply_op(f, _t(x), op_name="argsort")


def sort(x, axis=-1, descending=False, stable=False, name=None):
    def f(a):
        r = jnp.sort(a, axis=axis, stable=True)
        if descending:
            r = jnp.flip(r, axis=axis)
        return r
    return apply_op(f, _t(x), op_name="sort")


def topk(x, k, axis=-1, largest=True, sorted=True, name=None):
    if isinstance(k, Tensor):
        k = int(k.item())

    def f(a):
        ax = axis if axis >= 0 else a.ndim + axis
        moved = jnp.moveaxis(a, ax, -1)
        src = moved if largest else -moved
        vals, idx = jax.lax.top_k(src, k)
        if not largest:
            vals = -vals
        return (jnp.moveaxis(vals, -1, ax),
                jnp.moveaxis(idx.astype(jnp.int64), -1, ax))
    return apply_op(f, _t(x), op_name="topk")


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    def f(a):
        s = jnp.sort(a, axis=axis)
        i = jnp.argsort(a, axis=axis, stable=True)
        v = jnp.take(s, k - 1, axis=axis)
        ix = jnp.take(i, k - 1, axis=axis).astype(jnp.int64)
        if keepdim:
            v = jnp.expand_dims(v, axis)
            ix = jnp.expand_dims(ix, axis)
        return v, ix
    return apply_op(f, _t(x), op_name="kthvalue")


def mode(x, axis=-1, keepdim=False, name=None):
    # values differentiable (grad scatters to the selected occurrence,
    # like median); dispatched through the tape
    return apply_op(lambda a: _mode_impl(a, axis, keepdim), _t(x),
                    op_name="mode")


def _mode_impl(xd, axis, keepdim):
    ax = axis if axis >= 0 else xd.ndim + axis
    moved = jnp.moveaxis(xd, ax, -1)
    batch_shape, n = moved.shape[:-1], moved.shape[-1]
    flat = moved.reshape(-1, n)
    s = jnp.sort(flat, axis=-1)

    def counts(row_sorted):
        lo = jnp.searchsorted(row_sorted, row_sorted, side="left")
        hi = jnp.searchsorted(row_sorted, row_sorted, side="right")
        return hi - lo

    cnt = jax.vmap(counts)(s)
    best = jnp.argmax(cnt, axis=-1, keepdims=True)
    # stop_gradient: the mode VALUE is selected through the sorted copy,
    # but the gradient must scatter to the REPORTED occurrence (paddle's
    # mode_grad contract) — so re-gather from the original positions
    sel = jax.lax.stop_gradient(jnp.take_along_axis(s, best, axis=-1))
    occ = flat == sel
    idx = (n - 1) - jnp.argmax(occ[:, ::-1], axis=-1, keepdims=True)
    vals = jnp.take_along_axis(flat, idx, axis=-1)
    vals = jnp.moveaxis(vals.reshape(batch_shape + (1,)), -1, ax)
    idx = jnp.moveaxis(idx.reshape(batch_shape + (1,)), -1, ax)
    if not keepdim:
        vals, idx = jnp.squeeze(vals, ax), jnp.squeeze(idx, ax)
    return vals, idx.astype(jnp.int64)


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    xd = np.asarray(x._data if isinstance(x, Tensor) else x)
    res = np.unique(xd, return_index=return_index,
                    return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        return Tensor(jnp.asarray(res))
    return tuple(Tensor(jnp.asarray(r)) for r in res)


def searchsorted(sorted_sequence, values, out_int32=False, right=False,
                 name=None):
    def f(s, v):
        r = jnp.searchsorted(s, v, side="right" if right else "left")
        return r.astype(jnp.int32 if out_int32 else jnp.int64)
    return apply_op(f, _t(sorted_sequence), _t(values),
                    op_name="searchsorted")


def index_sample(x, index):
    def f(a, idx):
        return jnp.take_along_axis(a, idx, axis=1)
    return apply_op(f, _t(x), _t(index), op_name="index_sample")


def bincount(x, weights=None, minlength=0, name=None):
    xd = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    wd = weights._data if isinstance(weights, Tensor) else weights
    n = int(jnp.maximum(jnp.max(xd) + 1, minlength)) if xd.size else minlength
    return Tensor(jnp.bincount(xd, wd, length=n))


def nanmean(x, axis=None, keepdim=False, name=None):
    return apply_op(
        lambda a: jnp.nanmean(a, axis=_norm_axis(axis), keepdims=keepdim),
        _t(x), op_name="nanmean")


def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    d = convert_dtype(dtype)
    return apply_op(
        lambda a: jnp.nansum(a, axis=_norm_axis(axis), dtype=d,
                             keepdims=keepdim), _t(x), op_name="nansum")


def count_nonzero(x, axis=None, keepdim=False, name=None):
    return apply_op(
        lambda a: jnp.count_nonzero(a, axis=_norm_axis(axis),
                                    keepdims=keepdim).astype(jnp.int64),
        _t(x), op_name="count_nonzero")


def nonzero(x, as_tuple=False):
    xd = np.asarray(x._data if isinstance(x, Tensor) else x)
    idx = np.nonzero(xd)
    if as_tuple:
        return tuple(Tensor(jnp.asarray(i[:, None])) for i in idx)
    return Tensor(jnp.asarray(np.stack(idx, axis=1).astype(np.int64)))
