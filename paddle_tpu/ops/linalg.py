"""Linear-algebra ops. ref: python/paddle/tensor/linalg.py, einsum.py.

matmul is the MXU hot path: inputs stay in their dtype (bf16 preferred) and
XLA chooses fp32 accumulation on TPU by default.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.autograd import apply_op
from ..core.tensor import Tensor


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    def f(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        return jnp.matmul(a, b)
    return apply_op(f, x, y, op_name="matmul")


def mm(input, mat2, name=None):
    return matmul(input, mat2)


def bmm(x, y, name=None):
    return matmul(x, y)


def dot(x, y, name=None):
    return apply_op(lambda a, b: jnp.sum(a * b, axis=-1), x, y, op_name="dot")


def inner(x, y, name=None):
    return apply_op(jnp.inner, x, y, op_name="inner")


def outer(x, y, name=None):
    return apply_op(lambda a, b: jnp.outer(a, b), x, y, op_name="outer")


def cross(x, y, axis=9, name=None):
    def f(a, b):
        ax = axis
        if ax == 9:  # paddle default: first axis with dim 3
            ax = next(i for i, s in enumerate(a.shape) if s == 3)
        return jnp.cross(a, b, axis=ax)
    return apply_op(f, x, y, op_name="cross")


def t(input, name=None):
    return apply_op(lambda a: a.T, input, op_name="t")


def norm(x, p=None, axis=None, keepdim=False, name=None):
    def f(a):
        if p is None or p == "fro":
            if axis is None:
                return jnp.sqrt(jnp.sum(a * a))
            return jnp.linalg.norm(a, ord=None, axis=_ax(axis),
                                   keepdims=keepdim)
        if p == "inf" or p == float("inf"):
            ordv = jnp.inf
        elif p == "-inf" or p == -float("inf"):
            ordv = -jnp.inf
        else:
            ordv = p
        if axis is None:
            return jnp.linalg.norm(a.reshape(-1), ord=ordv)
        return jnp.linalg.norm(a, ord=ordv, axis=_ax(axis), keepdims=keepdim)
    return apply_op(f, x, op_name="norm")


def _ax(axis):
    if isinstance(axis, list):
        return tuple(axis)
    return axis


def dist(x, y, p=2, name=None):
    return apply_op(
        lambda a, b: jnp.linalg.norm((a - b).reshape(-1), ord=p), x, y,
        op_name="dist")


def einsum(equation, *operands):
    return apply_op(lambda *ops: jnp.einsum(equation, *ops), *operands,
                    op_name="einsum")


def transpose(x, perm, name=None):
    return apply_op(lambda a: jnp.transpose(a, perm), x, op_name="transpose")


def cholesky(x, upper=False, name=None):
    def f(a):
        L = jnp.linalg.cholesky(a)
        return jnp.swapaxes(L, -1, -2) if upper else L
    return apply_op(f, x, op_name="cholesky")


def cholesky_solve(x, y, upper=False, name=None):
    def f(b, L):
        return jax.scipy.linalg.cho_solve((L, not upper), b)
    return apply_op(f, x, y, op_name="cholesky_solve")


def inverse(x, name=None):
    return apply_op(jnp.linalg.inv, x, op_name="inverse")


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return apply_op(lambda a: jnp.linalg.pinv(a, rtol=rcond,
                                              hermitian=hermitian), x,
                    op_name="pinv")


def solve(x, y, name=None):
    return apply_op(jnp.linalg.solve, x, y, op_name="solve")


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False,
                     name=None):
    def f(a, b):
        return jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0,
            unit_diagonal=unitriangular)
    return apply_op(f, x, y, op_name="triangular_solve")


def lstsq(x, y, rcond=None, driver=None, name=None):
    xd = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    yd = y._data if isinstance(y, Tensor) else jnp.asarray(y)
    sol, res, rank, sv = jnp.linalg.lstsq(xd, yd, rcond=rcond)
    return (Tensor(sol), Tensor(res), Tensor(rank), Tensor(sv))


def qr(x, mode="reduced", name=None):
    xd = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    q, r = jnp.linalg.qr(xd, mode=mode)
    return Tensor(q), Tensor(r)


def svd(x, full_matrices=False, name=None):
    """Returns (U, S, VH) — VH is the conjugate transpose of V, matching the
    reference contract (ref: python/paddle/tensor/linalg.py svd Returns)."""
    xd = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    u, s, vh = jnp.linalg.svd(xd, full_matrices=full_matrices)
    return Tensor(u), Tensor(s), Tensor(vh)


def eig(x, name=None):
    xd = np.asarray(x._data if isinstance(x, Tensor) else x)
    w, v = np.linalg.eig(xd)
    return Tensor(jnp.asarray(w)), Tensor(jnp.asarray(v))


def eigh(x, UPLO="L", name=None):
    xd = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    w, v = jnp.linalg.eigh(xd, UPLO=UPLO)
    return Tensor(w), Tensor(v)


def eigvals(x, name=None):
    xd = np.asarray(x._data if isinstance(x, Tensor) else x)
    return Tensor(jnp.asarray(np.linalg.eigvals(xd)))


def eigvalsh(x, UPLO="L", name=None):
    return apply_op(lambda a: jnp.linalg.eigvalsh(a, UPLO=UPLO), x,
                    op_name="eigvalsh")


def det(x, name=None):
    return apply_op(jnp.linalg.det, x, op_name="det")


def slogdet(x, name=None):
    xd = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    sign, logdet = jnp.linalg.slogdet(xd)
    return Tensor(jnp.stack([sign, logdet]))


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return apply_op(
        lambda a: jnp.linalg.matrix_rank(a, rtol=tol).astype(jnp.int64), x,
        op_name="matrix_rank")


def matrix_power(x, n, name=None):
    return apply_op(lambda a: jnp.linalg.matrix_power(a, n), x,
                    op_name="matrix_power")


def multi_dot(x, name=None):
    return apply_op(lambda *ops: jnp.linalg.multi_dot(ops), *x,
                    op_name="multi_dot")


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return apply_op(lambda a: jnp.trace(a, offset, axis1, axis2), x,
                    op_name="trace")


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return apply_op(lambda a: jnp.diagonal(a, offset, axis1, axis2), x,
                    op_name="diagonal")


def kron(x, y, name=None):
    return apply_op(jnp.kron, x, y, op_name="kron")


def mv(x, vec, name=None):
    return apply_op(lambda a, v: a @ v, x, vec, op_name="mv")


def corrcoef(x, rowvar=True, name=None):
    return apply_op(lambda a: jnp.corrcoef(a, rowvar=rowvar), x,
                    op_name="corrcoef")


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    fw = fweights._data if isinstance(fweights, Tensor) else fweights
    aw = aweights._data if isinstance(aweights, Tensor) else aweights
    return apply_op(
        lambda a: jnp.cov(a, rowvar=rowvar, ddof=1 if ddof else 0,
                          fweights=fw, aweights=aw), x, op_name="cov")


def householder_product(x, tau, name=None):
    def f(a, t):
        m, n = a.shape[-2], a.shape[-1]
        eye = jnp.eye(m, dtype=a.dtype)
        q = jnp.broadcast_to(eye, a.shape[:-2] + (m, m)).copy() \
            if a.ndim > 2 else eye
        for i in range(n):
            v = jnp.concatenate([
                jnp.zeros(a.shape[:-2] + (i,), a.dtype),
                jnp.ones(a.shape[:-2] + (1,), a.dtype),
                a[..., i + 1:, i]], axis=-1)
            h = (jnp.eye(m, dtype=a.dtype) -
                 t[..., i, None, None] * v[..., :, None] * v[..., None, :])
            q = q @ h
        return q[..., :, :n]
    return apply_op(f, x, tau, op_name="householder_product")
