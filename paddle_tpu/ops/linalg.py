"""Linear-algebra ops. ref: python/paddle/tensor/linalg.py, einsum.py.

matmul is the MXU hot path: inputs stay in their dtype (bf16 preferred) and
XLA chooses fp32 accumulation on TPU by default.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import fusion as _fusion
from ..core.autograd import apply_op
from ..core.tensor import Tensor


def _matmul_impl(a, b, transpose_x=False, transpose_y=False):
    # module-level (stable identity) so the eager dispatch fast path can
    # cache its jitted fwd/vjp pair; the flags ride as static kwargs
    if transpose_x:
        a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
    if transpose_y:
        b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
    return jnp.matmul(a, b)


# contraction/epilogue host (`fusable: epilogue` in ops.yaml): with
# FLAGS_eager_fusion_epilogue on, matmul defers into the fusion DAG so a
# following bias-add/activation chain compiles as an XLA epilogue of the
# dot (one pass) instead of re-reading the product from HBM
_fusion.register_param_impl("matmul", _matmul_impl)


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    return apply_op(_matmul_impl, x, y, op_name="matmul",
                    fuse_attrs=(("transpose_x", bool(transpose_x)),
                                ("transpose_y", bool(transpose_y))),
                    transpose_x=transpose_x, transpose_y=transpose_y)


def mm(input, mat2, name=None):
    return matmul(input, mat2)


def bmm(x, y, name=None):
    return matmul(x, y)


def dot(x, y, name=None):
    return apply_op(lambda a, b: jnp.sum(a * b, axis=-1), x, y, op_name="dot")


def inner(x, y, name=None):
    return apply_op(jnp.inner, x, y, op_name="inner")


def outer(x, y, name=None):
    return apply_op(lambda a, b: jnp.outer(a, b), x, y, op_name="outer")


def cross(x, y, axis=9, name=None):
    def f(a, b):
        ax = axis
        if ax == 9:  # paddle default: first axis with dim 3
            ax = next(i for i, s in enumerate(a.shape) if s == 3)
        return jnp.cross(a, b, axis=ax)
    return apply_op(f, x, y, op_name="cross")


def t(input, name=None):
    return apply_op(lambda a: a.T, input, op_name="t")


def norm(x, p=None, axis=None, keepdim=False, name=None):
    def f(a):
        if p is None or p == "fro":
            if axis is None:
                return jnp.sqrt(jnp.sum(a * a))
            return jnp.linalg.norm(a, ord=None, axis=_ax(axis),
                                   keepdims=keepdim)
        if p == "inf" or p == float("inf"):
            ordv = jnp.inf
        elif p == "-inf" or p == -float("inf"):
            ordv = -jnp.inf
        else:
            ordv = p
        if axis is None:
            return jnp.linalg.norm(a.reshape(-1), ord=ordv)
        return jnp.linalg.norm(a, ord=ordv, axis=_ax(axis), keepdims=keepdim)
    return apply_op(f, x, op_name="norm")


def _ax(axis):
    if isinstance(axis, list):
        return tuple(axis)
    return axis


def dist(x, y, p=2, name=None):
    return apply_op(
        lambda a, b: jnp.linalg.norm((a - b).reshape(-1), ord=p), x, y,
        op_name="dist")


def einsum(equation, *operands):
    return apply_op(lambda *ops: jnp.einsum(equation, *ops), *operands,
                    op_name="einsum")


def transpose(x, perm, name=None):
    return apply_op(lambda a: jnp.transpose(a, perm), x, op_name="transpose")


def cholesky(x, upper=False, name=None):
    def f(a):
        L = jnp.linalg.cholesky(a)
        return jnp.swapaxes(L, -1, -2) if upper else L
    return apply_op(f, x, op_name="cholesky")


def cholesky_solve(x, y, upper=False, name=None):
    def f(b, L):
        return jax.scipy.linalg.cho_solve((L, not upper), b)
    return apply_op(f, x, y, op_name="cholesky_solve")


def inverse(x, name=None):
    return apply_op(jnp.linalg.inv, x, op_name="inverse")


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return apply_op(lambda a: jnp.linalg.pinv(a, rtol=rcond,
                                              hermitian=hermitian), x,
                    op_name="pinv")


def solve(x, y, name=None):
    return apply_op(jnp.linalg.solve, x, y, op_name="solve")


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False,
                     name=None):
    def f(a, b):
        return jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0,
            unit_diagonal=unitriangular)
    return apply_op(f, x, y, op_name="triangular_solve")


def lstsq(x, y, rcond=None, driver=None, name=None):
    # on the tape: jax's SVD-based lstsq is differentiable in the
    # solution/singular values (rank stays int/no-grad)
    return apply_op(
        lambda a, b: tuple(jnp.linalg.lstsq(a, b, rcond=rcond)),
        _tt(x), _tt(y), op_name="lstsq")


def _tt(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


def qr(x, mode="reduced", name=None):
    # through the tape: QR is differentiable (jax ships its VJP) —
    # direct Tensor() construction silently dropped gradients
    return apply_op(lambda a: tuple(jnp.linalg.qr(a, mode=mode)), x,
                    op_name="qr")


def svd(x, full_matrices=False, name=None):
    """Returns (U, S, VH) — VH is the conjugate transpose of V, matching the
    reference contract (ref: python/paddle/tensor/linalg.py svd Returns)."""
    return apply_op(
        lambda a: tuple(jnp.linalg.svd(a, full_matrices=full_matrices)),
        x, op_name="svd")


def eig(x, name=None):
    xd = np.asarray(x._data if isinstance(x, Tensor) else x)
    w, v = np.linalg.eig(xd)
    return Tensor(jnp.asarray(w)), Tensor(jnp.asarray(v))


def eigh(x, UPLO="L", name=None):
    return apply_op(lambda a: tuple(jnp.linalg.eigh(a, UPLO=UPLO)), x,
                    op_name="eigh")


def eigvals(x, name=None):
    xd = np.asarray(x._data if isinstance(x, Tensor) else x)
    return Tensor(jnp.asarray(np.linalg.eigvals(xd)))


def eigvalsh(x, UPLO="L", name=None):
    return apply_op(lambda a: jnp.linalg.eigvalsh(a, UPLO=UPLO), x,
                    op_name="eigvalsh")


def det(x, name=None):
    return apply_op(jnp.linalg.det, x, op_name="det")


def slogdet(x, name=None):
    return apply_op(
        lambda a: jnp.stack(tuple(jnp.linalg.slogdet(a))), x,
        op_name="slogdet")


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return apply_op(
        lambda a: jnp.linalg.matrix_rank(a, rtol=tol).astype(jnp.int64), x,
        op_name="matrix_rank")


def matrix_power(x, n, name=None):
    return apply_op(lambda a: jnp.linalg.matrix_power(a, n), x,
                    op_name="matrix_power")


def multi_dot(x, name=None):
    return apply_op(lambda *ops: jnp.linalg.multi_dot(ops), *x,
                    op_name="multi_dot")


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return apply_op(lambda a: jnp.trace(a, offset, axis1, axis2), x,
                    op_name="trace")


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return apply_op(lambda a: jnp.diagonal(a, offset, axis1, axis2), x,
                    op_name="diagonal")


def kron(x, y, name=None):
    return apply_op(jnp.kron, x, y, op_name="kron")


def mv(x, vec, name=None):
    return apply_op(lambda a, v: a @ v, x, vec, op_name="mv")


def corrcoef(x, rowvar=True, name=None):
    return apply_op(lambda a: jnp.corrcoef(a, rowvar=rowvar), x,
                    op_name="corrcoef")


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    fw = fweights._data if isinstance(fweights, Tensor) else fweights
    aw = aweights._data if isinstance(aweights, Tensor) else aweights
    return apply_op(
        lambda a: jnp.cov(a, rowvar=rowvar, ddof=1 if ddof else 0,
                          fweights=fw, aweights=aw), x, op_name="cov")


def householder_product(x, tau, name=None):
    def f(a, t):
        m, n = a.shape[-2], a.shape[-1]
        eye = jnp.eye(m, dtype=a.dtype)
        q = jnp.broadcast_to(eye, a.shape[:-2] + (m, m)).copy() \
            if a.ndim > 2 else eye
        for i in range(n):
            v = jnp.concatenate([
                jnp.zeros(a.shape[:-2] + (i,), a.dtype),
                jnp.ones(a.shape[:-2] + (1,), a.dtype),
                a[..., i + 1:, i]], axis=-1)
            h = (jnp.eye(m, dtype=a.dtype) -
                 t[..., i, None, None] * v[..., :, None] * v[..., None, :])
            q = q @ h
        return q[..., :, :n]
    return apply_op(f, x, tau, op_name="householder_product")


# --- long-tail linalg surface (ref: python/paddle/linalg.py __all__) ----


def inv(x, name=None):
    """Alias of inverse (ref: linalg.py exposes both)."""
    return inverse(x, name=name)


def cholesky_inverse(x, upper=False, name=None):
    """Inverse of A from its Cholesky factor (ref: tensor/linalg.py
    cholesky_inverse): A^-1 = (LLᵀ)^-1 solved against identity."""
    def f(L):
        eye = jnp.eye(L.shape[-1], dtype=L.dtype)
        return jax.scipy.linalg.cho_solve((L, not upper), eye)
    return apply_op(f, x, op_name="cholesky_inverse")


def vector_norm(x, p=2.0, axis=None, keepdim=False, name=None):
    """ref: tensor/linalg.py vector_norm — p-norm treating the input
    (or the given axes, collapsed together) as a flat vector; multi-axis
    input is FLATTENED, never treated as a matrix norm."""
    axes = (tuple(axis) if isinstance(axis, (list, tuple))
            else None if axis is None else (int(axis),))

    def f(a):
        a32 = a.astype(jnp.float32)
        if axes is None:
            out = jnp.linalg.norm(a32.reshape(-1), ord=p)
            if keepdim:
                out = out.reshape((1,) * a.ndim)
            return out
        ax = tuple(d % a.ndim for d in axes)
        rest = tuple(d for d in range(a.ndim) if d not in ax)
        moved = jnp.transpose(a32, rest + ax)
        flat = moved.reshape(moved.shape[:len(rest)] + (-1,))
        out = jnp.linalg.norm(flat, ord=p, axis=-1)
        if keepdim:
            for d in sorted(ax):
                out = jnp.expand_dims(out, d)
        return out
    return apply_op(f, x, op_name="vector_norm")


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False, name=None):
    """ref: tensor/linalg.py matrix_norm — fro / nuc / ±1 / ±2 / ±inf
    over the two matrix axes."""
    def f(a):
        return jnp.linalg.norm(a.astype(jnp.float32), ord=p, axis=axis,
                               keepdims=keepdim)
    return apply_op(f, x, op_name="matrix_norm")


def cond(x, p=None, name=None):
    """Condition number (ref: tensor/linalg.py cond)."""
    def f(a):
        return jnp.linalg.cond(a.astype(jnp.float32), p=p)
    return apply_op(f, x, op_name="cond")


def matrix_exp(x, name=None):
    """Matrix exponential (ref: tensor/linalg.py matrix_exp)."""
    def f(a):
        if a.ndim > 2:
            flat = a.reshape((-1,) + a.shape[-2:])
            out = jax.vmap(jax.scipy.linalg.expm)(flat)
            return out.reshape(a.shape)
        return jax.scipy.linalg.expm(a)
    return apply_op(f, x, op_name="matrix_exp")


def lu(x, pivot=True, get_infos=False, name=None):
    """LU factorization, compact form (ref: tensor/linalg.py lu):
    returns (LU, pivots[, info]) — LU packs L (unit lower) and U;
    pivots are 1-based row-swap indices like the reference/LAPACK."""
    if not pivot:
        raise NotImplementedError(
            "lu(pivot=False) is unsupported (XLA's LU always pivots)")
    def f(a):
        lu_mat, piv, _ = jax.lax.linalg.lu(a.astype(jnp.float32))
        piv1 = (piv + 1).astype(jnp.int32)
        if get_infos:
            return lu_mat, piv1, jnp.zeros(a.shape[:-2], jnp.int32)
        return lu_mat, piv1

    return apply_op(f, x, op_name="lu")


def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True, name=None):
    """Unpack lu()'s compact result into (P, L, U)
    (ref: tensor/linalg.py lu_unpack)."""
    lu_mat = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    piv = (y._data if isinstance(y, Tensor) else jnp.asarray(y)) - 1
    m, n = lu_mat.shape[-2], lu_mat.shape[-1]
    k = min(m, n)
    L = U = P = None
    if unpack_ludata:
        L = jnp.tril(lu_mat[..., :, :k], -1) + jnp.eye(
            m, k, dtype=lu_mat.dtype)
        U = jnp.triu(lu_mat[..., :k, :])
    if unpack_pivots:
        # pivots are sequential row swaps; replay them on an identity
        perm = jnp.broadcast_to(jnp.arange(m), lu_mat.shape[:-2] + (m,))

        def one(perm_row, piv_row):
            def body(i, p):
                j = piv_row[i]
                pi, pj = p[i], p[j]
                return p.at[i].set(pj).at[j].set(pi)
            return jax.lax.fori_loop(0, piv_row.shape[0], body, perm_row)

        flat_perm = perm.reshape(-1, m)
        flat_piv = piv.reshape(-1, piv.shape[-1])
        out = jax.vmap(one)(flat_perm, flat_piv)
        perm = out.reshape(lu_mat.shape[:-2] + (m,))
        P = jax.nn.one_hot(perm, m, dtype=lu_mat.dtype)
        P = jnp.swapaxes(P, -1, -2)
    return (Tensor(P) if P is not None else None,
            Tensor(L) if L is not None else None,
            Tensor(U) if U is not None else None)


def ormqr(x, tau, y, left=True, transpose=False, name=None):
    """Multiply y by Q (from the householder factorization (x, tau)):
    op(Q) @ y or y @ op(Q) (ref: tensor/linalg.py ormqr). LAPACK's
    ormqr applies the implicit FULL m x m Q, so the k reflectors are
    zero-padded to m before the householder product — XLA has no
    direct ormqr primitive and the explicit product is MXU-friendly."""
    def f(hm, tm, ym):
        m, k = hm.shape[-2], hm.shape[-1]
        if k < m:
            pad_h = [(0, 0)] * (hm.ndim - 1) + [(0, m - k)]
            hm = jnp.pad(hm, pad_h)
            pad_t = [(0, 0)] * (tm.ndim - 1) + [(0, m - k)]
            tm = jnp.pad(tm, pad_t)  # tau=0 => identity reflector
        qm = jax.lax.linalg.householder_product(hm, tm)
        qop = jnp.swapaxes(qm, -1, -2) if transpose else qm
        return jnp.matmul(qop, ym) if left else jnp.matmul(ym, qop)
    return apply_op(f, x, tau, y, op_name="ormqr")


def _lowrank_q(a, q_size, niter, key):
    """Randomized range finder (Halko et al.): Q spans approx the top
    q_size-dim column space of a after ``niter`` power iterations."""
    m, n = a.shape[-2], a.shape[-1]
    omega = jax.random.normal(key, a.shape[:-2] + (n, q_size),
                              dtype=jnp.float32)
    y = a @ omega
    q, _ = jnp.linalg.qr(y)
    for _ in range(niter):
        z = jnp.swapaxes(a, -1, -2) @ q
        z, _ = jnp.linalg.qr(z)
        y = a @ z
        q, _ = jnp.linalg.qr(y)
    return q


def svd_lowrank(x, q=6, niter=2, M=None, name=None):
    """Randomized truncated SVD (ref: tensor/linalg.py svd_lowrank;
    Halko-Martinsson-Tropp). Returns (U, S, V) with V (not Vᵀ),
    matching the reference."""
    from ..core import random as random_mod

    def f(a, key, *rest):
        a = a.astype(jnp.float32)
        if rest:
            a = a - rest[0]
        qmat = _lowrank_q(a, min(q, *a.shape[-2:]), niter, key)
        b = jnp.swapaxes(qmat, -1, -2) @ a
        u_b, s, vh = jnp.linalg.svd(b, full_matrices=False)
        return qmat @ u_b, s, jnp.swapaxes(vh, -1, -2)

    # the projection key rides as an argument (random op contract) and
    # the whole factorization runs on the tape — it is differentiable
    args = [_tt(x), Tensor(random_mod.next_key())]
    if M is not None:
        args.append(_tt(M))
    return apply_op(f, *args, op_name="svd_lowrank")


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """Randomized PCA (ref: tensor/linalg.py pca_lowrank): low-rank SVD
    of the (optionally centered) data."""
    xd = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    m, n = xd.shape[-2], xd.shape[-1]
    if q is None:
        q = min(6, m, n)
    if center:
        centered = apply_op(
            lambda a: a.astype(jnp.float32)
            - jnp.mean(a.astype(jnp.float32), axis=-2, keepdims=True),
            _tt(x), op_name="pca_center")
        return svd_lowrank(centered, q=q, niter=niter)
    return svd_lowrank(_tt(x), q=q, niter=niter)


def fp8_fp8_half_gemm_fused(x, y, transpose_x=False, transpose_y=False,
                            bias=None, scale=1.0, output_dtype="bfloat16",
                            act="identity", name=None):
    """fp8 x fp8 -> half GEMM (ref: linalg.py fp8_fp8_half_gemm_fused,
    a Hopper cutlass kernel). TPU v5e has no fp8 MXU mode, so the
    contract is kept by computing in bf16 with the fp8 inputs upcast —
    numerically a superset of the reference (which quantizes to e4m3).
    Inputs may be float8_e4m3fn/e5m2 or any float dtype."""
    def f(a, b, *maybe_bias):
        a16 = a.astype(jnp.bfloat16)
        b16 = b.astype(jnp.bfloat16)
        if transpose_x:
            a16 = jnp.swapaxes(a16, -1, -2)
        if transpose_y:
            b16 = jnp.swapaxes(b16, -1, -2)
        out = jnp.matmul(a16, b16) * jnp.bfloat16(scale)
        # cutlass epilogue order: act(x @ y * scale + bias)
        if maybe_bias:
            out = out + maybe_bias[0].astype(out.dtype)
        if act == "gelu":
            out = jax.nn.gelu(out)
        elif act == "relu":
            out = jax.nn.relu(out)
        elif act != "identity":
            raise ValueError(f"unknown act {act!r}")
        return out.astype(output_dtype)
    args = (x, y) + ((bias,) if bias is not None else ())
    return apply_op(f, *args, op_name="fp8_gemm")
