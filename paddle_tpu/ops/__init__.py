"""Functional op surface + Tensor method patching.

Mirrors the reference's pattern of patching the Tensor type with the op
surface (ref: python/paddle/base/dygraph/tensor_patch_methods.py).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.autograd import apply_op
from ..core.tensor import Tensor

from .creation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .extra_math import *  # noqa: F401,F403

from . import creation, extra_math, linalg, manipulation, math as math_ops


def cast(x, dtype):
    return x.astype(dtype)


def increment(x, value=1.0, name=None):
    from ..core import tensor as tensor_mod
    if tensor_mod._mutation_hook is not None:
        tensor_mod._mutation_hook(x)
    x._data = x._data + value
    return x


# ---------------------------------------------------------------------------
# Tensor method patching
# ---------------------------------------------------------------------------
_METHOD_SOURCES = [math_ops, manipulation, linalg]

_METHODS = [
    # math
    "abs", "sqrt", "rsqrt", "exp", "log", "log2", "log10", "log1p", "sin",
    "cos", "tan", "tanh", "sigmoid", "floor", "ceil", "round", "trunc",
    "sign", "square", "reciprocal", "erf", "neg",
    "add", "subtract", "multiply", "divide", "mod", "remainder", "pow",
    "maximum", "minimum", "floor_divide", "scale", "clip", "lerp",
    "sum", "mean", "prod", "max", "min", "std", "var", "median",
    "logsumexp", "cumsum", "cumprod", "argmax", "argmin", "argsort", "sort",
    "topk", "kthvalue", "unique", "nonzero", "bincount",
    "equal", "not_equal", "greater_than", "greater_equal", "less_than",
    "less_equal", "logical_and", "logical_or", "logical_xor", "logical_not",
    "bitwise_and", "bitwise_or", "bitwise_xor", "bitwise_not",
    "isnan", "isinf", "isfinite", "allclose", "isclose", "equal_all",
    "all", "any", "nanmean", "nansum", "count_nonzero", "index_sample",
    # manipulation
    "reshape", "reshape_", "transpose", "concat", "split", "chunk", "unbind",
    "squeeze", "unsqueeze", "flatten", "expand", "broadcast_to", "expand_as",
    "tile", "repeat_interleave", "flip", "roll", "gather", "gather_nd",
    "take_along_axis", "put_along_axis", "scatter", "scatter_nd_add",
    "index_select", "index_add", "index_put", "masked_select", "masked_fill",
    "where", "pad", "numel", "moveaxis", "diff", "tensordot", "unfold",
    "strided_slice", "swapaxes",
    # linalg
    "matmul", "mm", "bmm", "dot", "inner", "outer", "cross", "t", "norm",
    "dist", "cholesky", "inverse", "solve", "qr", "svd", "eigh", "det",
    "matrix_power", "trace", "diagonal", "kron", "mv",
]


def _patch_methods():
    for name in _METHODS:
        fn = None
        for src in _METHOD_SOURCES:
            if hasattr(src, name):
                fn = getattr(src, name)
                break
        if fn is None:
            continue
        if not hasattr(Tensor, name):
            setattr(Tensor, name, fn)


def _binary_op(fn, reverse=False):
    def method(self, other):
        if reverse:
            return fn(other, self)
        return fn(self, other)
    return method


def _patch_operators():
    m = math_ops
    Tensor.__add__ = _binary_op(m.add)
    Tensor.__radd__ = _binary_op(m.add, reverse=True)
    Tensor.__sub__ = _binary_op(m.subtract)
    Tensor.__rsub__ = _binary_op(m.subtract, reverse=True)
    Tensor.__mul__ = _binary_op(m.multiply)
    Tensor.__rmul__ = _binary_op(m.multiply, reverse=True)
    Tensor.__truediv__ = _binary_op(m.divide)
    Tensor.__rtruediv__ = _binary_op(m.divide, reverse=True)
    Tensor.__floordiv__ = _binary_op(m.floor_divide)
    Tensor.__rfloordiv__ = _binary_op(m.floor_divide, reverse=True)
    Tensor.__mod__ = _binary_op(m.mod)
    Tensor.__rmod__ = _binary_op(m.mod, reverse=True)
    Tensor.__pow__ = _binary_op(m.pow)
    Tensor.__rpow__ = _binary_op(m.pow, reverse=True)
    Tensor.__matmul__ = _binary_op(linalg.matmul)
    Tensor.__rmatmul__ = _binary_op(linalg.matmul, reverse=True)
    Tensor.__neg__ = lambda self: m.neg(self)
    Tensor.__abs__ = lambda self: m.abs(self)
    Tensor.__invert__ = lambda self: m.logical_not(self)
    Tensor.__eq__ = _binary_op(m.equal)
    Tensor.__ne__ = _binary_op(m.not_equal)
    Tensor.__lt__ = _binary_op(m.less_than)
    Tensor.__le__ = _binary_op(m.less_equal)
    Tensor.__gt__ = _binary_op(m.greater_than)
    Tensor.__ge__ = _binary_op(m.greater_equal)
    Tensor.__and__ = _binary_op(m.logical_and)
    Tensor.__or__ = _binary_op(m.logical_or)
    Tensor.__xor__ = _binary_op(m.logical_xor)

    # in-place arithmetic used by optimizers / user code on leaves; the
    # mutation hook keeps the SOT tracer honest about buffer rebinds
    def _notify(self):
        from ..core import tensor as tensor_mod
        if tensor_mod._mutation_hook is not None:
            tensor_mod._mutation_hook(self)

    def _iadd(self, other):
        _notify(self)
        self._data = self._data + (other._data if isinstance(other, Tensor)
                                   else other)
        return self

    def _isub(self, other):
        _notify(self)
        self._data = self._data - (other._data if isinstance(other, Tensor)
                                   else other)
        return self

    def _imul(self, other):
        _notify(self)
        self._data = self._data * (other._data if isinstance(other, Tensor)
                                   else other)
        return self

    def _idiv(self, other):
        _notify(self)
        self._data = self._data / (other._data if isinstance(other, Tensor)
                                   else other)
        return self

    Tensor.add_ = _iadd
    Tensor.subtract_ = _isub
    Tensor.multiply_ = _imul
    Tensor.divide_ = _idiv

    def _iscale(self, scale=1.0, bias=0.0, bias_after_scale=True):
        _notify(self)
        if bias_after_scale:
            self._data = self._data * scale + bias
        else:
            self._data = (self._data + bias) * scale
        return self

    Tensor.scale_ = _iscale


_patch_methods()
_patch_operators()

# populate the native OpRegistry from the declarative op table
from . import op_registry  # noqa: F401,E402
from .op_registry import get_op_info, list_ops, num_ops  # noqa: F401,E402
