"""Tensor creation ops. ref: python/paddle/tensor/creation.py"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import random as random_mod
from ..core.dtype import convert_dtype, get_default_dtype
from ..core.tensor import Tensor, to_tensor  # noqa: F401


def _dt(dtype, default=None):
    d = convert_dtype(dtype)
    if d is None:
        d = default or get_default_dtype()
    return d


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in np.asarray(shape._data))
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s._data) if isinstance(s, Tensor) else int(s)
                 for s in shape)


def zeros(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(_shape(shape), _dt(dtype)))


def ones(shape, dtype=None, name=None):
    return Tensor(jnp.ones(_shape(shape), _dt(dtype)))


def full(shape, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    return Tensor(jnp.full(_shape(shape), fill_value, _dt(dtype)))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def zeros_like(x, dtype=None, name=None):
    d = convert_dtype(dtype)
    return Tensor(jnp.zeros_like(x._data if isinstance(x, Tensor) else x,
                                 dtype=d))


def ones_like(x, dtype=None, name=None):
    d = convert_dtype(dtype)
    return Tensor(jnp.ones_like(x._data if isinstance(x, Tensor) else x,
                                dtype=d))


def full_like(x, fill_value, dtype=None, name=None):
    d = convert_dtype(dtype)
    return Tensor(jnp.full_like(x._data if isinstance(x, Tensor) else x,
                                fill_value, dtype=d))


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    def _v(a):
        return a.item() if isinstance(a, Tensor) else a
    start, end, step = _v(start), _v(end), _v(step)
    if end is None:
        start, end = 0, start
    d = convert_dtype(dtype)
    if d is None:
        d = (np.dtype("int64") if all(
            isinstance(v, (int, np.integer)) for v in (start, end, step))
            else get_default_dtype())
    return Tensor(jnp.arange(start, end, step, dtype=d))


def linspace(start, stop, num, dtype=None, name=None):
    def _v(a):
        return a.item() if isinstance(a, Tensor) else a
    return Tensor(jnp.linspace(_v(start), _v(stop), int(_v(num)),
                               dtype=_dt(dtype)))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor(jnp.eye(num_rows, num_columns, dtype=_dt(dtype)))


def diag(x, offset=0, padding_value=0, name=None):
    # through apply_op: diag is differentiable (vector<->matrix diagonal
    # exchange) — a direct Tensor() construction would silently drop
    # gradients off the tape
    from ..core.autograd import apply_op

    def f(a):
        if a.ndim == 1 and padding_value != 0:
            n = a.shape[0] + abs(offset)
            base = jnp.full((n, n), padding_value, a.dtype)
            idx = jnp.arange(a.shape[0])
            if offset >= 0:
                return base.at[idx, idx + offset].set(a)
            return base.at[idx - offset, idx].set(a)
        return jnp.diag(a, k=offset)

    return apply_op(f, x if isinstance(x, Tensor)
                    else Tensor(jnp.asarray(x)), op_name="diag")


def tril(x, diagonal=0, name=None):
    from ..core.autograd import apply_op
    return apply_op(lambda a: jnp.tril(a, diagonal), x, op_name="tril")


def triu(x, diagonal=0, name=None):
    from ..core.autograd import apply_op
    return apply_op(lambda a: jnp.triu(a, diagonal), x, op_name="triu")


def meshgrid(*args, **kwargs):
    # differentiable in the reference (broadcast-expand per input);
    # dispatch each output through the tape
    from ..core.autograd import apply_op
    tens = [a if isinstance(a, Tensor) else Tensor(jnp.asarray(a))
            for a in args]
    outs = apply_op(
        lambda *xs: tuple(jnp.meshgrid(*xs, indexing="ij")), *tens,
        op_name="meshgrid")
    return list(outs) if isinstance(outs, tuple) else [outs]


def assign(x, output=None):
    if output is not None:
        data = x._data if isinstance(x, Tensor) \
            else jnp.asarray(np.asarray(x))
        output.set_value(data)
        return output
    if isinstance(x, Tensor):
        # identity with gradient flow (ref: assign backward = identity)
        from ..core.autograd import apply_op
        return apply_op(lambda a: a, x, op_name="assign")
    return Tensor(jnp.asarray(np.asarray(x)))


def clone(x, name=None):
    return x.clone()


# -- random creation ---------------------------------------------------------

def rand(shape, dtype=None, name=None):
    return uniform(shape, dtype, min=0.0, max=1.0)


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    key = (jax.random.key(seed) if seed else random_mod.next_key())
    return Tensor(jax.random.uniform(key, _shape(shape), _dt(dtype),
                                     minval=min, maxval=max))


def randn(shape, dtype=None, name=None):
    return Tensor(jax.random.normal(random_mod.next_key(), _shape(shape),
                                    _dt(dtype)))


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = mean._data if isinstance(mean, Tensor) else mean
        s = std._data if isinstance(std, Tensor) else std
        shp = jnp.broadcast_shapes(jnp.shape(m), jnp.shape(s))
        return Tensor(jax.random.normal(random_mod.next_key(), shp) * s + m)
    return Tensor(jax.random.normal(random_mod.next_key(), _shape(shape),
                                    get_default_dtype()) * std + mean)


def randint(low=0, high=None, shape=(1,), dtype=None, name=None):
    if high is None:
        low, high = 0, low
    d = convert_dtype(dtype) or np.dtype("int64")
    return Tensor(jax.random.randint(random_mod.next_key(), _shape(shape),
                                     low, high, dtype=d))


def randperm(n, dtype=None, name=None):
    d = convert_dtype(dtype) or np.dtype("int64")
    return Tensor(jax.random.permutation(random_mod.next_key(),
                                         jnp.arange(n, dtype=d)))


def multinomial(x, num_samples=1, replacement=False, name=None):
    xd = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    logits = jnp.log(jnp.maximum(xd, 1e-30))
    if replacement:
        out = jax.random.categorical(
            random_mod.next_key(), logits, axis=-1,
            shape=(num_samples,) + xd.shape[:-1]).T \
            if xd.ndim > 1 else jax.random.categorical(
                random_mod.next_key(), logits, shape=(num_samples,))
        return Tensor(out.astype(jnp.int64))
    # without replacement: Gumbel top-k trick
    g = jax.random.gumbel(random_mod.next_key(), xd.shape)
    _, idx = jax.lax.top_k(logits + g, num_samples)
    return Tensor(idx.astype(jnp.int64))


def bernoulli(x, name=None):
    xd = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    u = jax.random.uniform(random_mod.next_key(), xd.shape)
    return Tensor((u < xd).astype(xd.dtype))
