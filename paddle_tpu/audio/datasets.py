"""paddle.audio.datasets (ref: python/paddle/audio/datasets/): ESC50
and TESS. Served synthetically offline like the vision/text zoos —
deterministic waveforms with the datasets' real label spaces, loud
docstrings, identical (waveform, label) contract."""
from __future__ import annotations

import numpy as np

from ..io.dataset import Dataset

__all__ = ["ESC50", "TESS"]


class _SyntheticAudioDataset(Dataset):
    SR = 16000
    SECONDS = 1
    N = 64
    N_CLASSES = 2

    def __init__(self, mode: str = "train", split=1, feat_type="raw",
                 archive=None, **kwargs):
        self.mode = mode
        self.feat_type = feat_type
        seed = 0 if mode == "train" else 1
        rng = np.random.default_rng(seed)
        t = np.arange(self.SR * self.SECONDS) / self.SR
        self._labels = rng.integers(0, self.N_CLASSES, self.N)
        # per-sample tone at a label-dependent frequency + noise: real
        # waveform shapes, deterministic, classifiable
        freqs = 200.0 + 120.0 * self._labels
        phase = rng.random(self.N)[:, None]
        self._waves = (
            0.5 * np.sin(2 * np.pi * (freqs[:, None] * t[None] + phase))
            + 0.05 * rng.standard_normal((self.N, t.size))
        ).astype(np.float32)

    def __getitem__(self, idx):
        return self._waves[idx], int(self._labels[idx])

    def __len__(self):
        return self.N


class ESC50(_SyntheticAudioDataset):
    """ESC-50 environmental sounds (ref: audio/datasets/esc50.py; 50
    classes, 5-fold). Offline build: synthetic waveforms over the real
    label space."""
    N_CLASSES = 50
    N = 100


class TESS(_SyntheticAudioDataset):
    """TESS emotional speech (ref: audio/datasets/tess.py; 7 emotion
    classes). Offline build: synthetic waveforms over the real label
    space."""
    N_CLASSES = 7
    N = 70

    def __init__(self, mode: str = "train", n_folds=5, split=1,
                 feat_type="raw", archive=None, **kwargs):
        super().__init__(mode=mode, split=split, feat_type=feat_type)
