"""paddle.audio equivalent (ref: python/paddle/audio/__init__.py):
functional / features / datasets / backends submodules plus the
module-level load / info / save IO entry points."""
from . import backends  # noqa: F401
from . import datasets  # noqa: F401
from . import features  # noqa: F401
from . import functional  # noqa: F401
from ._impl import (  # noqa: F401
    LogMelSpectrogram, MelSpectrogram, MFCC, Spectrogram,
    compute_fbank_matrix, create_dct, fft_frequencies, get_window,
    hz_to_mel, mel_frequencies, mel_to_hz, power_to_db)
from .backends import info, load, save  # noqa: F401

__all__ = ["functional", "features", "datasets", "backends", "load",
           "info", "save"]
