"""Audio IO backends (ref: python/paddle/audio/backends/): the wave
backend reads/writes 16-bit PCM WAV via the stdlib — the role the
reference's 'wave_backend' plays without soundfile installed."""
from __future__ import annotations

import wave as _wave
from dataclasses import dataclass

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor

__all__ = ["get_current_backend", "list_available_backends",
           "set_backend"]

_current = "wave_backend"


def list_available_backends():
    return ["wave_backend"]


def get_current_backend() -> str:
    return _current


def set_backend(backend_name: str) -> None:
    if backend_name not in list_available_backends():
        raise NotImplementedError(
            f"backend {backend_name!r} unavailable (have "
            f"{list_available_backends()})")
    global _current
    _current = backend_name


@dataclass
class AudioInfo:
    """ref: backends metadata object (sample_rate, num_samples,
    num_channels, bits_per_sample, encoding)."""
    sample_rate: int
    num_samples: int
    num_channels: int
    bits_per_sample: int
    encoding: str = "PCM_S"


def info(filepath: str) -> AudioInfo:
    with _wave.open(filepath, "rb") as f:
        return AudioInfo(sample_rate=f.getframerate(),
                         num_samples=f.getnframes(),
                         num_channels=f.getnchannels(),
                         bits_per_sample=f.getsampwidth() * 8)


def load(filepath: str, frame_offset: int = 0, num_frames: int = -1,
         normalize: bool = True, channels_first: bool = True):
    """WAV -> (waveform Tensor, sample_rate); float32 in [-1, 1] when
    normalize=True (ref: backends load contract)."""
    with _wave.open(filepath, "rb") as f:
        sr = f.getframerate()
        nch = f.getnchannels()
        width = f.getsampwidth()
        f.setpos(frame_offset)
        n = f.getnframes() - frame_offset if num_frames < 0 else \
            num_frames
        raw = f.readframes(n)
    if width == 2:
        data = np.frombuffer(raw, np.int16).astype(np.float32)
        scale = 32768.0
    elif width == 4:
        data = np.frombuffer(raw, np.int32).astype(np.float32)
        scale = 2147483648.0
    elif width == 1:
        data = np.frombuffer(raw, np.uint8).astype(np.float32) - 128.0
        scale = 128.0
    else:
        raise ValueError(f"unsupported sample width {width}")
    data = data.reshape(-1, nch)
    if normalize:
        data = data / scale
    wavef = data.T if channels_first else data
    return Tensor(jnp.asarray(wavef)), sr


def save(filepath: str, src, sample_rate: int,
         channels_first: bool = True, encoding: str = "PCM_16",
         bits_per_sample: int = 16) -> None:
    """float waveform -> 16-bit PCM WAV (ref: backends save)."""
    arr = np.asarray(src.numpy() if isinstance(src, Tensor) else src)
    if arr.ndim == 1:
        arr = arr[None] if channels_first else arr[:, None]
    if channels_first:
        arr = arr.T                       # -> [frames, channels]
    pcm = np.clip(arr, -1.0, 1.0)
    pcm = (pcm * 32767.0).astype(np.int16)
    with _wave.open(filepath, "wb") as f:
        f.setnchannels(pcm.shape[1])
        f.setsampwidth(2)
        f.setframerate(int(sample_rate))
        f.writeframes(pcm.tobytes())
