"""paddle.audio.features (ref: python/paddle/audio/features/layers.py)."""
from ._impl import (  # noqa: F401
    LogMelSpectrogram, MelSpectrogram, MFCC, Spectrogram)

__all__ = ["LogMelSpectrogram", "MelSpectrogram", "MFCC", "Spectrogram"]
