"""paddle.audio equivalent: spectrogram/mel/MFCC features.

ref: python/paddle/audio/ — functional (hz_to_mel/mel_to_hz/
compute_fbank_matrix/create_dct, functional/functional.py) and features
(Spectrogram/MelSpectrogram/LogMelSpectrogram/MFCC, features/layers.py).
Built on paddle_tpu.signal.stft so features compile into the same XLA
program as the model consuming them.
"""
from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from ..core.autograd import apply_op
from ..core.tensor import Tensor
from ..nn.layer import Layer
from .. import signal as _signal

__all__ = [
    "hz_to_mel", "mel_to_hz", "compute_fbank_matrix", "create_dct",
    "Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC",
]


def hz_to_mel(freq, htk: bool = False):
    """ref: audio/functional/functional.py hz_to_mel (slaney default)."""
    f = np.asarray(freq, np.float64)
    if htk:
        out = 2595.0 * np.log10(1.0 + f / 700.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        out = (f - f_min) / f_sp
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        out = np.where(f >= min_log_hz,
                       min_log_mel + np.log(np.maximum(f, 1e-10)
                                            / min_log_hz) / logstep, out)
    return out if out.shape else float(out)


def mel_to_hz(mel, htk: bool = False):
    m = np.asarray(mel, np.float64)
    if htk:
        out = 700.0 * (10.0 ** (m / 2595.0) - 1.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        out = f_min + f_sp * m
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        out = np.where(m >= min_log_mel,
                       min_log_hz * np.exp(logstep * (m - min_log_mel)),
                       out)
    return out if out.shape else float(out)


def compute_fbank_matrix(sr: int, n_fft: int, n_mels: int = 64,
                         f_min: float = 0.0, f_max=None, htk: bool = False,
                         norm: str = "slaney"):
    """[n_mels, n_fft//2+1] triangular mel filter bank (ref: functional.py
    compute_fbank_matrix)."""
    f_max = f_max or sr / 2.0
    fft_freqs = np.linspace(0, sr / 2.0, n_fft // 2 + 1)
    mel_pts = np.linspace(hz_to_mel(f_min, htk), hz_to_mel(f_max, htk),
                          n_mels + 2)
    hz_pts = np.asarray([mel_to_hz(m, htk) for m in mel_pts])
    fb = np.zeros((n_mels, n_fft // 2 + 1), np.float32)
    for i in range(n_mels):
        lo, ctr, hi = hz_pts[i], hz_pts[i + 1], hz_pts[i + 2]
        up = (fft_freqs - lo) / max(ctr - lo, 1e-10)
        down = (hi - fft_freqs) / max(hi - ctr, 1e-10)
        fb[i] = np.maximum(0.0, np.minimum(up, down))
    if norm == "slaney":
        enorm = 2.0 / (hz_pts[2:] - hz_pts[:-2])
        fb *= enorm[:, None]
    return Tensor(jnp.asarray(fb))


def create_dct(n_mfcc: int, n_mels: int, norm: str = "ortho"):
    """[n_mels, n_mfcc] DCT-II matrix (ref: functional.py create_dct)."""
    n = np.arange(n_mels)
    k = np.arange(n_mfcc)[:, None]
    dct = np.cos(math.pi / n_mels * (n + 0.5) * k).astype(np.float32)
    if norm == "ortho":
        dct[0] *= 1.0 / math.sqrt(2.0)
        dct *= math.sqrt(2.0 / n_mels)
    else:
        dct *= 2.0
    return Tensor(jnp.asarray(dct.T))


class Spectrogram(Layer):
    """ref: audio/features/layers.py Spectrogram — |STFT|^power."""

    def __init__(self, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True,
                 pad_mode="reflect"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        if window == "hann":
            w = jnp.asarray(np.hanning(self.win_length).astype(np.float32))
        elif window == "hamming":
            w = jnp.asarray(np.hamming(self.win_length).astype(np.float32))
        else:
            w = jnp.ones((self.win_length,), jnp.float32)
        self.window = Tensor(w)

    def forward(self, x):
        spec = _signal.stft(x, self.n_fft, self.hop_length,
                            self.win_length, self.window,
                            center=self.center, pad_mode=self.pad_mode)
        return apply_op(
            lambda s: jnp.abs(s) ** self.power, spec, op_name="spec_power")


class MelSpectrogram(Layer):
    """ref: features/layers.py MelSpectrogram."""

    def __init__(self, sr=16000, n_fft=512, hop_length=None,
                 win_length=None, window="hann", power=2.0, n_mels=64,
                 f_min=0.0, f_max=None):
        super().__init__()
        self.spectrogram = Spectrogram(n_fft, hop_length, win_length,
                                       window, power)
        self.fbank = compute_fbank_matrix(sr, n_fft, n_mels, f_min, f_max)

    def forward(self, x):
        spec = self.spectrogram(x)   # [..., freq, time]
        return apply_op(lambda s, fb: jnp.einsum("...ft,mf->...mt", s, fb),
                        spec, self.fbank, op_name="mel_fbank")


class LogMelSpectrogram(Layer):
    def __init__(self, sr=16000, n_fft=512, hop_length=None,
                 win_length=None, window="hann", power=2.0, n_mels=64,
                 f_min=0.0, f_max=None, ref_value=1.0, amin=1e-10,
                 top_db=None):
        super().__init__()
        self.mel = MelSpectrogram(sr, n_fft, hop_length, win_length,
                                  window, power, n_mels, f_min, f_max)
        self.amin = amin
        self.ref_value = ref_value
        self.top_db = top_db

    def forward(self, x):
        m = self.mel(x)

        def f(s):
            log_spec = 10.0 * jnp.log10(jnp.maximum(s, self.amin)
                                        / self.ref_value)
            if self.top_db is not None:
                log_spec = jnp.maximum(log_spec,
                                       log_spec.max() - self.top_db)
            return log_spec

        return apply_op(f, m, op_name="log_mel")


class MFCC(Layer):
    """ref: features/layers.py MFCC = DCT(log-mel)."""

    def __init__(self, sr=16000, n_mfcc=40, n_fft=512, n_mels=64, **kw):
        super().__init__()
        self.log_mel = LogMelSpectrogram(sr=sr, n_fft=n_fft, n_mels=n_mels,
                                         **kw)
        self.dct = create_dct(n_mfcc, n_mels)

    def forward(self, x):
        lm = self.log_mel(x)         # [..., n_mels, time]
        return apply_op(lambda s, d: jnp.einsum("...mt,mk->...kt", s, d),
                        lm, self.dct, op_name="mfcc_dct")


# --- functional long tail (ref: audio/functional/functional.py) --------


def fft_frequencies(sr: int, n_fft: int, dtype="float32"):
    """Center frequencies of rfft bins (ref: functional.py
    fft_frequencies)."""
    return Tensor(jnp.linspace(0, sr / 2, 1 + n_fft // 2).astype(dtype))


def mel_frequencies(n_mels: int = 64, f_min: float = 0.0,
                    f_max: float = 11025.0, htk: bool = False,
                    dtype="float32"):
    """n_mels frequencies evenly spaced on the mel scale (ref:
    functional.py mel_frequencies)."""
    lo = hz_to_mel(f_min, htk)
    hi = hz_to_mel(f_max, htk)
    mels = np.linspace(lo, hi, n_mels)
    return Tensor(jnp.asarray(
        np.asarray([mel_to_hz(m, htk) for m in mels]).astype(dtype)))


def power_to_db(spect, ref_value: float = 1.0, amin: float = 1e-10,
                top_db=80.0):
    """Power spectrogram -> dB with optional dynamic-range clamp (ref:
    functional.py power_to_db)."""
    if amin <= 0:
        raise ValueError("amin must be strictly positive")
    if ref_value <= 0:
        raise ValueError("ref_value must be strictly positive")

    def f(x):
        log_spec = 10.0 * jnp.log10(jnp.maximum(amin, x))
        log_spec = log_spec - 10.0 * math.log10(max(amin, ref_value))
        if top_db is not None:
            if top_db < 0:
                raise ValueError("top_db must be non-negative")
            log_spec = jnp.maximum(log_spec, log_spec.max() - top_db)
        return log_spec
    return apply_op(f, spect, op_name="power_to_db")


def get_window(window, win_length: int, fftbins: bool = True,
               dtype="float32"):
    """Window function by name (ref: functional/window.py get_window):
    hamming/hann/blackman/bartlett/... periodic when fftbins=True."""
    if isinstance(window, tuple):
        name, args = window[0], window[1:]
    else:
        name, args = window, ()
    n = win_length + (0 if fftbins else -1)
    k = np.arange(win_length, dtype=np.float64)
    if name in ("hann", "hanning"):
        w = 0.5 - 0.5 * np.cos(2 * np.pi * k / max(n, 1))
    elif name == "hamming":
        w = 0.54 - 0.46 * np.cos(2 * np.pi * k / max(n, 1))
    elif name == "blackman":
        w = (0.42 - 0.5 * np.cos(2 * np.pi * k / max(n, 1))
             + 0.08 * np.cos(4 * np.pi * k / max(n, 1)))
    elif name == "bartlett":
        w = 1.0 - np.abs(2 * k / max(n, 1) - 1.0)
    elif name in ("rect", "rectangular", "boxcar", "ones"):
        w = np.ones(win_length)
    elif name == "gaussian":
        std = args[0] if args else 7.0
        w = np.exp(-0.5 * ((k - (win_length - 1) / 2) / std) ** 2)
    elif name == "triang":
        m = (win_length + 1) // 2
        up = (np.arange(1, m + 1) - 0.5 if win_length % 2 == 0
              else np.arange(1, m + 1))
        denom = (win_length if win_length % 2 == 0
                 else (win_length + 1) / 2)
        half = up / denom if win_length % 2 == 0 else up / denom
        w = np.concatenate([half, half[::-1][win_length % 2:]])
        w = w[:win_length]
    else:
        raise ValueError(f"unknown window {name!r}")
    return Tensor(jnp.asarray(w.astype(dtype)))
