"""ASGD / NAdam / RAdam / Rprop / LBFGS.

ref: python/paddle/optimizer/{asgd,nadam,radam,rprop,lbfgs}.py — semantics
re-derived from the documented update equations; implementations are pure
jnp per-parameter updates on the shared Optimizer base (optimizer.py), so
they run eagerly and inside compiled train steps alike.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.autograd import no_grad
from ..core.tensor import Tensor
from .optimizer import Optimizer


class ASGD(Optimizer):
    """Stochastic Average Gradient (ref asgd.py docstring equations):

        i = m % n;  d = d - y_i + g;  y_i = g
        x = x - lr * (d / min(m+1, n) + lambda * x)

    State per param: running sum ``d`` and an ``[n, *shape]`` gradient
    history ``ys`` (n = batch_num).
    """

    def __init__(self, learning_rate=0.001, batch_num=1, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        if batch_num < 1:
            raise ValueError(f"batch_num must be >= 1, got {batch_num}")
        self._batch_num = int(batch_num)
        self._multi_precision = multi_precision

    def _init_state(self, p):
        n = self._batch_num
        s = {"d": jnp.zeros_like(p._data, jnp.float32),
             "ys": jnp.zeros((n,) + tuple(p._data.shape), jnp.float32),
             "m": jnp.zeros((), jnp.int32)}
        if self._multi_precision and p._data.dtype != jnp.float32:
            s["master"] = p._data.astype(jnp.float32)
        return s

    def _update(self, p, g, state, lr):
        n = self._batch_num
        g = g.astype(jnp.float32)
        m = state["m"]
        i = m % n
        y_i = state["ys"][i]
        d = state["d"] - y_i + g
        ys = state["ys"].at[i].set(g)
        p32 = state.get("master", p.astype(jnp.float32))
        denom = jnp.minimum(m + 1, n).astype(jnp.float32)
        upd = d / denom + self._weight_decay * p32
        new_p32 = p32 - lr * upd
        out = {"d": d, "ys": ys, "m": m + 1}
        if "master" in state:
            out["master"] = new_p32
        return new_p32.astype(p.dtype), out


class NAdam(Optimizer):
    """Nesterov Adam (ref nadam.py docstring equations), psi = 0.004:

        mu_t     = beta1 * (1 - 0.5 * 0.96^(t * psi))
        mu_{t+1} = beta1 * (1 - 0.5 * 0.96^((t+1) * psi))
        m_t = beta1 m + (1-beta1) g ; v_t = beta2 v + (1-beta2) g^2
        m_hat = mu_{t+1} m_t / (1 - mu_prod_{t+1}) + (1-mu_t) g / (1 - mu_prod_t)
        v_hat = v_t / (1 - beta2^t)
        p = p - lr * m_hat / (sqrt(v_hat) + eps)
    """
    _psi = 0.004

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, momentum_decay=0.004, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._psi = momentum_decay

    def _init_state(self, p):
        return {"moment1": jnp.zeros_like(p._data, jnp.float32),
                "moment2": jnp.zeros_like(p._data, jnp.float32),
                "beta2_pow": jnp.ones((), jnp.float32),
                "mu_product": jnp.ones((), jnp.float32),
                "t": jnp.zeros((), jnp.float32)}

    def _update(self, p, g, state, lr):
        b1, b2, eps, psi = self._beta1, self._beta2, self._epsilon, self._psi
        g = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        if self._weight_decay:
            g = g + self._weight_decay * p32
        t = state["t"] + 1
        mu_t = b1 * (1 - 0.5 * jnp.power(0.96, t * psi))
        mu_t1 = b1 * (1 - 0.5 * jnp.power(0.96, (t + 1) * psi))
        m = b1 * state["moment1"] + (1 - b1) * g
        v = b2 * state["moment2"] + (1 - b2) * g * g
        mu_prod = state["mu_product"] * mu_t
        mu_prod1 = mu_prod * mu_t1
        b2p = state["beta2_pow"] * b2
        m_hat = mu_t1 * m / (1 - mu_prod1) + (1 - mu_t) * g / (1 - mu_prod)
        v_hat = v / (1 - b2p)
        new_p = (p32 - lr * m_hat / (jnp.sqrt(v_hat) + eps)).astype(p.dtype)
        return new_p, {"moment1": m, "moment2": v, "beta2_pow": b2p,
                       "mu_product": mu_prod, "t": t}


class RAdam(Optimizer):
    """Rectified Adam (ref radam.py docstring equations):

        rho_inf = 2/(1-beta2) - 1
        rho_t   = rho_inf - 2 t beta2^t / (1 - beta2^t)
        m_hat   = m_t / (1 - beta1^t)
        if rho_t > 5:  r_t = sqrt(((rho_t-4)(rho_t-2) rho_inf) /
                                  ((rho_inf-4)(rho_inf-2) rho_t))
                       p -= lr * m_hat * r_t / (sqrt(v_hat) + eps)
        else:          p -= lr * m_hat
    """

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _init_state(self, p):
        return {"moment1": jnp.zeros_like(p._data, jnp.float32),
                "moment2": jnp.zeros_like(p._data, jnp.float32),
                "beta1_pow": jnp.ones((), jnp.float32),
                "beta2_pow": jnp.ones((), jnp.float32),
                "t": jnp.zeros((), jnp.float32)}

    def _update(self, p, g, state, lr):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        g = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        if self._weight_decay:
            g = g + self._weight_decay * p32
        t = state["t"] + 1
        m = b1 * state["moment1"] + (1 - b1) * g
        v = b2 * state["moment2"] + (1 - b2) * g * g
        b1p = state["beta1_pow"] * b1
        b2p = state["beta2_pow"] * b2
        rho_inf = 2.0 / (1 - b2) - 1
        rho_t = rho_inf - 2.0 * t * b2p / (1 - b2p)
        m_hat = m / (1 - b1p)
        v_hat = jnp.sqrt(v / (1 - b2p))
        r_t = jnp.sqrt(((rho_t - 4) * (rho_t - 2) * rho_inf) /
                       jnp.maximum((rho_inf - 4) * (rho_inf - 2) * rho_t,
                                   eps))
        rectified = p32 - lr * m_hat * r_t / (v_hat + eps)
        plain = p32 - lr * m_hat
        new_p = jnp.where(rho_t > 5.0, rectified, plain).astype(p.dtype)
        return new_p, {"moment1": m, "moment2": v, "beta1_pow": b1p,
                       "beta2_pow": b2p, "t": t}


class Rprop(Optimizer):
    """Resilient backprop (ref rprop.py): per-weight step sizes adapted by
    gradient sign agreement; sign-flip steps shrink by eta_minus and the
    gradient is zeroed for that step (so the next sign product is 0).
    """

    def __init__(self, learning_rate=0.001,
                 learning_rate_range=(1e-5, 50.0), parameters=None,
                 etas=(0.5, 1.2), grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, None, grad_clip)
        lo, hi = learning_rate_range
        if not (0.0 < lo <= learning_rate <= hi):
            raise ValueError(
                f"need 0 < {lo} <= learning_rate={learning_rate} <= {hi}")
        self._lr_range = (float(lo), float(hi))
        if not (0.0 < etas[0] < 1.0 < etas[1]):
            raise ValueError(f"need 0 < eta_minus < 1 < eta_plus, got {etas}")
        self._etas = (float(etas[0]), float(etas[1]))
        self._multi_precision = multi_precision

    def _init_state(self, p):
        s = {"prev_grad": jnp.zeros_like(p._data, jnp.float32),
             "step_size": jnp.full_like(
                 p._data, float(self.get_lr()), jnp.float32)}
        if self._multi_precision and p._data.dtype != jnp.float32:
            s["master"] = p._data.astype(jnp.float32)
        return s

    def _update(self, p, g, state, lr):
        lo, hi = self._lr_range
        eta_m, eta_p = self._etas
        g = g.astype(jnp.float32)
        sign = g * state["prev_grad"]
        factor = jnp.where(sign > 0, eta_p, jnp.where(sign < 0, eta_m, 1.0))
        step = jnp.clip(state["step_size"] * factor, lo, hi)
        g_eff = jnp.where(sign < 0, 0.0, g)
        p32 = state.get("master", p.astype(jnp.float32))
        new_p32 = p32 - jnp.sign(g_eff) * step
        out = {"prev_grad": g_eff, "step_size": step}
        if "master" in state:
            out["master"] = new_p32
        return new_p32.astype(p.dtype), out


class LBFGS(Optimizer):
    """Limited-memory BFGS with closure-based step and optional strong-Wolfe
    line search (ref lbfgs.py API: step(closure)). Operates on the flattened
    parameter vector; history (s, y, rho) kept host-side.
    """

    # closure-driven multi-evaluation step with host-side convergence
    # tests — not expressible as one pure whole-step program
    _fusable_step = False

    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None,
                 tolerance_grad=1e-7, tolerance_change=1e-9, history_size=100,
                 line_search_fn=None, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._max_iter = max_iter
        self._max_eval = max_eval if max_eval is not None else max_iter * 5 // 4
        self._tol_grad = tolerance_grad
        self._tol_change = tolerance_change
        self._history_size = history_size
        if line_search_fn not in (None, "strong_wolfe"):
            raise ValueError(
                f"line_search_fn must be None or 'strong_wolfe', "
                f"got {line_search_fn!r}")
        self._line_search_fn = line_search_fn
        self._hist_s: list = []
        self._hist_y: list = []
        self._hist_rho: list = []
        self._prev_flat_grad = None

    # -- flat views ----------------------------------------------------------
    def _params(self):
        return [p for p in self._parameter_list if not p.stop_gradient]

    def _gather_flat_grad(self):
        """Flatten grads with the base-class weight_decay / regularizer /
        grad_clip contract applied (so LBFGS(weight_decay=..., grad_clip=...)
        optimizes the same objective the other optimizers would)."""
        params_grads = []
        for p in self._params():
            g = p.grad
            gd = g._data if isinstance(g, Tensor) else g
            if gd is None:
                gd = jnp.zeros_like(p._data)
            params_grads.append((p, Tensor(gd)))
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        outs = []
        for p, g in params_grads:
            gd = (g._data if isinstance(g, Tensor) else g).astype(jnp.float32)
            gd = self._apply_regularizer(p._data, gd)
            if self._weight_decay:
                gd = gd + self._weight_decay * p._data.astype(jnp.float32)
            outs.append(gd.reshape(-1))
        return jnp.concatenate(outs) if outs else jnp.zeros((0,), jnp.float32)

    def _set_flat_params(self, flat):
        off = 0
        for p in self._params():
            n = int(p._data.size)
            p._data = flat[off:off + n].reshape(p._data.shape).astype(
                p._data.dtype)
            off += n

    def _gather_flat_params(self):
        return jnp.concatenate(
            [p._data.astype(jnp.float32).reshape(-1) for p in self._params()])

    # -- two-loop recursion --------------------------------------------------
    def _direction(self, flat_grad):
        q = -flat_grad
        if not self._hist_s:
            return q
        alphas = []
        for s, y, rho in zip(reversed(self._hist_s), reversed(self._hist_y),
                             reversed(self._hist_rho)):
            a = rho * jnp.dot(s, q)
            q = q - a * y
            alphas.append(a)
        s, y = self._hist_s[-1], self._hist_y[-1]
        gamma = jnp.dot(s, y) / jnp.maximum(jnp.dot(y, y), 1e-30)
        q = gamma * q
        for (s, y, rho), a in zip(zip(self._hist_s, self._hist_y,
                                      self._hist_rho), reversed(alphas)):
            b = rho * jnp.dot(y, q)
            q = q + (a - b) * s
        return q

    def _eval_closure(self, closure, x, d, t):
        self._set_flat_params(x + t * d)
        loss = closure()
        loss_v = float(loss.item() if isinstance(loss, Tensor) else loss)
        return loss_v, self._gather_flat_grad()

    def _strong_wolfe(self, closure, x, d, t, f0, g0, c1=1e-4, c2=0.9,
                      max_ls=25):
        """Bracketing strong-Wolfe line search on phi(t) = f(x + t d)."""
        dg0 = float(jnp.dot(g0, d))
        f_prev, t_prev = f0, 0.0
        f_t, g_t = self._eval_closure(closure, x, d, t)
        evals = 1
        lo, hi = None, None
        for _ in range(max_ls):
            dg_t = float(jnp.dot(g_t, d))
            if f_t > f0 + c1 * t * dg0 or (evals > 1 and f_t >= f_prev):
                lo, hi = (t_prev, f_prev), (t, f_t)
                break
            if abs(dg_t) <= -c2 * dg0:
                return t, f_t, g_t, evals
            if dg_t >= 0:
                lo, hi = (t, f_t), (t_prev, f_prev)
                break
            t_prev, f_prev = t, f_t
            t = min(t * 2.0, 1e10)
            f_t, g_t = self._eval_closure(closure, x, d, t)
            evals += 1
        if lo is None:  # never bracketed: accept last
            return t, f_t, g_t, evals
        # zoom by bisection
        for _ in range(max_ls):
            t = 0.5 * (lo[0] + hi[0])
            f_t, g_t = self._eval_closure(closure, x, d, t)
            evals += 1
            dg_t = float(jnp.dot(g_t, d))
            if f_t > f0 + c1 * t * dg0 or f_t >= lo[1]:
                hi = (t, f_t)
            else:
                if abs(dg_t) <= -c2 * dg0:
                    break
                if dg_t * (hi[0] - lo[0]) >= 0:
                    hi = lo
                lo = (t, f_t)
            if abs(hi[0] - lo[0]) < self._tol_change:
                break
        return t, f_t, g_t, evals

    @no_grad()
    def step(self, closure=None):
        if closure is None:
            raise ValueError("LBFGS.step requires a closure returning the "
                             "loss (ref lbfgs.py)")
        self._global_step += 1

        def run_closure():
            from ..core import autograd as _ag
            with _ag.enable_grad():
                return closure()

        loss = run_closure()
        loss_v = float(loss.item() if isinstance(loss, Tensor) else loss)
        flat_grad = self._gather_flat_grad()
        evals = 1
        lr = self.get_lr()
        for _ in range(self._max_iter):
            if float(jnp.max(jnp.abs(flat_grad))) <= self._tol_grad:
                break
            d = self._direction(flat_grad)
            x = self._gather_flat_params()
            t = lr if self._hist_s else min(1.0, 1.0 / max(
                float(jnp.sum(jnp.abs(flat_grad))), 1e-30)) * lr
            if self._line_search_fn == "strong_wolfe":
                t, new_loss, new_grad, n_evals = self._strong_wolfe(
                    closure=lambda: run_closure(), x=x, d=d, t=t,
                    f0=loss_v, g0=flat_grad)
                evals += n_evals
                self._set_flat_params(x + t * d)
            else:
                self._set_flat_params(x + t * d)
                new_loss_t = run_closure()
                new_loss = float(new_loss_t.item()
                                 if isinstance(new_loss_t, Tensor)
                                 else new_loss_t)
                new_grad = self._gather_flat_grad()
                evals += 1
            s = t * d
            y = new_grad - flat_grad
            ys = float(jnp.dot(y, s))
            if ys > 1e-10:
                if len(self._hist_s) >= self._history_size:
                    self._hist_s.pop(0)
                    self._hist_y.pop(0)
                    self._hist_rho.pop(0)
                self._hist_s.append(s)
                self._hist_y.append(y)
                self._hist_rho.append(1.0 / ys)
            if abs(new_loss - loss_v) < self._tol_change:
                loss_v, flat_grad = new_loss, new_grad
                break
            loss_v, flat_grad = new_loss, new_grad
            if evals >= self._max_eval:
                break
        self._prev_flat_grad = flat_grad
        return loss
