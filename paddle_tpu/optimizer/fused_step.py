"""One-executable optimizer step: fused multi-tensor updates with buffer
donation.

The forward path compiles as few, fat executables (core/fusion.py, the
jit train steps), but an eager training step still ended in a dispatch
storm: Adam/AdamW/Momentum issue ~5-10 tiny ops *per parameter* (moment
updates, bias correction, write-back), plus a full per-parameter pass
for global-norm clipping and another for the AMP grad-scaler's finite
check. This module flattens the whole parameter tree — grads, params,
moments — into one pytree and compiles **ONE** jitted, buffer-donated
executable per (optimizer type, tree structure, dtypes/shapes,
hyperparameter-static config) key:

* **Donation** — params and optimizer state (and, on the grad-scaler
  path, grads) are donated to XLA, so the update happens in place in
  HBM instead of allocating a second copy of the model. The handles'
  ``._data`` are rebound to the outputs; the old buffers are dead.
* **Dynamic scalars** — lr (from any ``optimizer.lr`` scheduler) and
  the AMP loss scale enter as 0-d device-array *arguments*, never as
  baked constants: a changing LR schedule hits the same executable
  every step (<= 1 steady-state compile across a whole schedule).
  Beta-power accumulators are ordinary state leaves, already dynamic.
* **Folded clip + AMP** — ``ClipGradByGlobalNorm``/``ByNorm``/``ByValue``
  (utils/clip_grad pure specs) run inside the same program, and
  ``GradScaler.step`` routes here with the loss scale so grad unscale,
  the global inf/nan check AND the conditional skip (``where(found_inf,
  old, new)`` on every param/state leaf) are part of the one executable
  — the skip decision never touches the host.
* **Compile policy** — mirrors the fusion plane: a structure compiles on
  its SECOND sighting (one-off steps run un-jitted, steady loops compile
  once at step two) and lives in an LRU keyed as above, shared across
  optimizer instances with identical static config.

Fallbacks are total and cheap: unknown clip/regularizer objects,
non-static hyperparameters, aliased buffers, tracer leaves or the
``FLAGS_fused_optimizer=0`` kill switch all return to the existing
per-param eager loop (``Optimizer._eager_step``), counted by reason in
``optimizer.fallbacks_total``. ``state_dict()``/``set_state_dict()``
round-trips are byte-identical: state dicts keep their exact keys and
leaf arrays, only produced by one program instead of N dispatches.

Observability (PR 3 registry): ``optimizer.fused_steps_total``,
``fused_step_seconds``, ``donated_bytes``, ``fused_compiles_total``,
``cache_hits_total``, ``uncompiled_runs_total``, ``fallbacks_total``
{reason} and a ``fused_optimizer_compile`` host-tracer span on the
first (trace+compile) execution of each program.
"""
from __future__ import annotations

import threading
import time as _time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..core.flags import _registry as _flag_registry
from ..core.tensor import Tensor, buffer_has_alias as _has_alias
from ..observability import flight as _flight
from ..observability import metrics as _om
from ..utils.clip_grad import clip_by_spec, clip_spec

__all__ = ["try_step", "try_step_scaled", "unscale_and_check", "enabled",
           "clear_cache", "apply_update_tail"]

_flag = _flag_registry["fused_optimizer"]
_cache_cap = _flag_registry["fused_optimizer_cache"]

_M = _om.scope("optimizer")
_M_flag = _om.flag_info()
_M_steps = _M.counter(
    "fused_steps_total",
    "Optimizer steps executed as one fused, donated executable")
_M_step_s = _M.histogram(
    "fused_step_seconds",
    "Host wall seconds per fused optimizer step (dispatch-side; the "
    "device work is async)")
_M_donated = _M.counter(
    "donated_bytes",
    "Bytes of params + optimizer state (+ grads on the scaled path) "
    "donated to fused step executables — updated in place in HBM")
_M_compiles = _M.counter(
    "fused_compiles_total",
    "Fused optimizer-step programs compiled (trace + XLA build)")
_M_hits = _M.counter(
    "cache_hits_total", "Fused steps served by a cached executable")
_M_uncompiled = _M.counter(
    "uncompiled_runs_total", "First-sighting steps run un-jitted")
_M_fallbacks = _M.counter(
    "fallbacks_total",
    "Steps that fell back to the per-param eager loop, by reason")
_M_compile_s = _M.histogram(
    "compile_seconds",
    "First execution (trace+compile) of a fused step program")

# optimizer attrs that are NOT numeric hyperparameters: containers,
# transient per-step scratch, the dynamic lr, and this module's own
# per-optimizer caches. Everything else must be a hashable scalar/tuple
# or the optimizer falls back (conservative: unknown state never fuses).
_HYPER_EXCLUDE = frozenset({
    "_parameter_list", "_learning_rate", "_grad_clip", "_regularizer",
    "_states", "_global_step", "_param_names", "_current_pid",
    "_cur_param", "_exclude_fn", "_apply_decay_param_fun",
    "_found_inf_arg", "_fused_lr_host", "_fused_lr_dev",
})

_programs: "OrderedDict[tuple, tuple]" = OrderedDict()
_lock = threading.Lock()
_SEEN = object()  # first-sighting marker: structure noted, not compiled

# Analysis-auditor hook (paddle_tpu.analysis.auditor): notified with
# (opt, prep, mode) just before a donating (jit-mode) fused step
# executes, so a capture audit can record every donated buffer and
# later detect live handles that would read one after XLA deletes it.
# None outside an audit.
_donation_observer = None


def enabled() -> bool:
    return bool(_flag.value)


def clear_cache() -> None:
    with _lock:
        _programs.clear()


def _fallback(reason: str):
    _M_fallbacks.inc(reason=reason)
    _flight.record("optimizer", "fallback", reason=reason)
    return None


def _hyper_key(opt) -> Optional[tuple]:
    """Hashable static-hyperparameter tuple, or None when the optimizer
    carries attrs this plane can't prove static (user subclass state)."""
    items = []
    for k, v in sorted(vars(opt).items()):
        if k in _HYPER_EXCLUDE:
            continue
        if v is None or isinstance(v, (bool, int, float, str)):
            items.append((k, v))
        elif isinstance(v, tuple) and all(
                isinstance(x, (bool, int, float, str)) for x in v):
            items.append((k, v))
        else:
            return None
    return tuple(items)


def _param_statics(opt, params) -> Optional[tuple]:
    """Per-param trace-time-static decisions that must ride the cache
    key: the effective weight-decay coefficient (AdamW's
    apply_decay_param_fun) and Lamb's exclude decision."""
    has_pid = hasattr(opt, "_current_pid")
    exf = getattr(opt, "_exclude_fn", None)
    out = []
    for p in params:
        if has_pid:
            opt._current_pid = id(p)
        opt._cur_param = p
        try:
            wd = float(opt._use_wd(p))
        except (TypeError, ValueError):
            return None
        out.append((wd, bool(exf(p)) if exf is not None else None))
    if has_pid:
        opt._current_pid = None
    return tuple(out)


class _TraceCtx:
    """Mutable cell carrying the live optimizer + Parameter handles into
    ``step_fn`` ONLY for the duration of a call: ``_execute`` fills it
    just before invoking the (possibly re-tracing) program and clears it
    after, so a cached executable never pins a dead model's params or
    optimizer state between steps. Any trace necessarily happens inside
    an active call, when the cell is populated — and every numeric
    constant the trace reads off the instance is part of the cache key,
    so a structural hit from a different optimizer instance is
    numerically identical."""
    __slots__ = ("opt", "params")

    def __init__(self):
        self.opt = None
        self.params = None


def apply_update_tail(opt, param_objs, p_leaves, g_leaves, s_leaves, lr,
                      cspec):
    """The optimizer tail segment: clip -> regularizer -> per-param pure
    ``_update`` over raw leaves, pure and jittable. ONE definition shared
    by the fused optimizer step (:func:`_make_fn`) and the SOT whole-step
    capture engine (jit/sot.py), where the donated optimizer program is
    the tail of the captured fwd+bwd+opt executable. Returns
    ``(new_p_leaves, new_s_leaves)``."""
    gs = list(g_leaves)
    if cspec:
        gs = clip_by_spec(cspec, gs)
    has_pid = hasattr(opt, "_current_pid")
    new_ps: List[Any] = []
    new_ss: List[Dict[str, Any]] = []
    for i, p in enumerate(param_objs):
        if has_pid:
            opt._current_pid = id(p)
        opt._cur_param = p
        g = opt._apply_regularizer(p_leaves[i], gs[i])
        new_p, new_s = opt._update(p_leaves[i], g, s_leaves[i], lr)
        new_ps.append(new_p)
        new_ss.append(new_s)
    if has_pid:
        opt._current_pid = None
    return new_ps, new_ss


def _make_fn(ctx, mode, cspec, n):
    """The pure whole-step function. ``mode``:

    - "plain": scalars=(lr,)            -> (new_params, new_states)
    - "found": scalars=(lr, found_inf)  -> + masked updates
    - "scaled": scalars=(lr, inv_scale, prior_found) -> unscale + finite
      check inside; updates masked by ``this_check | prior_found`` (the
      scaler's OR-accumulated flag from earlier unscale_ calls, so the
      skip decision matches the unfused fallback exactly); returns
      (new_params, new_states, unscaled_grads, found_inf_of_this_check)
    """

    def step_fn(params, grads, states, scalars):
        opt, param_objs = ctx.opt, ctx.params
        lr = scalars[0]
        gs = list(grads)
        found = None
        if mode == "scaled":
            gs, found_own = _unscale_fn(gs, scalars[1])
            unscaled = list(gs)
            found = jnp.logical_or(found_own, scalars[2])
        elif mode == "found":
            found = scalars[1]
        new_ps, new_ss = apply_update_tail(opt, param_objs, params, gs,
                                           states, lr, cspec)
        if found is not None:
            # conditional skip ON DEVICE: a non-finite grad signal keeps
            # every param AND state leaf at its old value
            new_ps = [jnp.where(found, p, q)
                      for p, q in zip(params, new_ps)]
            new_ss = [{k: jnp.where(found, st[k], v)
                       for k, v in ns.items()}
                      for st, ns in zip(states, new_ss)]
        if mode == "scaled":
            return new_ps, new_ss, unscaled, found_own
        if mode == "found":
            return new_ps, new_ss, found
        return new_ps, new_ss

    return step_fn


def _trace_compile_span(dt: float) -> None:
    """Land the trace+compile window as a ``fused_optimizer_compile``
    span when the native host tracer is live (same contract as the
    fusion plane's ``fusion_compile[kind]`` spans). Lazy module lookup
    only — never triggers the native build."""
    import sys
    mod = sys.modules.get("paddle_tpu._native")
    lib = getattr(mod, "lib", None)
    if lib is None:
        return
    try:
        if lib.tracer_enabled():
            now = lib.tracer_now()
            lib.tracer_record("fused_optimizer_compile",
                              now - dt * 1e6, now)
    except Exception:
        pass


def _timed_first_call(jf):
    done = [False]

    def wrapper(*a):
        if done[0]:
            return jf(*a)
        t0 = _time.perf_counter()
        out = jf(*a)
        done[0] = True
        dt = _time.perf_counter() - t0
        _M_compiles.inc()
        _M_compile_s.observe(dt)
        _trace_compile_span(dt)
        return out

    return wrapper


def _get_program(key, builder, donate):
    """Second-sighting compile policy (mirrors the fusion plane): the
    first flush of a structure runs the pure fn un-jitted, the second
    compiles + donates, later ones hit the cache. Entries are
    (kind, fn, ctx) — ``ctx`` the program's _TraceCtx cell."""
    with _lock:
        entry = _programs.get(key)
        if entry is not None and entry is not _SEEN:
            _programs.move_to_end(key)
            _M_hits.inc()
            return entry

    def _put(e):
        with _lock:
            _programs[key] = e
            cap = max(int(_cache_cap.value or 32), 4)
            while len(_programs) > cap:
                _programs.popitem(last=False)

    ctx = _TraceCtx()
    if entry is _SEEN:
        from ..jit.warmup import ensure_executable_cache
        ensure_executable_cache()  # fused steps persist across boots
        jf = jax.jit(builder(ctx), donate_argnums=donate)
        entry = ("jit", _timed_first_call(jf), ctx)
        _put(entry)
        return entry
    _M_uncompiled.inc()
    _put(_SEEN)
    return ("eager", builder(ctx), ctx)


class _Prep:
    __slots__ = ("params", "p_leaves", "g_leaves", "s_leaves", "key",
                 "cspec", "nbytes")


def _prepare(opt, params_grads, mode) -> Optional[_Prep]:
    """Gate + flatten. Returns None (fallback, reason counted) or the
    prepared leaves + structural cache key."""
    if getattr(opt, "_fusable_step", True) is False:
        return _fallback("optimizer")
    cspec = clip_spec(opt._grad_clip)
    if cspec is None:
        return _fallback("grad_clip")
    reg = opt._regularizer
    if reg is None:
        rspec = ()
    else:
        coeff = getattr(reg, "_coeff", getattr(reg, "coeff", None))
        if coeff is None:
            return _fallback("regularizer")
        rspec = (type(reg).__qualname__, float(coeff))
    hyper = _hyper_key(opt)
    if hyper is None:
        return _fallback("hyper")
    params = [p for p, _ in params_grads]
    statics = _param_statics(opt, params)
    if statics is None:
        return _fallback("param_static")
    if len({id(p) for p in params}) != len(params):
        return _fallback("duplicate_param")

    p_leaves, g_leaves, s_leaves, tree = [], [], [], []
    donated_ids = set()
    nbytes = 0
    for (p, g), stat in zip(params_grads, statics):
        pd = p._data
        gd = g._data if isinstance(g, Tensor) else g
        if isinstance(pd, jax.core.Tracer) or \
                isinstance(gd, jax.core.Tracer):
            return _fallback("tracer")
        st = opt._state_for(p)
        for v in st.values():
            if not (hasattr(v, "shape") and hasattr(v, "dtype")):
                return _fallback("state")
        if not isinstance(pd, jax.Array):
            pd = jnp.asarray(pd)
        if not isinstance(gd, jax.Array):
            gd = jnp.asarray(gd)
        st = {k: (v if isinstance(v, jax.Array) else jnp.asarray(v))
              for k, v in st.items()}
        # a leaf another live Tensor handle shares (p.detach()) must not
        # be donated — XLA would delete it under the alias; copy it so
        # the snapshot stays readable (eager replace-don't-mutate parity)
        if _has_alias(pd):
            pd = jnp.copy(pd)
        if mode == "scaled" and _has_alias(gd):
            gd = jnp.copy(gd)
        st = {k: (jnp.copy(v) if _has_alias(v) else v)
              for k, v in st.items()}
        # donated leaves must be distinct buffers: donating one buffer
        # twice (tied weights sharing storage, a state aliasing its
        # param) is an XLA error — fall back rather than risk it
        for leaf in [pd, *st.values()] + ([gd] if mode == "scaled"
                                          else []):
            if id(leaf) in donated_ids:
                return _fallback("aliased")
            donated_ids.add(id(leaf))
            nbytes += int(getattr(leaf, "nbytes", 0))
        p_leaves.append(pd)
        g_leaves.append(gd)
        s_leaves.append(st)
        tree.append((tuple(pd.shape), str(pd.dtype),
                     tuple(gd.shape), str(gd.dtype), stat,
                     tuple(sorted((k, tuple(v.shape), str(v.dtype))
                                  for k, v in st.items()))))

    prep = _Prep()
    prep.params = params
    prep.p_leaves = p_leaves
    prep.g_leaves = g_leaves
    prep.s_leaves = s_leaves
    prep.cspec = cspec
    prep.nbytes = nbytes
    prep.key = (type(opt).__qualname__, mode, hyper, cspec, rspec,
                tuple(tree))
    return prep


def _lr_device(opt):
    """Per-step lr as a committed 0-d f32 device array, uploaded only
    when the host value actually changed (TrainStep's lr cache)."""
    lr_now = float(opt.get_lr())
    if getattr(opt, "_fused_lr_host", None) != lr_now:
        opt._fused_lr_dev = jnp.float32(lr_now)
        opt._fused_lr_host = lr_now
    return opt._fused_lr_dev


def _flush_pending_chains():
    """A pending lazy-fusion chain may hold a buffer we are about to
    DONATE (e.g. ``wn = (p * p).sum()`` deferred past the step) —
    flush every pending chain before XLA invalidates its inputs."""
    from ..core import fusion
    if fusion.has_pending():
        fusion.flush_pending("donation")


def _execute(opt, prep, mode, scalars):
    n = len(prep.params)
    kind, fn, ctx = _get_program(
        prep.key,
        lambda ctx: _make_fn(ctx, mode, prep.cspec, n),
        donate=(0, 1, 2) if mode == "scaled" else (0, 2))
    if kind == "jit":
        _flush_pending_chains()
        _flight.record("optimizer", "fused_step", mode=mode,
                       params=len(prep.params))
        if _donation_observer is not None:
            _donation_observer(opt, prep, mode)
    # populate the trace cell only for the duration of the call: a
    # (re)trace can only happen inside it, and the cache pins nothing
    # of this model/optimizer afterwards
    ctx.opt, ctx.params = opt, prep.params
    t0 = _time.perf_counter()
    try:
        outs = fn(prep.p_leaves, prep.g_leaves, prep.s_leaves, scalars)
    finally:
        ctx.opt = ctx.params = None
    if _M_flag.value:
        _M_steps._v += 1
    _M_step_s.observe(_time.perf_counter() - t0)
    if kind == "jit":
        _M_donated.inc(prep.nbytes)
    new_ps, new_ss = outs[0], outs[1]
    for p, new_p, new_s in zip(prep.params, new_ps, new_ss):
        p._data = new_p
        opt._states[id(p)] = new_s
    if mode == "scaled":
        for p, ng in zip(prep.params, outs[2]):
            if isinstance(p.grad, Tensor):
                p.grad._data = ng
            else:
                p.grad = Tensor(ng)
        return outs[3]
    if mode == "found":
        return outs[2]
    return None


def try_step(opt, params_grads, found_inf=None) -> bool:
    """Run the whole optimizer step as ONE fused executable. Returns
    False when the caller should run the per-param eager loop instead
    (kill switch, unsupported config). ``found_inf`` (a 0-d device bool
    from GradScaler.unscale_) masks every update on device."""
    if not _flag.value:
        return False
    mode = "plain" if found_inf is None else "found"
    prep = _prepare(opt, params_grads, mode)
    if prep is None:
        return False
    lr = _lr_device(opt)
    if mode == "found":
        scalars = (lr, jnp.asarray(found_inf, bool))
    else:
        scalars = (lr,)
    _execute(opt, prep, mode, scalars)
    return True


def try_step_scaled(opt, scale, prior_found=False):
    """GradScaler.step fast path: grad unscale, global finite check,
    clip, every param update AND the conditional skip as ONE donated
    executable. ``prior_found`` (the scaler's OR-accumulated flag from
    earlier unscale_ calls this iteration) joins the on-device mask so
    multi-optimizer skip decisions match the unfused fallback. Returns
    the 0-d device found_inf of THIS check, or None when the caller
    must fall back (then: batched unscale_ + masked step)."""
    if not _flag.value:
        return None
    params_grads = [(p, p.grad) for p in opt._parameter_list
                    if not p.stop_gradient and p.grad is not None]
    if not params_grads:
        return None
    if any(p.stop_gradient and p.grad is not None
           for p in opt._parameter_list):
        # the fallback unscales + finite-checks EVERY grad, including
        # frozen params'; this program only sees trainable ones — defer
        # so the skip decision and post-step p.grad values can't depend
        # on the flag
        return _fallback("frozen_param_grads")
    prep = _prepare(opt, params_grads, "scaled")
    if prep is None:
        return None
    inv = jnp.float32(1.0) / scale
    found = _execute(opt, prep, "scaled",
                     (_lr_device(opt), inv,
                      jnp.asarray(prior_found, bool)))
    opt._global_step += 1
    return found


# -- batched unscale + finite check (the unfused-path device decision) ----

_unscale_jit = None
_unscale_jit_donated = None


def _unscale_fn(gs, inv):
    """Unscale in fp32 then restore the grad dtype — one pass; the
    check runs AFTER the unscale like the reference's
    check_finite_and_unscale (inf/nan survive the multiply). The ONE
    numeric definition shared by the fused scaled step (_make_fn) and
    the batched fallback (unscale_and_check)."""
    outs = [(g.astype(jnp.float32) * inv).astype(g.dtype)
            for g in gs]
    finite = jnp.stack(
        [jnp.all(jnp.isfinite(g)) for g in outs]).all()
    return outs, jnp.logical_not(finite)


def unscale_and_check(grads, inv_scale):
    """ONE executable over every grad: unscale (fp32 math, dtype
    restored) + global finite check. Returns (new_grads, found_inf 0-d
    device bool) — the skip decision never syncs to host. The caller
    rebinds every grad to the outputs, so the input buffers are
    donated (in-place unscale, no transient second grad copy) unless
    two entries alias one buffer. jax.jit's own cache keys the
    grad-tree structure, so steady-state loops reuse one program per
    tree."""
    global _unscale_jit, _unscale_jit_donated
    gs = list(grads)
    if len({id(g) for g in gs}) == len(gs):
        _flush_pending_chains()
        # a grad buffer shared by a live detached handle must survive
        # the donation — copy it, donate the copy
        gs = [jnp.copy(g) if _has_alias(g) else g for g in gs]
        if _unscale_jit_donated is None:
            _unscale_jit_donated = jax.jit(_unscale_fn, donate_argnums=0)
        return _unscale_jit_donated(gs, inv_scale)
    if _unscale_jit is None:
        _unscale_jit = jax.jit(_unscale_fn)
    return _unscale_jit(gs, inv_scale)
