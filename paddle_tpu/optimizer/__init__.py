"""paddle.optimizer equivalent. ref: python/paddle/optimizer/__init__.py"""
from .optimizer import (  # noqa: F401
    Optimizer, SGD, Momentum, Adagrad, Adam, AdamW, Adamax, RMSProp, Lamb,
    Adadelta,
)
from . import lr  # noqa: F401
