"""paddle.optimizer equivalent. ref: python/paddle/optimizer/__init__.py"""
from .optimizer import (  # noqa: F401
    Optimizer, SGD, Momentum, Adagrad, Adam, AdamW, Adamax, RMSProp, Lamb,
    Adadelta,
)
from .extra import ASGD, LBFGS, NAdam, RAdam, Rprop  # noqa: F401
from . import fused_step  # noqa: F401
from . import lr  # noqa: F401
