"""Optimizer base + SGD/Momentum/Adam/AdamW/etc.

ref: python/paddle/optimizer/optimizer.py. TPU-native design: each optimizer
defines a *pure* per-parameter update ``_update(p, g, state, lr) ->
(new_p, new_state)`` over jnp arrays. Eager ``step()`` loops parameters and
mutates leaf tensors; the jit path (paddle_tpu.jit.TrainStep) calls the same
pure update inside the traced program, so eager and compiled training share
one numeric definition (the analog of the reference's fused
multi-tensor/adamw kernels is XLA fusing this update across params).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.autograd import no_grad
from ..core.tensor import Parameter, Tensor
from .lr import LRScheduler


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        if parameters is None:
            raise ValueError(
                "parameters must be provided (pass model.parameters())")
        self._parameter_list = list(parameters)
        self._learning_rate = learning_rate
        self._grad_clip = grad_clip
        self._regularizer = None  # non-L2 penalty applied to grads
        if isinstance(weight_decay, float):
            self._weight_decay = weight_decay
        elif weight_decay is None:
            self._weight_decay = 0.0
        else:
            from ..regularizer import L1Decay
            if isinstance(weight_decay, L1Decay):
                # L1 is NOT a coefficient-foldable decay: apply its grad
                # penalty explicitly (ref: regularizer.py append to grad)
                self._regularizer = weight_decay
                self._weight_decay = 0.0
            else:  # L2Decay-like object with a coeff
                self._weight_decay = getattr(
                    weight_decay, "_coeff",
                    getattr(weight_decay, "coeff", 0.0))
        # per-param slot states keyed by id(param)
        self._states: Dict[int, Dict[str, Any]] = {}
        self._global_step = 0

    # -- lr ------------------------------------------------------------------
    def get_lr(self) -> float:
        if isinstance(self._learning_rate, LRScheduler):
            return float(self._learning_rate())
        return float(self._learning_rate)

    def set_lr(self, value):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError(
                "cannot set_lr when the learning rate is a scheduler")
        self._learning_rate = value

    # -- states --------------------------------------------------------------
    def _init_state(self, p: Parameter) -> Dict[str, Any]:
        return {}

    def _state_for(self, p: Parameter) -> Dict[str, Any]:
        s = self._states.get(id(p))
        if s is None:
            s = self._init_state(p)
            self._states[id(p)] = s
        return s

    # -- the pure update (override per optimizer) ---------------------------
    def _update(self, p, g, state, lr):
        raise NotImplementedError

    def _apply_regularizer(self, p, g):
        """Non-L2 grad penalty (e.g. L1Decay); pure, safe under jit. Called
        by step() and the compiled train steps before _update."""
        if self._regularizer is None:
            return g
        return self._regularizer(p, g)

    def _use_wd(self, p) -> float:
        return self._weight_decay

    # -- step ----------------------------------------------------------------
    @no_grad()
    def step(self):
        self._global_step += 1
        params_grads = [(p, p.grad) for p in self._parameter_list
                        if not p.stop_gradient and p.grad is not None]
        if not params_grads:
            return
        from . import fused_step
        if fused_step.try_step(self, params_grads):
            return
        self._eager_step(params_grads)

    @no_grad()
    def _step_masked(self, found_inf, try_fused=True):
        """AMP path (GradScaler.step): identical to ``step()`` except
        every param/state write is masked by the 0-d device bool
        ``found_inf`` — a non-finite grad keeps the old values without
        the skip decision ever syncing to host. ``try_fused=False`` when
        the caller already ran (and failed) the fused gate this step,
        so the O(n-params) prepare scan and its fallback counter don't
        run twice."""
        self._global_step += 1
        params_grads = [(p, p.grad) for p in self._parameter_list
                        if not p.stop_gradient and p.grad is not None]
        if not params_grads:
            return
        if try_fused:
            from . import fused_step
            if fused_step.try_step(self, params_grads,
                                   found_inf=found_inf):
                return
        self._eager_step(params_grads, found_inf=found_inf)

    def _eager_step(self, params_grads, found_inf=None):
        """The per-param update loop: the FLAGS_fused_optimizer=0 kill
        switch and the fallback for configs the fused plane can't prove
        safe (unknown clip/regularizer objects, non-static hyperparams,
        aliased buffers, tracers)."""
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        lr = self.get_lr()
        has_pid = hasattr(self, "_current_pid")
        for p, g in params_grads:
            gd = g._data if isinstance(g, Tensor) else g
            gd = self._apply_regularizer(p._data, gd)
            state = self._state_for(p)
            self._cur_param = p  # lets _update consult Parameter metadata
            if has_pid:
                self._current_pid = id(p)
            new_p, new_state = self._update(p._data, gd, state, lr)
            if found_inf is not None:
                new_p = jnp.where(found_inf, p._data, new_p)
                new_state = {k: jnp.where(found_inf, state[k], v)
                             for k, v in new_state.items()}
            p._data = new_p
            self._states[id(p)] = new_state
        if has_pid:
            self._current_pid = None

    def clear_grad(self, set_to_zero=False):
        for p in self._parameter_list:
            p.clear_gradient(set_to_zero)

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        from ..static.program import current_program
        prog = current_program()
        if prog is not None:
            # static mode: attach; Executor.run compiles loss->grads->update
            # into the replayed program (ref: append_backward + optimizer
            # ops in static Program)
            prog._optimizer = self
            prog._loss = loss
            prog.version += 1
            return [], [(p, None) for p in self._parameter_list]
        loss.backward()
        self.step()
        self.clear_grad()

    # -- checkpointing -------------------------------------------------------
    def state_dict(self):
        out = {"global_step": self._global_step}
        if isinstance(self._learning_rate, LRScheduler):
            out["LR_Scheduler"] = self._learning_rate.state_dict()
        for i, p in enumerate(self._parameter_list):
            s = self._states.get(id(p))
            if s:
                for k, v in s.items():
                    # snapshot-copy: the live leaf will be DONATED by
                    # the next fused step (deleted), and the old eager
                    # loop's replace-don't-mutate gave the exported dict
                    # exactly these point-in-time values
                    if isinstance(v, Tensor):
                        v = v._data
                    out[f"param_{i}_{k}"] = Tensor(jnp.copy(v))
        return out

    def set_state_dict(self, state_dict):
        self._global_step = state_dict.get("global_step", 0)
        if isinstance(self._learning_rate, LRScheduler) and \
                "LR_Scheduler" in state_dict:
            self._learning_rate.set_state_dict(state_dict["LR_Scheduler"])
        for i, p in enumerate(self._parameter_list):
            s = {}
            prefix = f"param_{i}_"
            for k, v in state_dict.items():
                if isinstance(k, str) and k.startswith(prefix):
                    val = v._data if isinstance(v, Tensor) else jnp.asarray(
                        np.asarray(v))
                    # copy on install: the leaf will be donated by the
                    # next fused step; the caller's dict must survive
                    s[k[len(prefix):]] = jnp.copy(val)
            if s:
                self._states[id(p)] = s


class SGD(Optimizer):
    """ref: python/paddle/optimizer/sgd.py"""

    def _update(self, p, g, state, lr):
        g = g.astype(jnp.float32)
        wd = self._weight_decay
        if wd:
            g = g + wd * p.astype(jnp.float32)
        return (p - lr * g.astype(p.dtype)).astype(p.dtype), state


class Momentum(Optimizer):
    """ref: python/paddle/optimizer/momentum.py"""

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _init_state(self, p):
        return {"velocity": jnp.zeros_like(p._data, jnp.float32)}

    def _update(self, p, g, state, lr):
        g = g.astype(jnp.float32)
        if self._weight_decay:
            g = g + self._weight_decay * p.astype(jnp.float32)
        v = self._momentum * state["velocity"] + g
        if self._nesterov:
            upd = g + self._momentum * v
        else:
            upd = v
        return (p.astype(jnp.float32) - lr * upd).astype(p.dtype), \
            {"velocity": v}


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None,
                 initial_accumulator_value=0.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _init_state(self, p):
        return {"moment": jnp.full_like(p._data, self._init_acc,
                                        jnp.float32)}

    def _update(self, p, g, state, lr):
        g = g.astype(jnp.float32)
        if self._weight_decay:
            g = g + self._weight_decay * p.astype(jnp.float32)
        m = state["moment"] + g * g
        new_p = p.astype(jnp.float32) - lr * g / (jnp.sqrt(m) +
                                                  self._epsilon)
        return new_p.astype(p.dtype), {"moment": m}


class Adam(Optimizer):
    """ref: python/paddle/optimizer/adam.py (L2 regularization folded into
    the gradient, unlike AdamW's decoupled decay)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=True,
                 use_multi_tensor=False, name=None, amsgrad=False):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._decoupled_wd = False
        # multi_precision=True (default): fp32 moments regardless of param
        # dtype (ref: adam.py multi_precision master-state semantics).
        # False: moments stored in the param dtype — halves optimizer HBM
        # for bf16 models at a small numerics cost.
        self._multi_precision = multi_precision

    def _moment_dtype(self, p_data):
        return jnp.float32 if self._multi_precision else p_data.dtype

    def _init_state(self, p):
        d = self._moment_dtype(p._data)
        return {
            "moment1": jnp.zeros_like(p._data, d),
            "moment2": jnp.zeros_like(p._data, d),
            "beta1_pow": jnp.ones((), jnp.float32),
            "beta2_pow": jnp.ones((), jnp.float32),
        }

    def _update(self, p, g, state, lr):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        g = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        wd = self._use_wd(p)
        if wd and not self._decoupled_wd:
            g = g + wd * p32
        m1 = b1 * state["moment1"].astype(jnp.float32) + (1 - b1) * g
        m2 = b2 * state["moment2"].astype(jnp.float32) + (1 - b2) * g * g
        b1p = state["beta1_pow"] * b1
        b2p = state["beta2_pow"] * b2
        m1_hat = m1 / (1 - b1p)
        m2_hat = m2 / (1 - b2p)
        upd = m1_hat / (jnp.sqrt(m2_hat) + eps)
        if wd and self._decoupled_wd:
            upd = upd + wd * p32
        new_p = (p32 - lr * upd).astype(p.dtype)
        md = self._moment_dtype(p)
        return new_p, {"moment1": m1.astype(md), "moment2": m2.astype(md),
                       "beta1_pow": b1p, "beta2_pow": b2p}


class AdamW(Adam):
    """Decoupled weight decay. ref: python/paddle/optimizer/adamw.py"""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=True, name=None,
                 amsgrad=False):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip,
                         multi_precision=multi_precision)
        self._decoupled_wd = True
        self._apply_decay_param_fun = apply_decay_param_fun
        self._param_names = {id(p): getattr(p, "name", "") or f"param_{i}"
                             for i, p in enumerate(self._parameter_list)}
        self._current_pid = None

    def _use_wd(self, p):
        if self._apply_decay_param_fun is not None:
            name = self._param_names.get(self._current_pid, "")
            if not self._apply_decay_param_fun(name):
                return 0.0
        return self._weight_decay


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _init_state(self, p):
        return {"moment": jnp.zeros_like(p._data, jnp.float32),
                "inf_norm": jnp.zeros_like(p._data, jnp.float32),
                "beta1_pow": jnp.ones((), jnp.float32)}

    def _update(self, p, g, state, lr):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        g = g.astype(jnp.float32)
        if self._weight_decay:
            g = g + self._weight_decay * p.astype(jnp.float32)
        m = b1 * state["moment"] + (1 - b1) * g
        u = jnp.maximum(b2 * state["inf_norm"], jnp.abs(g))
        b1p = state["beta1_pow"] * b1
        new_p = (p.astype(jnp.float32) -
                 lr / (1 - b1p) * m / (u + eps)).astype(p.dtype)
        return new_p, {"moment": m, "inf_norm": u, "beta1_pow": b1p}


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _init_state(self, p):
        s = {"mean_square": jnp.zeros_like(p._data, jnp.float32),
             "momentum": jnp.zeros_like(p._data, jnp.float32)}
        if self._centered:
            s["mean_grad"] = jnp.zeros_like(p._data, jnp.float32)
        return s

    def _update(self, p, g, state, lr):
        rho, eps = self._rho, self._epsilon
        g = g.astype(jnp.float32)
        if self._weight_decay:
            g = g + self._weight_decay * p.astype(jnp.float32)
        ms = rho * state["mean_square"] + (1 - rho) * g * g
        new_state = {"mean_square": ms}
        if self._centered:
            mg = rho * state["mean_grad"] + (1 - rho) * g
            denom = jnp.sqrt(ms - mg * mg + eps)
            new_state["mean_grad"] = mg
        else:
            denom = jnp.sqrt(ms + eps)
        mom = self._momentum * state["momentum"] + lr * g / denom
        new_state["momentum"] = mom
        return (p.astype(jnp.float32) - mom).astype(p.dtype), new_state


class Lamb(Optimizer):
    """ref: python/paddle/optimizer/lamb.py"""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None,
                 name=None):
        super().__init__(learning_rate, parameters, lamb_weight_decay,
                         grad_clip)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._exclude_fn = exclude_from_weight_decay_fn

    def _init_state(self, p):
        return {"moment1": jnp.zeros_like(p._data, jnp.float32),
                "moment2": jnp.zeros_like(p._data, jnp.float32),
                "beta1_pow": jnp.ones((), jnp.float32),
                "beta2_pow": jnp.ones((), jnp.float32)}

    def _update(self, p, g, state, lr):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        g = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        m1 = b1 * state["moment1"] + (1 - b1) * g
        m2 = b2 * state["moment2"] + (1 - b2) * g * g
        b1p = state["beta1_pow"] * b1
        b2p = state["beta2_pow"] * b2
        r = (m1 / (1 - b1p)) / (jnp.sqrt(m2 / (1 - b2p)) + eps)
        wd = self._weight_decay
        if self._exclude_fn is not None and \
                self._exclude_fn(getattr(self, "_cur_param", None)):
            wd = 0.0
        r = r + wd * p32
        w_norm = jnp.linalg.norm(p32)
        r_norm = jnp.linalg.norm(r)
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        new_p = (p32 - lr * trust * r).astype(p.dtype)
        return new_p, {"moment1": m1, "moment2": m2, "beta1_pow": b1p,
                       "beta2_pow": b2p}


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._epsilon, self._rho = epsilon, rho

    def _init_state(self, p):
        return {"avg_squared_grad": jnp.zeros_like(p._data, jnp.float32),
                "avg_squared_update": jnp.zeros_like(p._data, jnp.float32)}

    def _update(self, p, g, state, lr):
        rho, eps = self._rho, self._epsilon
        g = g.astype(jnp.float32)
        if self._weight_decay:
            g = g + self._weight_decay * p.astype(jnp.float32)
        asg = rho * state["avg_squared_grad"] + (1 - rho) * g * g
        upd = g * jnp.sqrt(state["avg_squared_update"] + eps) / \
            jnp.sqrt(asg + eps)
        asu = rho * state["avg_squared_update"] + (1 - rho) * upd * upd
        return (p.astype(jnp.float32) - lr * upd).astype(p.dtype), \
            {"avg_squared_grad": asg, "avg_squared_update": asu}
