"""paddle.sparse equivalent: COO/CSR tensors over jax.experimental.sparse.

ref: python/paddle/sparse/ (creation.py sparse_coo_tensor/sparse_csr_tensor,
unary/binary ops, nn.functional) + phi/core/sparse_coo_tensor.h. The BCOO
format is XLA's sparse representation; matmul/elementwise dispatch through
it, densifying where the TPU path prefers dense compute (small nnz ratio
decisions belong to the caller, as in the reference).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ..core.autograd import apply_op
from ..core.tensor import Tensor

# NOTE: __all__ is defined ONCE at the bottom of this module, after the
# full op surface exists.


class SparseCooTensor(Tensor):
    """Tensor whose _data is a BCOO array (ref: sparse_coo_tensor.h:49 —
    indices + values + dims). Dense Tensor methods that densify go through
    .to_dense()."""

    @property
    def nnz(self):
        return int(self._data.nse)

    def indices(self):
        return Tensor(jnp.swapaxes(self._data.indices, 0, 1))

    def values(self):
        # through the tape so grads flow back into the sparse graph
        return apply_op(lambda a: a.data, self, op_name="coo_values")

    def to_dense(self):
        return apply_op(lambda d: d.todense(), self, op_name="coo_to_dense")

    def is_sparse(self):
        return True

    def is_sparse_coo(self):
        return True


def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      place=None, stop_gradient=True):
    """ref: sparse/creation.py sparse_coo_tensor(indices [ndim, nnz],
    values [nnz])."""
    idx = np.asarray(indices._data if isinstance(indices, Tensor)
                     else indices)
    val = values._data if isinstance(values, Tensor) else jnp.asarray(
        np.asarray(values))
    if dtype is not None:
        val = val.astype(dtype)
    if shape is None:
        shape = tuple(int(m) + 1 for m in idx.max(axis=1))
    coo = jsparse.BCOO((val, jnp.asarray(idx.T)), shape=tuple(shape))
    return SparseCooTensor(coo, stop_gradient=stop_gradient)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      place=None, stop_gradient=True):
    """ref: sparse/creation.py sparse_csr_tensor — stored as BCOO
    internally (csr -> coo expansion), same API surface."""
    crows_np = np.asarray(crows._data if isinstance(crows, Tensor)
                          else crows)
    cols_np = np.asarray(cols._data if isinstance(cols, Tensor) else cols)
    rows = np.repeat(np.arange(len(crows_np) - 1),
                     np.diff(crows_np))
    idx = np.stack([rows, cols_np])
    return sparse_coo_tensor(idx, values, shape, dtype,
                             stop_gradient=stop_gradient)


def is_same_shape(x, y) -> bool:
    return tuple(x.shape) == tuple(y.shape)


def _coo(x):
    if isinstance(x, SparseCooTensor):
        return x
    raise TypeError(f"expected SparseCooTensor, got {type(x).__name__}")


def add(x, y):
    """ref: sparse/binary.py add."""
    def f(a, b):
        return (a.todense() if isinstance(a, jsparse.BCOO) else a) + \
               (b.todense() if isinstance(b, jsparse.BCOO) else b)
    out = apply_op(f, x, y, op_name="sparse_add")
    return out


def multiply(x, y):
    def f(a, b):
        return (a.todense() if isinstance(a, jsparse.BCOO) else a) * \
               (b.todense() if isinstance(b, jsparse.BCOO) else b)
    return apply_op(f, x, y, op_name="sparse_multiply")


def matmul(x, y):
    """Sparse @ dense (ref: sparse/matmul.py) — BCOO dot_general keeps the
    sparse operand sparse through XLA."""
    def f(a, b):
        if isinstance(a, jsparse.BCOO):
            return jsparse.bcoo_dot_general(
                a, b, dimension_numbers=(([a.ndim - 1], [0]), ([], [])))
        return a @ b
    return apply_op(f, x, y, op_name="sparse_matmul")


def masked_matmul(x, y, mask):
    """Dense @ dense with sparse output mask (ref: sparse/matmul.py
    masked_matmul)."""
    def f(a, b, m):
        dense = a @ b
        return jnp.where(m.todense() != 0, dense, 0.0)
    return apply_op(f, x, y, mask, op_name="masked_matmul")


# relu defined below via _unary_on_values (same pattern as sin/tanh/...)


# ---------------------------------------------------------------------------
# round-2 completion: the full reference surface (ref:
# python/paddle/sparse/__init__.py __all__ — unary ops on values, binary
# ops, matmul family, layout utilities) + the sparse.nn subpackage.
# ---------------------------------------------------------------------------

def _unary_on_values(name, np_safe_fn):
    """Sparse unary ops act on the stored values; the zero pattern is
    preserved for zero-preserving fns (the reference's contract — these
    ops are only registered for f(0)=0 functions)."""
    def op(x):
        def f(a):
            if isinstance(a, jsparse.BCOO):
                return jsparse.BCOO((np_safe_fn(a.data), a.indices),
                                    shape=a.shape,
                                    indices_sorted=a.indices_sorted,
                                    unique_indices=a.unique_indices)
            return np_safe_fn(a)
        out = apply_op(f, x, op_name=f"sparse_{name}")
        return _rewrap(out, x)
    op.__name__ = name
    return op


def _rewrap(out, like):
    if isinstance(like, SparseCooTensor) and isinstance(
            out._data, jsparse.BCOO):
        return SparseCooTensor(out._data, stop_gradient=out.stop_gradient,
                               node=out._node, out_index=out._out_index)
    return out


sin = _unary_on_values("sin", jnp.sin)
tan = _unary_on_values("tan", jnp.tan)
asin = _unary_on_values("asin", jnp.arcsin)
atan = _unary_on_values("atan", jnp.arctan)
sinh = _unary_on_values("sinh", jnp.sinh)
tanh = _unary_on_values("tanh", jnp.tanh)
asinh = _unary_on_values("asinh", jnp.arcsinh)
atanh = _unary_on_values("atanh", jnp.arctanh)
sqrt = _unary_on_values("sqrt", jnp.sqrt)
square = _unary_on_values("square", jnp.square)
log1p = _unary_on_values("log1p", jnp.log1p)
abs = _unary_on_values("abs", jnp.abs)  # noqa: A001 (reference name)
neg = _unary_on_values("neg", jnp.negative)
expm1 = _unary_on_values("expm1", jnp.expm1)
deg2rad = _unary_on_values("deg2rad", jnp.deg2rad)
rad2deg = _unary_on_values("rad2deg", jnp.rad2deg)
relu = _unary_on_values("relu", jax.nn.relu)


def pow(x, factor):  # noqa: A001 (reference name)
    return _rewrap(apply_op(
        lambda a: jsparse.BCOO((jnp.power(a.data, factor), a.indices),
                               shape=a.shape)
        if isinstance(a, jsparse.BCOO) else jnp.power(a, factor),
        x, op_name="sparse_pow"), x)


def cast(x, index_dtype=None, value_dtype=None):
    def f(a):
        if isinstance(a, jsparse.BCOO):
            idx = a.indices.astype(index_dtype) if index_dtype else \
                a.indices
            val = a.data.astype(value_dtype) if value_dtype else a.data
            return jsparse.BCOO((val, idx), shape=a.shape)
        return a.astype(value_dtype) if value_dtype else a
    return _rewrap(apply_op(f, x, op_name="sparse_cast"), x)


def isnan(x):
    return _rewrap(apply_op(
        lambda a: jsparse.BCOO((jnp.isnan(a.data), a.indices),
                               shape=a.shape)
        if isinstance(a, jsparse.BCOO) else jnp.isnan(a),
        x, op_name="sparse_isnan"), x)


def subtract(x, y):
    def f(a, b):
        da = a.todense() if isinstance(a, jsparse.BCOO) else a
        db = b.todense() if isinstance(b, jsparse.BCOO) else b
        return da - db
    return apply_op(f, x, y, op_name="sparse_subtract")


def divide(x, y):
    def f(a, b):
        da = a.todense() if isinstance(a, jsparse.BCOO) else a
        db = b.todense() if isinstance(b, jsparse.BCOO) else b
        return da / db
    return apply_op(f, x, y, op_name="sparse_divide")


def mv(x, vec):
    """Sparse matrix @ dense vector (ref: sparse/matmul.py mv)."""
    return matmul(x, vec)


def addmm(input, x, y, beta=1.0, alpha=1.0):
    """ref: sparse/matmul.py addmm: beta*input + alpha*(x@y)."""
    def f(inp, a, b):
        di = inp.todense() if isinstance(inp, jsparse.BCOO) else inp
        if isinstance(a, jsparse.BCOO):
            prod = jsparse.bcoo_dot_general(
                a, b, dimension_numbers=(([a.ndim - 1], [0]), ([], [])))
        else:
            prod = a @ b
        return beta * di + alpha * prod
    return apply_op(f, input, x, y, op_name="sparse_addmm")


def mask_as(x, mask):
    """Keep x's entries at mask's sparsity pattern
    (ref: sparse/multiary.py mask_as)."""
    def f(a, m):
        da = a.todense() if isinstance(a, jsparse.BCOO) else a
        vals = da[tuple(m.indices[:, i] for i in range(m.indices.shape[1]))]
        return jsparse.BCOO((vals, m.indices), shape=m.shape)
    out = apply_op(f, x, mask, op_name="sparse_mask_as")
    return SparseCooTensor(out._data, stop_gradient=out.stop_gradient,
                           node=out._node, out_index=out._out_index)


def coalesce(x):
    """Merge duplicate indices (ref: sparse/unary.py coalesce)."""
    def f(a):
        return jsparse.bcoo_sum_duplicates(a)
    out = apply_op(f, x, op_name="sparse_coalesce")
    return _rewrap(out, x)


def transpose(x, perm):
    def f(a):
        if isinstance(a, jsparse.BCOO):
            return jsparse.bcoo_transpose(a, permutation=tuple(perm))
        return jnp.transpose(a, perm)
    return _rewrap(apply_op(f, x, op_name="sparse_transpose"), x)


def reshape(x, shape):
    def f(a):
        if isinstance(a, jsparse.BCOO):
            return jsparse.bcoo_reshape(a, new_sizes=tuple(shape))
        return jnp.reshape(a, shape)
    return _rewrap(apply_op(f, x, op_name="sparse_reshape"), x)


def sum(x, axis=None, dtype=None, keepdim=False):  # noqa: A001
    def f(a):
        da = a.todense() if isinstance(a, jsparse.BCOO) else a
        out = jnp.sum(da, axis=axis, keepdims=keepdim)
        return out.astype(dtype) if dtype else out
    return apply_op(f, x, op_name="sparse_sum")


_py_slice = slice  # captured before the op below shadows the builtin


def slice(x, axes, starts, ends):  # noqa: A001
    def f(a):
        da = a.todense() if isinstance(a, jsparse.BCOO) else a
        sl = [_py_slice(None)] * da.ndim
        for ax, st, en in zip(axes, starts, ends):
            sl[ax] = _py_slice(st, en)
        return da[tuple(sl)]
    return apply_op(f, x, op_name="sparse_slice")


def pca_lowrank(x, q=None, center=True, niter=2):
    """ref: sparse/unary.py pca_lowrank — dense SVD on the densified
    matrix (the reference likewise densifies for the factorization)."""
    def f(a):
        da = a.todense() if isinstance(a, jsparse.BCOO) else a
        m, n = da.shape
        k = q if q is not None else min(6, m, n)
        if center:
            da = da - da.mean(axis=0, keepdims=True)
        u, s, vt = jnp.linalg.svd(da, full_matrices=False)
        return u[:, :k], s[:k], vt[:k].T
    return apply_op(f, x, op_name="sparse_pca_lowrank")


from . import nn  # noqa: E402,F401

__all__ = [
    "sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor",
    "sin", "tan", "asin", "atan", "sinh", "tanh", "asinh", "atanh",
    "sqrt", "square", "log1p", "abs", "pow", "pca_lowrank", "cast",
    "neg", "deg2rad", "rad2deg", "expm1", "mv", "matmul", "mask_as",
    "masked_matmul", "addmm", "add", "subtract", "transpose", "sum",
    "multiply", "divide", "coalesce", "is_same_shape", "reshape",
    "isnan", "slice", "relu", "nn",
]
