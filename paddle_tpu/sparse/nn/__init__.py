"""paddle.sparse.nn: layers over sparse COO activations.

ref: python/paddle/sparse/nn/__init__.py (ReLU/ReLU6/LeakyReLU/Softmax/
BatchNorm/SyncBatchNorm/Conv2D/Conv3D/SubmConv2D/SubmConv3D/MaxPool3D,
kernels under paddle/phi/kernels/sparse/). TPU-native stance: activations
keep the COO (indices, values) pair; pointwise ops act on values, conv/
pool densify through XLA's conv (which the MXU wants anyway) and
re-sparsify — SubmConv masks the output to the input's active sites, the
submanifold contract. A gather/scatter Pallas kernel is the future perf
path for very low densities; these implementations are the numeric
contract.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ...core.autograd import apply_op
from ...nn import initializer as I
from ...nn.layer import Layer
from .. import SparseCooTensor, sparse_coo_tensor
from . import functional  # noqa: F401

__all__ = ["ReLU", "ReLU6", "LeakyReLU", "Softmax", "BatchNorm",
           "SyncBatchNorm", "Conv2D", "Conv3D", "SubmConv2D",
           "SubmConv3D", "MaxPool3D"]


def _values_layer(name, fn):
    class _L(Layer):
        def forward(self, x):
            return functional._apply_values(x, fn, name)
    _L.__name__ = name
    _L.__qualname__ = name
    _L.__doc__ = f"ref: sparse/nn/layer/activation.py {name}."
    return _L


ReLU = _values_layer("ReLU", jax.nn.relu)
ReLU6 = _values_layer("ReLU6", lambda v: jnp.clip(v, 0, 6))


class LeakyReLU(Layer):
    """ref: sparse/nn/layer/activation.py LeakyReLU(negative_slope)."""

    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self.negative_slope = float(negative_slope)

    def forward(self, x):
        return functional.leaky_relu(x, self.negative_slope)


class Softmax(Layer):
    """ref: sparse/nn/layer/activation.py Softmax — softmax over the last
    dense dim, computed per row across the ACTIVE entries only."""

    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return functional.softmax(x, self.axis)


class BatchNorm(Layer):
    """ref: sparse/nn/layer/norm.py BatchNorm — normalizes the values
    table [nnz, C] over active sites (channels-last sparse layout)."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NDHWC",
                 use_global_stats=None, name=None):
        super().__init__()
        self.num_features = num_features
        self.momentum = momentum
        self.epsilon = epsilon
        self.weight = self.create_parameter(
            [num_features], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter(
            [num_features], attr=bias_attr, is_bias=True,
            default_initializer=I.Constant(0.0))
        self.register_buffer("_mean", self._zeros(num_features))
        self.register_buffer("_variance", self._ones(num_features))

    @staticmethod
    def _zeros(n):
        from ...core.tensor import Tensor
        return Tensor(jnp.zeros((n,), jnp.float32))

    @staticmethod
    def _ones(n):
        from ...core.tensor import Tensor
        return Tensor(jnp.ones((n,), jnp.float32))

    def forward(self, x):
        training = self.training
        vals = x.values()

        def f(v, w, b, m, var):
            if training:
                mean = v.mean(axis=0)
                vvar = v.var(axis=0)
            else:
                mean, vvar = m, var
            out = (v - mean) / jnp.sqrt(vvar + self.epsilon) * w + b
            return out, mean, vvar

        out, mean, vvar = apply_op(f, vals, self.weight, self.bias,
                                   self._mean, self._variance,
                                   op_name="sparse_batch_norm")
        if training:
            mom = self.momentum
            self._mean._data = mom * self._mean._data + \
                (1 - mom) * mean._data
            self._variance._data = mom * self._variance._data + \
                (1 - mom) * vvar._data
        coo = x._data
        new = jsparse.BCOO((out._data.astype(coo.data.dtype), coo.indices),
                           shape=coo.shape)
        res = SparseCooTensor(new, stop_gradient=out.stop_gradient,
                              node=out._node, out_index=out._out_index)
        return res


class SyncBatchNorm(BatchNorm):
    """ref: sparse/nn/layer/norm.py SyncBatchNorm — on a single controller
    the compiled mesh program already sees the global batch; cross-process
    eager sync rides the collective API when installed."""


class _SparseConvNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, nd,
                 stride=1, padding=0, dilation=1, groups=1, subm=False,
                 weight_attr=None, bias_attr=None, data_format=None,
                 name=None):
        super().__init__()
        if groups != 1:
            raise NotImplementedError("sparse conv groups != 1")
        self.nd = nd
        self.subm = subm
        ks = kernel_size if isinstance(kernel_size, (tuple, list)) \
            else (kernel_size,) * nd
        self.kernel_size = tuple(int(k) for k in ks)
        self.stride = stride if isinstance(stride, (tuple, list)) \
            else (stride,) * nd
        self.padding = padding if isinstance(padding, (tuple, list)) \
            else (padding,) * nd
        self.dilation = dilation if isinstance(dilation, (tuple, list)) \
            else (dilation,) * nd
        # reference layout: kernel [*ks, in, out] (sparse convs are
        # channels-last, ref sparse/nn/layer/conv.py)
        self.weight = self.create_parameter(
            list(self.kernel_size) + [in_channels, out_channels],
            attr=weight_attr, default_initializer=I.XavierUniform())
        self.bias = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                [out_channels], attr=bias_attr, is_bias=True,
                default_initializer=I.Constant(0.0))

    def forward(self, x):
        return functional._sparse_conv(
            x, self.weight, self.bias, self.nd, self.stride, self.padding,
            self.dilation, self.subm)


class Conv2D(_SparseConvNd):
    """ref: sparse/nn/layer/conv.py Conv2D (NHWC sparse input)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NHWC"):
        super().__init__(in_channels, out_channels, kernel_size, 2,
                         stride, padding, dilation, groups, False,
                         weight_attr, bias_attr, data_format)


class Conv3D(_SparseConvNd):
    """ref: sparse/nn/layer/conv.py Conv3D (NDHWC sparse input)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NDHWC"):
        super().__init__(in_channels, out_channels, kernel_size, 3,
                         stride, padding, dilation, groups, False,
                         weight_attr, bias_attr, data_format)


class SubmConv2D(_SparseConvNd):
    """Submanifold conv: output active set == input active set
    (ref: sparse/nn/layer/conv.py SubmConv2D)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 key=None, weight_attr=None, bias_attr=None,
                 data_format="NHWC"):
        super().__init__(in_channels, out_channels, kernel_size, 2,
                         stride, padding, dilation, groups, True,
                         weight_attr, bias_attr, data_format)


class SubmConv3D(_SparseConvNd):
    """ref: sparse/nn/layer/conv.py SubmConv3D."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 key=None, weight_attr=None, bias_attr=None,
                 data_format="NDHWC"):
        super().__init__(in_channels, out_channels, kernel_size, 3,
                         stride, padding, dilation, groups, True,
                         weight_attr, bias_attr, data_format)


class MaxPool3D(Layer):
    """ref: sparse/nn/layer/pooling.py MaxPool3D (NDHWC sparse input)."""

    def __init__(self, kernel_size, stride=None, padding=0,
                 ceil_mode=False, return_mask=False, data_format="NDHWC",
                 name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride if stride is not None else kernel_size
        self.padding = padding

    def forward(self, x):
        return functional.max_pool3d(x, self.kernel_size, self.stride,
                                     self.padding)
