"""paddle.sparse.nn.functional.

ref: python/paddle/sparse/nn/functional/ (activation.py, conv.py,
pooling.py, transformer.py attention). Conv/pool densify through XLA's
conv/reduce_window (MXU path) and re-sparsify; attention is the CSR-
masked softmax(QK^T)V contract of the reference's sparse attention
kernel (phi/kernels/sparse/gpu/fused_attention_kernel.cu).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ...core.autograd import apply_op
from ...core.tensor import Tensor

__all__ = ["relu", "relu6", "leaky_relu", "softmax", "conv2d", "conv3d",
           "subm_conv2d", "subm_conv3d", "max_pool3d", "attention"]


def _rewrap(out, like):
    from .. import SparseCooTensor
    if isinstance(out._data, jsparse.BCOO):
        return SparseCooTensor(out._data, stop_gradient=out.stop_gradient,
                               node=out._node, out_index=out._out_index)
    return out


def _apply_values(x, fn, name):
    def f(a):
        if isinstance(a, jsparse.BCOO):
            return jsparse.BCOO((fn(a.data), a.indices), shape=a.shape)
        return fn(a)
    return _rewrap(apply_op(f, x, op_name=f"sparse_{name}"), x)


def relu(x, name=None):
    return _apply_values(x, jax.nn.relu, "relu")


def relu6(x, name=None):
    return _apply_values(x, lambda v: jnp.clip(v, 0, 6), "relu6")


def leaky_relu(x, negative_slope=0.01, name=None):
    return _apply_values(
        x, lambda v: jnp.where(v >= 0, v, negative_slope * v),
        "leaky_relu")


def softmax(x, axis=-1, name=None):
    """Row softmax over ACTIVE entries only (ref: sparse softmax kernel:
    zeros do not participate)."""
    def f(a):
        if not isinstance(a, jsparse.BCOO):
            return jax.nn.softmax(a, axis=axis)
        if axis not in (-1, a.ndim - 1):
            raise ValueError("sparse softmax supports the last axis")
        # segment-softmax keyed by the row (= all index dims but last)
        idx = a.indices
        strides = np.cumprod([1] + list(a.shape[-2::-1]))[::-1]
        row = jnp.zeros((idx.shape[0],), jnp.int32)
        for d in range(idx.shape[1] - 1):
            row = row + idx[:, d].astype(jnp.int32) * int(strides[d + 1])
        nrows = int(np.prod(a.shape[:-1]))
        mx = jax.ops.segment_max(a.data, row, num_segments=nrows)
        e = jnp.exp(a.data - mx[row])
        denom = jax.ops.segment_sum(e, row, num_segments=nrows)
        return jsparse.BCOO((e / denom[row], a.indices), shape=a.shape)
    return _rewrap(apply_op(f, x, op_name="sparse_softmax"), x)


def _sparse_conv(x, weight, bias, nd, stride, padding, dilation, subm):
    """Densify -> lax conv (channels-last) -> re-sparsify; submanifold
    masks outputs to the input active set
    (ref: phi/kernels/sparse/conv_kernel)."""
    def f(a, w, *rest):
        b = rest[0] if rest else None
        dense = a.todense() if isinstance(a, jsparse.BCOO) else a
        n = dense.shape[0]
        cin = dense.shape[-1]
        spatial = dense.shape[1:-1]
        dn = jax.lax.conv_dimension_numbers(
            dense.shape, w.shape,
            ("NHWC", "HWIO", "NHWC") if nd == 2 else
            ("NDHWC", "DHWIO", "NDHWC"))
        pad = [(int(p), int(p)) for p in
               (padding if isinstance(padding, (tuple, list))
                else (padding,) * nd)]
        out = jax.lax.conv_general_dilated(
            dense.astype(jnp.float32), w.astype(jnp.float32),
            window_strides=tuple(int(s) for s in (
                stride if isinstance(stride, (tuple, list))
                else (stride,) * nd)),
            padding=pad,
            rhs_dilation=tuple(int(d) for d in (
                dilation if isinstance(dilation, (tuple, list))
                else (dilation,) * nd)),
            dimension_numbers=dn)
        if b is not None:
            out = out + b
        if subm and isinstance(a, jsparse.BCOO):
            # submanifold: only the input's active sites stay active
            active = jnp.any(dense != 0, axis=-1, keepdims=True)
            out = jnp.where(active, out, 0.0)
        return out.astype(dense.dtype)

    args = [x, weight] + ([bias] if bias is not None else [])
    dense_out = apply_op(f, *args, op_name="sparse_conv")
    return _densify_to_coo(dense_out)


def _densify_to_coo(dense_t):
    from .. import SparseCooTensor
    out = apply_op(
        lambda d: jsparse.bcoo_fromdense(d, n_batch=0, n_dense=1),
        dense_t, op_name="dense_to_coo")
    return SparseCooTensor(out._data, stop_gradient=out.stop_gradient,
                           node=out._node, out_index=out._out_index)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1,
           groups=1, data_format="NHWC", name=None):
    """ref: sparse/nn/functional/conv.py conv2d."""
    return _sparse_conv(x, weight, bias, 2, stride, padding, dilation,
                        False)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
           groups=1, data_format="NDHWC", name=None):
    """ref: sparse/nn/functional/conv.py conv3d."""
    return _sparse_conv(x, weight, bias, 3, stride, padding, dilation,
                        False)


def subm_conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NHWC", key=None, name=None):
    """ref: sparse/nn/functional/conv.py subm_conv2d."""
    return _sparse_conv(x, weight, bias, 2, stride, padding, dilation,
                        True)


def subm_conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NDHWC", key=None, name=None):
    """ref: sparse/nn/functional/conv.py subm_conv3d."""
    return _sparse_conv(x, weight, bias, 3, stride, padding, dilation,
                        True)


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               data_format="NDHWC", name=None):
    """ref: sparse/nn/functional/pooling.py max_pool3d (NDHWC)."""
    ks = kernel_size if isinstance(kernel_size, (tuple, list)) \
        else (kernel_size,) * 3
    st = stride if stride is not None else ks
    st = st if isinstance(st, (tuple, list)) else (st,) * 3
    pd = padding if isinstance(padding, (tuple, list)) else (padding,) * 3

    def f(a):
        window = (1,) + tuple(int(k) for k in ks) + (1,)
        strides = (1,) + tuple(int(s) for s in st) + (1,)
        pads = [(0, 0)] + [(int(p), int(p)) for p in pd] + [(0, 0)]
        if isinstance(a, jsparse.BCOO):
            # max over ACTIVE sites only (the reference sparse maxpool
            # contract): inactive positions become -inf so an
            # all-negative active window still returns its active max
            dense = a.todense()
            ones = jsparse.BCOO(
                (jnp.ones_like(a.data), a.indices), shape=a.shape)
            active = ones.todense() > 0
            dense = jnp.where(active, dense, -jnp.inf)
        else:
            dense = a
        out = jax.lax.reduce_window(
            dense, -jnp.inf, jax.lax.max, window, strides, pads)
        return jnp.where(jnp.isfinite(out), out, 0.0)  # empty windows

    return _densify_to_coo(apply_op(f, x, op_name="sparse_max_pool3d"))


def attention(query, key, value, sparse_mask, key_padding_mask=None,
              attn_mask=None, name=None):
    """Sparse-masked attention (ref: sparse/nn/functional/transformer.py
    attention — softmax over the CSR pattern of sparse_mask, then @ V).
    q/k/v: [B, H, L, D]; sparse_mask: SparseCsrTensor/CooTensor with
    shape [B*H, L, L]."""
    def f(q, k, v, m, *rest):
        b, h, l, d = q.shape
        mask_dense = (m.todense() if isinstance(m, jsparse.BCOO)
                      else m).reshape(b, h, l, l)
        logits = jnp.einsum("bhld,bhmd->bhlm", q, k) / jnp.sqrt(
            jnp.asarray(d, jnp.float32))
        neg = jnp.asarray(-1e30, logits.dtype)
        logits = jnp.where(mask_dense != 0, logits, neg)
        i = 0
        if key_padding_mask is not None:
            kpm = rest[i]; i += 1
            logits = jnp.where(kpm[:, None, None, :] != 0, logits, neg)
        if attn_mask is not None:
            am = rest[i]; i += 1
            logits = jnp.where(am != 0, logits, neg)
        probs = jax.nn.softmax(logits, axis=-1)
        probs = jnp.where(jnp.any(mask_dense != 0, -1, keepdims=True),
                          probs, 0.0)
        return jnp.einsum("bhlm,bhmd->bhld", probs, v)

    extra = [t for t in (key_padding_mask, attn_mask) if t is not None]
    return apply_op(f, query, key, value, sparse_mask, *extra,
                    op_name="sparse_attention")
