from .model import Model, summary, flops  # noqa: F401
from . import callbacks  # noqa: F401
