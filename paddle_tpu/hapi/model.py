"""hapi high-level Model API.

ref: python/paddle/hapi/model.py (Model.prepare, fit :1472, evaluate,
predict, save/load, train_batch/eval_batch) plus model_summary.py
(summary) and dynamic_flops.py (flops). TPU-native: fit's inner step is
the same eager-over-compiled-ops path train_batch uses, so the whole
surface stays jit-friendly.
"""
from __future__ import annotations

import os
from typing import List

import numpy as np

from ..core.tensor import Tensor
from .callbacks import config_callbacks

__all__ = ["Model", "summary", "flops"]


def _to_tensor(x):
    if isinstance(x, Tensor):
        return x
    import jax.numpy as jnp
    return Tensor(jnp.asarray(np.asarray(x)))


def _mean_loss(losses):
    """Mean of a list of lazy 0-d loss Tensors (or floats) with ONE
    device->host transfer: the scalars stack on device and fetch as a
    single array — not one round-trip per step at the epoch boundary."""
    import jax.numpy as jnp
    vals = [v._data.astype(jnp.float32) if isinstance(v, Tensor)
            else jnp.float32(v) for v in losses]
    return float(np.asarray(jnp.stack(vals)).mean())


def _as_batches(data, batch_size, shuffle, drop_last=False):
    """Accepts DataLoader / Dataset / (x, y) arrays; yields (ins, labels)
    pairs."""
    from ..io import DataLoader, Dataset

    if isinstance(data, DataLoader):
        return data
    if isinstance(data, Dataset):
        return DataLoader(data, batch_size=batch_size or 1,
                          shuffle=shuffle, drop_last=drop_last)
    if isinstance(data, (tuple, list)) and len(data) == 2:
        x, y = data
        n = len(x)
        bs = batch_size or n

        def gen():
            order = (np.random.permutation(n) if shuffle
                     else np.arange(n))
            stop = (n - n % bs) if drop_last else n
            for i in range(0, stop, bs):
                sel = order[i:i + bs]
                yield (x[sel], y[sel])
        return gen()
    raise TypeError(f"unsupported data type {type(data)!r} — pass a "
                    f"DataLoader, Dataset, or (inputs, labels) pair")


class Model:
    """ref: hapi/model.py Model — high-level train/eval/predict over a
    Layer."""

    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics: List = []
        self._captured = None  # SOT whole-step capture engine (lazy)
        self._amp = None       # auto_cast kwargs (amp_configs)
        self._scaler = None    # GradScaler driving the AMP step
        self.stop_training = False

    # -- configuration -------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None, warm_bundle=None):
        """ref: hapi/model.py prepare. ``amp_configs`` (a level string
        or a dict: level/dtype/custom_white_list/custom_black_list +
        GradScaler knobs init_loss_scaling/incr_ratio/decr_ratio/
        incr_every_n_steps/decr_every_n_nan_or_inf/
        use_dynamic_loss_scaling, or an explicit ``scaler``) turns
        train/eval batches into AMP steps: forward+loss under
        ``amp.auto_cast``, backward+update through the GradScaler when
        one is configured. Under whole-step capture the ENTIRE AMP
        iteration (scale, backward, unscale, finite check, masked
        update, scale bookkeeping) runs as ONE donated executable.

        ``warm_bundle`` (a manifest path or loaded bundle dict;
        default ``FLAGS_warmup_bundle``) pre-warms the whole-step
        capture engine NOW — the recorded train/eval programs are
        rebuilt AOT against the persistent executable cache
        (``FLAGS_executable_cache_dir``), so the FIRST ``train_batch``
        runs captured with zero fresh XLA compiles instead of paying
        the first-sighting eager step + compile."""
        self._optimizer = optimizer
        self._loss = loss
        ms = metrics or []
        self._metrics = list(ms) if isinstance(ms, (list, tuple)) else [ms]
        self._captured = None  # new loss/optimizer: stale programs out
        self._amp, self._scaler = self._parse_amp(amp_configs)
        from ..jit import warmup as _warmup
        from ..core.flags import flag_value
        bundle = warm_bundle if warm_bundle is not None \
            else (flag_value("warmup_bundle") or None)
        if bundle:
            _warmup.prewarm(bundle, captured=self._capture_engine())
        return self

    @staticmethod
    def _parse_amp(amp_configs):
        if not amp_configs:
            return None, None
        if isinstance(amp_configs, str):
            amp_configs = {"level": amp_configs}
        cfg = dict(amp_configs)
        level = str(cfg.pop("level", "O1")).upper()
        if level == "O0":
            return None, None
        scaler = cfg.pop("scaler", None)
        scaler_keys = {
            "init_loss_scaling", "incr_ratio", "decr_ratio",
            "incr_every_n_steps", "decr_every_n_nan_or_inf",
            "use_dynamic_loss_scaling"}
        scaler_kw = {k: cfg.pop(k) for k in list(cfg)
                     if k in scaler_keys}
        amp = {"level": level,
               "dtype": cfg.pop("dtype", "bfloat16"),
               "custom_white_list": cfg.pop("custom_white_list", None),
               "custom_black_list": cfg.pop("custom_black_list", None)}
        cfg.pop("use_fp16_guard", None)  # accepted for reference parity
        if cfg:
            raise ValueError(f"unknown amp_configs keys: {sorted(cfg)}")
        if scaler is not None and scaler_kw:
            raise ValueError(
                f"amp_configs passes both an explicit scaler and "
                f"scaler knobs {sorted(scaler_kw)} — configure the "
                f"scaler you pass, or drop it and pass the knobs")
        if scaler is None and (scaler_kw
                               or str(amp["dtype"]) == "float16"):
            # fp16 needs loss scaling; bf16 gets a scaler only when
            # scaler knobs ask for one (same exponent range as fp32)
            from ..amp import GradScaler
            scaler = GradScaler(**scaler_kw)
        return amp, scaler

    def _amp_ctx(self):
        from ..amp.auto_cast import auto_cast
        if self._amp is None:
            import contextlib
            return contextlib.nullcontext()
        return auto_cast(True, **{k: v for k, v in self._amp.items()})

    def _capture_engine(self):
        """The SOT whole-step engine behind train_batch/eval_batch: one
        cached, donated executable per signature. Falls back to the
        eager path (returns None from step/forward) on the
        FLAGS_sot_capture kill switch or any gate reason."""
        if self._captured is None:
            from ..jit.sot import CapturedStep
            self._captured = CapturedStep(
                self.network, self._loss, self._optimizer,
                mean_reduce=True, name="hapi.step",
                build_kind="captured_step")
        return self._captured

    # -- single-batch ops ----------------------------------------------------
    def train_batch(self, inputs, labels=None, update=True):
        """ref: model.py train_batch — one fwd/bwd(/step) on a batch.

        Returns ``[loss]`` where ``loss`` is a LAZY 0-d device Tensor:
        the hot path never fetches it (the PTC003 hoist the capture
        plan prescribed) — ``fit`` and the logging callbacks convert at
        the log boundary via ``float(loss)``. In steady state the whole
        fwd+bwd+optimizer step runs as ONE captured, buffer-donated
        executable (``FLAGS_sot_capture=0`` restores per-chain eager
        fusion)."""
        self.network.train()
        ins = inputs if isinstance(inputs, (tuple, list)) else [inputs]
        ins = [_to_tensor(i) for i in ins]
        lbl = labels if isinstance(labels, (tuple, list)) else [labels]
        lbl = [_to_tensor(v) for v in lbl if v is not None]
        scaler = self._scaler if self._amp is not None else None
        if update and self._optimizer is not None:
            # the capture engine traces the forward under the ambient
            # autocast regime; with a scaler the whole AMP iteration
            # (scale/backward/unscale/check/masked update/scale
            # bookkeeping) is the one captured executable
            with self._amp_ctx():
                loss = self._capture_engine().step(ins, lbl,
                                                   scaler=scaler)
            if loss is not None:
                return [loss]
        with self._amp_ctx():
            out = self.network(*ins)
            loss = out
            if self._loss is not None:
                loss = self._loss(out, *lbl)
            if loss._data.ndim > 0:
                loss = loss.mean()
        if scaler is not None and scaler.is_enable():
            scaler.scale(loss).backward()
            if update and self._optimizer is not None:
                scaler.step(self._optimizer)
                scaler.update()
                self._optimizer.clear_grad()
            return [loss]
        loss.backward()
        if update and self._optimizer is not None:
            self._optimizer.step()
            self._optimizer.clear_grad()
        return [loss]

    def eval_batch(self, inputs, labels=None):
        """One eval forward; ``outs['loss']`` is a lazy device Tensor
        (fetch at the log boundary), the forward+loss runs captured in
        steady state."""
        self.network.eval()
        ins = inputs if isinstance(inputs, (tuple, list)) else [inputs]
        ins = [_to_tensor(i) for i in ins]
        lbl = labels if isinstance(labels, (tuple, list)) else [labels]
        lbl = [_to_tensor(v) for v in lbl if v is not None]
        out = loss = None
        with self._amp_ctx():
            res = self._capture_engine().forward(ins, lbl)
            if res is not None:
                out, loss = res
            else:
                out = self.network(*ins)
                if self._loss is not None and labels is not None:
                    loss = self._loss(out, *lbl)
                    if loss._data.ndim > 0:
                        loss = loss.mean()
        outs = {}
        if loss is not None:
            outs["loss"] = loss
        if labels is not None:
            for m in self._metrics:
                lbl0 = labels[0] if isinstance(labels, (tuple, list)) \
                    else labels
                corr = m.compute(out, _to_tensor(lbl0))
                m.update(corr)
        return outs

    def predict_batch(self, inputs):
        self.network.eval()
        ins = inputs if isinstance(inputs, (tuple, list)) else [inputs]
        ins = [_to_tensor(i) for i in ins]
        out = self.network(*ins)
        return out.numpy() if isinstance(out, Tensor) else out

    # -- loops ---------------------------------------------------------------
    def fit(self, train_data=None, eval_data=None, batch_size=1,
            epochs=1, eval_freq=1, log_freq=10, save_dir=None,
            save_freq=1, verbose=2, drop_last=False, shuffle=True,
            num_workers=0, callbacks=None):
        """ref: model.py fit :1472."""
        cbks, history = config_callbacks(
            callbacks, model=self, epochs=epochs, verbose=verbose,
            save_freq=save_freq, save_dir=save_dir, log_freq=log_freq,
            metrics=[m.name() for m in self._metrics])
        self.stop_training = False
        logs = {}
        cbks.on_train_begin()
        for epoch in range(epochs):
            cbks.on_epoch_begin(epoch)
            losses = []
            for step, (ins, lbl) in enumerate(
                    _as_batches(train_data, batch_size, shuffle,
                                drop_last)):
                cbks.on_train_batch_begin(step)
                loss = self.train_batch(ins, lbl)
                losses.append(loss[0])  # lazy device scalars
                cbks.on_train_batch_end(step, {"loss": loss[0]})
            # THE log boundary: one batched fetch per epoch, not one
            # per step — the captured hot path stays sync-free
            logs = {"loss": _mean_loss(losses) if losses else None}
            if eval_data is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self.evaluate(eval_data,
                                          batch_size=batch_size,
                                          verbose=0, _callbacks=cbks)
                logs.update({f"eval_{k}": v for k, v in eval_logs.items()})
            cbks.on_epoch_end(epoch, logs)
            if self.stop_training:
                break
        cbks.on_train_end(logs)
        return history.history

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, _callbacks=None):
        cbks = _callbacks
        if cbks is None:
            cbks, _ = config_callbacks(callbacks, model=self,
                                       verbose=verbose)
        for m in self._metrics:
            m.reset()
        cbks.on_eval_begin()
        losses = []
        for step, (ins, lbl) in enumerate(
                _as_batches(eval_data, batch_size, False)):
            cbks.on_eval_batch_begin(step)
            outs = self.eval_batch(ins, lbl)
            if "loss" in outs:
                losses.append(outs["loss"])  # lazy device scalars
            cbks.on_eval_batch_end(step, outs)
        logs = {}
        if losses:  # the eval log boundary fetches, not the hot loop
            logs["loss"] = _mean_loss(losses)
        for m in self._metrics:
            nm = m.name()
            logs[nm[0] if isinstance(nm, (list, tuple)) else nm] = \
                m.accumulate()
        cbks.on_eval_end(logs)
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, verbose=1, callbacks=None):
        outs = []
        for batch in _as_batches(test_data, batch_size, False):
            ins = batch[0] if isinstance(batch, (tuple, list)) and \
                len(batch) == 2 else batch
            outs.append(self.predict_batch(ins))
        if stack_outputs and outs:
            return [np.concatenate(outs, axis=0)]
        return [outs]

    # -- persistence ---------------------------------------------------------
    def save(self, path, training=True):
        """ref: model.py save — parameters (+ optimizer state when
        training=True) via the framework pickle format."""
        from ..framework.io import save as _save
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        _save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            _save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework.io import load as _load
        sd = _load(path + ".pdparams")
        self.network.set_state_dict(sd)
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(_load(path + ".pdopt"))

    # -- introspection -------------------------------------------------------
    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        return summary(self.network, input_size, dtypes=dtype)


# --------------------------- summary / flops --------------------------------

def summary(net, input_size=None, dtypes=None, input=None):
    """ref: hapi/model_summary.py summary — per-layer table of output
    shapes and own-parameter counts; returns
    {'total_params', 'trainable_params'}."""
    import jax.numpy as jnp

    rows = []
    hooks = []

    def _own_params(layer):
        return sum(int(np.prod(p.shape))
                   for p in layer._parameters.values() if p is not None)

    def make_hook(name, layer):
        def hook(lyr, inputs, outputs):
            out = outputs[0] if isinstance(outputs, (tuple, list)) \
                else outputs
            rows.append((name, layer.__class__.__name__,
                         list(getattr(out, "shape", [])),
                         _own_params(layer)))
        return hook

    for name, sub in net.named_sublayers():
        hooks.append(sub.register_forward_post_hook(make_hook(name, sub)))

    was_training = net.training
    net.eval()  # the probe forward must not touch BN stats / dropout
    try:
        if input is not None:
            net(input)
        else:
            if input_size is None:
                raise ValueError("summary needs input_size or input")
            sizes = (input_size
                     if isinstance(input_size[0], (list, tuple))
                     else [input_size])
            dts = dtypes if isinstance(dtypes, (list, tuple)) else \
                [dtypes or "float32"] * len(sizes)
            xs = [Tensor(jnp.zeros(
                [d if isinstance(d, int) and d > 0 else 1 for d in s], dt))
                for s, dt in zip(sizes, dts)]
            net(*xs)
    finally:
        if was_training:
            net.train()
        for h in hooks:
            h.remove()

    total = sum(int(np.prod(p.shape)) for p in net.parameters())
    trainable = sum(int(np.prod(p.shape)) for p in net.parameters()
                    if not p.stop_gradient)
    lines = [f"{'Layer':<36}{'Type':<24}{'Output Shape':<22}"
             f"{'Params':>10}", "-" * 92]
    for nm, ty, shape, np_ in rows:
        lines.append(f"{nm:<36}{ty:<24}{str(shape):<22}{np_:>10}")
    lines += ["-" * 92, f"Total params: {total}",
              f"Trainable params: {trainable}"]
    print("\n".join(lines))
    return {"total_params": total, "trainable_params": trainable}


def flops(net, input_size, custom_ops=None, print_detail=False):
    """ref: hapi/dynamic_flops.py flops — multiply-add count for common
    layer types via forward hooks."""
    import jax.numpy as jnp

    from .. import nn

    total = {"n": 0}
    hooks = []

    def count_for(layer, inputs, outputs):
        out = outputs[0] if isinstance(outputs, (tuple, list)) else outputs
        oshape = list(getattr(out, "shape", []))
        n = 0
        if custom_ops and type(layer) in custom_ops:
            n = custom_ops[type(layer)](layer, inputs, outputs)
        elif isinstance(layer, nn.Linear):
            n = int(np.prod(oshape)) * int(layer.weight.shape[0])
        elif layer.__class__.__name__.startswith("Conv"):
            w = layer.weight
            n = int(np.prod(oshape)) * int(np.prod(w.shape[1:]))
        elif "Norm" in layer.__class__.__name__:
            n = int(np.prod(oshape)) * 2
        elif "Pool" in layer.__class__.__name__:
            n = int(np.prod(oshape))
        total["n"] += n

    for _, sub in net.named_sublayers(include_self=True):
        hooks.append(sub.register_forward_post_hook(count_for))

    sizes = input_size if isinstance(input_size[0], (list, tuple)) \
        else [input_size]
    xs = [Tensor(jnp.zeros([d if isinstance(d, int) and d > 0 else 1
                            for d in s], "float32")) for s in sizes]
    was_training = net.training
    net.eval()
    try:
        net(*xs)
    finally:
        if was_training:
            net.train()
        for h in hooks:
            h.remove()
    if print_detail:
        print(f"FLOPs (multiply-adds): {total['n']}")
    return total["n"]
