class Model:  # fleshed out in hapi milestone
    def __init__(self, network, inputs=None, labels=None):
        self.network = network


def summary(net, input_size=None, dtypes=None):
    raise NotImplementedError
