"""hapi callbacks. ref: python/paddle/hapi/callbacks.py (Callback,
ProgBarLogger, ModelCheckpoint, LRScheduler, EarlyStopping, VisualDL,
History via the config dict)."""
from __future__ import annotations

import os
import time
from typing import List, Optional

import numpy as np

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint", "LRScheduler",
           "EarlyStopping", "VisualDL", "History", "MetricsLogger",
           "CallbackList", "config_callbacks"]


class Callback:
    """ref: callbacks.py Callback — every hook is a no-op by default."""

    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = dict(params or {})

    def on_train_begin(self, logs=None): ...
    def on_train_end(self, logs=None): ...
    def on_eval_begin(self, logs=None): ...
    def on_eval_end(self, logs=None): ...
    def on_predict_begin(self, logs=None): ...
    def on_predict_end(self, logs=None): ...
    def on_epoch_begin(self, epoch, logs=None): ...
    def on_epoch_end(self, epoch, logs=None): ...
    def on_train_batch_begin(self, step, logs=None): ...
    def on_train_batch_end(self, step, logs=None): ...
    def on_eval_batch_begin(self, step, logs=None): ...
    def on_eval_batch_end(self, step, logs=None): ...
    def on_predict_batch_begin(self, step, logs=None): ...
    def on_predict_batch_end(self, step, logs=None): ...


class CallbackList:
    def __init__(self, callbacks: Optional[List[Callback]] = None):
        self.callbacks = list(callbacks or [])

    def append(self, cb):
        self.callbacks.append(cb)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def _call(self, name, *args):
        for c in self.callbacks:
            getattr(c, name)(*args)

    def __getattr__(self, name):
        if name.startswith("on_"):
            return lambda *a: self._call(name, *a)
        raise AttributeError(name)


class History(Callback):
    """Collects per-epoch logs; installed automatically by fit
    (mirrors the reference's history bookkeeping)."""

    def on_train_begin(self, logs=None):
        self.history = {}

    def on_epoch_end(self, epoch, logs=None):
        for k, v in (logs or {}).items():
            self.history.setdefault(k, []).append(v)


class ProgBarLogger(Callback):
    """ref: callbacks.py ProgBarLogger — prints per-epoch metrics; the
    terminal progressbar degrades to line logging."""

    def __init__(self, log_freq: int = 1, verbose: int = 2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self._epoch = epoch
        self._t0 = time.time()
        if self.verbose:
            print(f"Epoch {epoch + 1}/{self.params.get('epochs', '?')}")

    def on_train_batch_end(self, step, logs=None):
        if self.verbose > 1 and step % self.log_freq == 0:
            items = " - ".join(f"{k}: {_fmt(v)}"
                               for k, v in (logs or {}).items())
            print(f"step {step}: {items}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            items = " - ".join(f"{k}: {_fmt(v)}"
                               for k, v in (logs or {}).items())
            print(f"epoch {epoch + 1} done in "
                  f"{time.time() - self._t0:.1f}s - {items}")


def _fmt(v):
    try:
        arr = np.asarray(v, dtype=np.float64)
        if arr.size == 1:
            return f"{float(arr):.4f}"
        return np.array2string(arr, precision=4)
    except (TypeError, ValueError):
        return str(v)


class ModelCheckpoint(Callback):
    """ref: callbacks.py ModelCheckpoint — saves every save_freq epochs
    and at train end."""

    def __init__(self, save_freq: int = 1, save_dir: str = "checkpoint"):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.model is not None and epoch % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.model is not None:
            self.model.save(os.path.join(self.save_dir, "final"))


class LRScheduler(Callback):
    """ref: callbacks.py LRScheduler — steps the optimizer's LRScheduler
    per epoch (or per batch when by_step)."""

    def __init__(self, by_step: bool = False, by_epoch: bool = True):
        super().__init__()
        if by_step and by_epoch:
            raise ValueError("by_step and by_epoch are mutually exclusive")
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        return lr if hasattr(lr, "step") else None

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()


class EarlyStopping(Callback):
    """ref: callbacks.py EarlyStopping — monitors an eval metric, stops
    after `patience` non-improving evals, optionally restores best
    weights."""

    def __init__(self, monitor: str = "loss", mode: str = "auto",
                 patience: int = 0, verbose: int = 1, min_delta: float = 0,
                 baseline=None, save_best_model: bool = True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        if mode not in ("auto", "min", "max"):
            mode = "auto"
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        self.stopped_epoch = 0

    def on_train_begin(self, logs=None):
        self.wait = 0
        self.best = (self.baseline if self.baseline is not None
                     else (np.inf if self.mode == "min" else -np.inf))
        self.best_weights = None
        self._epoch = 0

    def on_epoch_begin(self, epoch, logs=None):
        self._epoch = epoch

    def _improved(self, cur):
        if self.mode == "min":
            return cur < self.best - self.min_delta
        return cur > self.best + self.min_delta

    def on_eval_end(self, logs=None):
        logs = logs or {}
        if self.monitor not in logs:
            return
        cur = float(np.asarray(logs[self.monitor]).reshape(-1)[0])
        if self._improved(cur):
            self.best = cur
            self.wait = 0
            if self.save_best_model and self.model is not None:
                self.best_weights = {
                    k: np.asarray(v.numpy())
                    for k, v in self.model.network.state_dict().items()}
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True
                self.stopped_epoch = self._epoch
                if self.verbose:
                    print(f"early stopping: {self.monitor} did not "
                          f"improve past {self.best:.5f} for "
                          f"{self.patience} evals")
                if self.best_weights is not None:
                    self.model.network.set_state_dict(self.best_weights)


class MetricsLogger(Callback):
    """Telemetry bridge for ``Model.fit``: drives an
    ``observability.timeline.StepTimer`` through the batch boundaries
    (whole-step wall time lands in ``step.step_seconds`` and as
    chrome-trace counter events merged into ``export_chrome_tracing``)
    and mirrors batch/epoch logs into registry gauges
    (``train.<metric>`` / ``eval.<metric>``), so one
    ``observability.snapshot()`` after fit() carries loss curves next to
    dispatch/fusion/checkpoint counters.

    ``log_freq > 0`` additionally prints a compact one-line registry
    digest every N batches (dispatched ops, fused chains, step
    seconds) — the "what did the last N steps look like" answer without
    a trace file."""

    def __init__(self, log_freq: int = 0, timer_name: str = "hapi"):
        super().__init__()
        self.log_freq = int(log_freq)
        self.timer_name = timer_name
        self.timer = None

    def _gauges(self):
        from ..observability import metrics as om
        return om

    def on_train_begin(self, logs=None):
        from ..observability.timeline import StepTimer
        if self.timer is None:
            self.timer = StepTimer(self.timer_name)
        self._phase_cm = None

    def on_train_batch_begin(self, step, logs=None):
        if self.timer is None:
            return
        self._phase_cm = self.timer.phase("step")
        self._phase_cm.__enter__()

    def on_train_batch_end(self, step, logs=None):
        if self.timer is None:
            return
        if self._phase_cm is not None:
            self._phase_cm.__exit__(None, None, None)
            self._phase_cm = None
        phases = self.timer.step()
        om = self._gauges()
        for k, v in (logs or {}).items():
            try:
                om.gauge(f"train.{k}").set(
                    float(np.asarray(v).reshape(-1)[0]))
            except (TypeError, ValueError):
                continue
        if self.log_freq > 0 and step % self.log_freq == 0:
            snap = om.snapshot()
            disp = snap.get("dispatch", {}).get("ops_total", 0)
            chains = snap.get("fusion", {}).get("chains_flushed_total", 0)
            print(f"[metrics] step {step}: "
                  f"step_s={phases.get('step', 0.0):.4f} "
                  f"ops_dispatched={disp} fused_chains={chains}")

    def on_eval_end(self, logs=None):
        om = self._gauges()
        for k, v in (logs or {}).items():
            try:
                om.gauge(f"eval.{k}").set(
                    float(np.asarray(v).reshape(-1)[0]))
            except (TypeError, ValueError):
                continue


class VisualDL(Callback):
    """Scalar logger. The reference streams to the VisualDL service; with
    zero egress here, scalars append to a JSONL file under log_dir (same
    tag/step/value triples a VisualDL writer would record). Records
    buffer in memory and flush on epoch/eval end + train end, keeping the
    per-batch hot path free of filesystem round-trips."""

    def __init__(self, log_dir: str = "vdl_log"):
        super().__init__()
        self.log_dir = log_dir
        self._step = 0
        self._buf = []

    def _record(self, tag, value, step):
        try:
            self._buf.append({"tag": tag, "step": step,
                              "value": float(np.asarray(value)
                                             .reshape(-1)[0])})
        except (TypeError, ValueError):
            pass

    def _flush(self):
        if not self._buf:
            return
        import json
        os.makedirs(self.log_dir, exist_ok=True)
        with open(os.path.join(self.log_dir, "scalars.jsonl"), "a") as f:
            for rec in self._buf:
                f.write(json.dumps(rec) + "\n")
        self._buf.clear()

    def on_train_batch_end(self, step, logs=None):
        self._step += 1
        for k, v in (logs or {}).items():
            self._record(f"train/{k}", v, self._step)

    def on_epoch_end(self, epoch, logs=None):
        self._flush()

    def on_eval_end(self, logs=None):
        for k, v in (logs or {}).items():
            self._record(f"eval/{k}", v, self._step)
        self._flush()

    def on_train_end(self, logs=None):
        self._flush()


def config_callbacks(callbacks=None, model=None, epochs=None, steps=None,
                     verbose=2, save_freq=1, save_dir=None, metrics=None,
                     log_freq=1, mode="train"):
    """ref: callbacks.py config_callbacks — assembles the default set."""
    cbks = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks.append(ProgBarLogger(log_freq=log_freq, verbose=verbose))
    if save_dir and not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks.append(ModelCheckpoint(save_freq, save_dir))
    if not any(isinstance(c, LRScheduler) for c in cbks):
        cbks.append(LRScheduler())
    history = next((c for c in cbks if isinstance(c, History)), None)
    if history is None:
        history = History()
        cbks.append(history)
    lst = CallbackList(cbks)
    lst.set_model(model)
    lst.set_params({"epochs": epochs, "steps": steps, "verbose": verbose,
                    "metrics": metrics or []})
    return lst, history
